#!/usr/bin/env python
"""Headline benchmark: Llama-3-8B decode throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the BASELINE.md config-1 path (Llama-3-8B-Instruct chat serving)
through the real engine: continuous batching, paged KV cache, Pallas paged
decode attention, int8 weight-only quantization (a v5e chip has 16 GiB HBM;
8B bf16 is 16.06 GB, so single-chip serving is int8 — multi-chip TP shards
bf16).  Weights are random-initialised: decode throughput is independent of
weight values, and this environment has no network egress to fetch HF
checkpoints.

vs_baseline: A100-80G running vLLM serves Llama-3-8B at ~2300 tok/s decode
throughput at comparable batch (public vLLM benchmarks, bs~32); the
reference's serving plane is exactly that vLLM path (SURVEY.md §2.2), so
vs_baseline = ours / 2300.
"""

import json
import os
import subprocess
import sys
import time

A100_VLLM_LLAMA3_8B_TOKS = 2300.0  # public vLLM A100-80G decode throughput


def _device_healthy_once(timeout_s: float = 90.0) -> tuple:
    """Probe the accelerator in a subprocess: the axon TPU relay is
    single-tenant and can wedge (a hung relay blocks the first jax op
    forever, even under JAX_PLATFORMS=cpu, because plugin init touches it).
    A probe child that times out is killed without poisoning this process —
    we then run the bench in a CPU-simulator child so a line ALWAYS prints.

    Returns (healthy, backend_platform) — the platform lets the caller
    distinguish "jax works but there is no TPU here" from "TPU wedged".
    """
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "float(jnp.arange(4).sum());"
             "print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True,
        )
        if p.returncode != 0:
            return False, ""
        out = (p.stdout or b"").decode("utf-8", "replace").strip()
        return True, out.splitlines()[-1] if out else ""
    except subprocess.TimeoutExpired:
        return False, ""


def _tpu_plausible() -> bool:
    """Any evidence a TPU could exist on this host?  Device nodes, the
    usual TPU env vars, or an axon relay config.  When none are present a
    failed probe means 'CPU-only host', not 'wedged relay' — retrying for
    the full budget just burns the bench window (BENCH_r05 tail)."""
    import glob

    if glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"):
        return True
    return any(
        os.environ.get(v)
        for v in ("TPU_NAME", "TPU_WORKER_ID", "TPU_SKIP_MDS_QUERY",
                  "HELIX_AXON_RELAY", "AXON_RELAY_ADDR")
    )


def _device_healthy() -> bool:
    """Retry the probe over a window: the relay wedges and *recovers* (its
    grant timeout is minutes), so one 90 s attempt undersells a chip that
    would be reachable two minutes later.  Bounded by HELIX_BENCH_PROBE_S
    (default 15 min) so the driver still always gets its JSON line.

    CPU-only escape hatches (no retry loop): an explicit
    ``JAX_PLATFORMS=cpu`` skips probing entirely, and a host with no TPU
    evidence gives up after ONE failed probe instead of burning the full
    budget retrying a chip that was never there."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        print(
            "[bench] JAX_PLATFORMS=cpu set: skipping device probe, "
            "running the CPU smoke path", file=sys.stderr,
        )
        return False
    try:
        budget_s = float(os.environ.get("HELIX_BENCH_PROBE_S", "900"))
    except ValueError:
        budget_s = 900.0
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        attempt += 1
        healthy, platform = _device_healthy_once()
        if healthy:
            if platform not in ("tpu", "axon"):
                # jax initialised fine but only found CPU devices: a
                # CPU-only host, fully healthy — no point retrying for a
                # TPU that does not exist.  Return False so the smoke
                # runs in the clean CPU CHILD (the in-process path
                # enables the persistent XLA compile cache, whose
                # XLA:CPU AOT deserialization segfaults in this build)
                print(
                    f"[bench] device probe found backend "
                    f"{platform or 'cpu'!r}: CPU-only host, running the "
                    "CPU smoke path", file=sys.stderr,
                )
                return False
            return True
        if not _tpu_plausible():
            print(
                "[bench] device probe failed and no TPU evidence on this "
                "host (no /dev/accel*, no TPU env): skipping straight to "
                "the CPU smoke path after one probe", file=sys.stderr,
            )
            return False
        remaining = deadline - time.monotonic()
        print(
            f"[bench] device probe attempt {attempt} failed; "
            f"{remaining:.0f}s of probe budget left", file=sys.stderr,
        )
        if remaining <= 0:
            return False
        time.sleep(min(60.0, max(0.0, remaining)))


def main():
    if os.environ.get("HELIX_BENCH_CHILD") != "1" and not _device_healthy():
        # accelerator unreachable: emit an honest degraded-mode line from a
        # clean CPU child (axon plugin stripped so it cannot hang)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["HELIX_BENCH_CHILD"] = "1"
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        # the smoke suite has grown a block per PR (tiering, migration,
        # disagg, multihost, canary, long-context...) — an hour bounds
        # the whole ladder with headroom while still failing a hang
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=3600,
        )
        out = (p.stdout or "").strip().splitlines()
        if out:
            print(out[-1])
        else:
            print(json.dumps({
                "metric": "bench_unavailable",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
            }))
        return

    import jax
    import jax.numpy as jnp

    # Persistent compile cache ONLY on the accelerator path: repeat bench
    # runs skip XLA compilation.  The CPU fallback must not use it —
    # XLA:CPU AOT cache deserialization segfaults in this jax build
    # (see tests/conftest.py), and a dead bench emits no JSON line.
    if os.environ.get("HELIX_BENCH_CHILD") != "1":
        jax.config.update(
            "jax_compilation_cache_dir", "/root/.jax_bench_cache"
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )

    from helix_tpu.engine.engine import Engine, EngineConfig
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import LLAMA3_8B
    from helix_tpu.models.llama import init_params

    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")

    if on_tpu:
        cfg = LLAMA3_8B
        batch = int(os.environ.get("HELIX_BENCH_BATCH", "32"))
        prompt_len = 128
        gen_len = 128
        num_pages = 2048          # 16 tokens/page -> 32k cached tokens
        # (int8 8B weights ~8.1G + 2x2.15G KV pools leaves ~3G HBM
        #  headroom on a 16G v5e chip; the bs=32 x 256-token workload
        #  peaks at 512 pages, so 2048 is still 4x over-provisioned)
    else:  # CPU smoke fallback so the script always emits a line
        import dataclasses

        from helix_tpu.models.common import ModelConfig

        cfg = ModelConfig.tiny(dtype="float32")
        batch, prompt_len, gen_len, num_pages = 2, 8, 8, 64

    if on_tpu:
        # Build int8 weights directly on device (bf16 8B would not fit HBM
        # even transiently). Values are irrelevant to throughput; scales of
        # 0.01 keep activations in a sane range.
        L, E, H, KVH, D, F, V = (
            cfg.num_layers, cfg.hidden_size, cfg.num_heads,
            cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size,
            cfg.vocab_size,
        )

        def qw(shape):
            n = shape[-1]
            w = (
                jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1) % 13
                - 6
            ).astype(jnp.int8)
            scale_shape = (shape[0], 1, n) if len(shape) == 3 else (1, n)
            return {
                "weight": w,
                "scale": jnp.full(scale_shape, 0.01, jnp.float32),
            }

        @jax.jit
        def build():
            return {
                "embed": {
                    "weight": (
                        jax.lax.broadcasted_iota(jnp.int32, (V, E), 1) % 13 - 6
                    ).astype(jnp.int8),
                    "embed_scale": jnp.full((V, 1), 0.01, jnp.float32),
                },
                "layers": {
                    "attn_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                    "mlp_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                    "wq": qw((L, E, H * D)),
                    "wk": qw((L, E, KVH * D)),
                    "wv": qw((L, E, KVH * D)),
                    "wo": qw((L, H * D, E)),
                    "w_gate": qw((L, E, F)),
                    "w_up": qw((L, E, F)),
                    "w_down": qw((L, F, E)),
                },
                "final_norm": {"weight": jnp.ones((E,), jnp.bfloat16)},
                "lm_head": qw((E, V)),
            }

        params = build()
        jax.block_until_ready(params)
    else:
        params = init_params(cfg, jax.random.PRNGKey(0))

    # KV-cache storage dtype under test: int8 halves page bytes (scale
    # pools included) so fit_hbm admits ~1.94x the pages — the decode
    # batch-capacity lever.  HELIX_BENCH_KV picks the primary config;
    # HELIX_BENCH_KV_COMPARE=0 skips the secondary comparison pass.
    kv_dtype = os.environ.get("HELIX_BENCH_KV", "int8")
    compare = os.environ.get("HELIX_BENCH_KV_COMPARE", "1") == "1"

    def make_engine(kv, **extra):
        return Engine(
            cfg,
            params,
            EngineConfig(
                max_decode_batch=batch,
                page_size=16,
                num_pages=num_pages,
                max_pages_per_seq=64,
                max_prefill_len=512 if on_tpu else 32,
                # one host fetch per 16 decode steps: the axon relay costs
                # ~28 ms per device_get, which at 1 step/fetch caps the chip
                # at ~35 steps/s no matter how fast the model runs
                decode_steps_per_sync=16 if on_tpu else 1,
                # keep the headline number comparable across rounds and to
                # the A100 baseline: the warmup pass uses the SAME prompts
                # as the timed pass, so automatic prefix caching would
                # serve the timed prefills from cache and flatter the
                # result
                enable_prefix_cache=False,
                kv_cache_dtype=kv,
                **extra,
            ),
        )

    prompts = [
        [(7 * i + j) % (cfg.vocab_size - 2) + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]
    sampling = SamplingParams(temperature=0.0, max_tokens=gen_len)

    from helix_tpu.engine.engine import Request

    def run_workload(eng, tag: str):
        """Admit the full batch at once and drain it — the measured
        pattern. Called twice per engine: the first pass IS the warmup,
        so every shape the timed pass hits (each packed-prefill bucket
        the admission loop packs this batch into + the fused decode step)
        is compiled before the clock starts. Timing the warm pass is what
        round-2's harness got wrong: it warmed one request, then timed
        two, and the second packed bucket compiled inside the window."""
        reqs = [
            Request(
                id=f"{tag}-{i}", prompt_tokens=list(p), sampling=sampling
            )
            for i, p in enumerate(prompts)
        ]
        d0 = eng.num_decode_tokens
        s0 = eng.num_decode_device_steps
        t0 = time.perf_counter()
        for r in reqs:
            eng.add_request(r)
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        return (
            reqs, dt,
            eng.num_decode_device_steps - s0,
            eng.num_decode_tokens - d0,
        )

    def measure(kv):
        eng = make_engine(kv)
        run_workload(eng, f"warmup-{kv}")   # compiles every measured shape
        reqs, dt, steps, decode_toks = run_workload(eng, f"bench-{kv}")
        return eng, reqs, dt, steps, decode_toks

    other_toks_per_s = None
    if compare:
        # secondary config first (engine freed before the primary runs so
        # two page pools never coexist in HBM)
        other_kv = "auto" if kv_dtype == "int8" else "int8"
        o_eng, o_reqs, o_dt, _, _ = measure(other_kv)
        other_toks_per_s = (
            sum(len(r.output_tokens) for r in o_reqs) / o_dt
        )
        del o_eng, o_reqs

    eng, reqs, dt, bench_steps, bench_decode_toks = measure(kv_dtype)

    # single-session TTFT (north star line 2: "p50 TTFT, single-session
    # chat") — measured separately from burst admission: one request on an
    # idle engine, prefill + first token, repeated for a median
    single_ttfts = []
    for k in range(5):
        r1 = Request(
            id=f"ttft-{k}",
            prompt_tokens=list(prompts[0]),
            sampling=SamplingParams(temperature=0.0, max_tokens=2),
        )
        t0 = time.perf_counter()
        eng.add_request(r1)
        while eng.has_work() and r1.first_token_time is None:
            eng.step()
        single_ttfts.append(
            (r1.first_token_time - r1.submit_time) * 1000.0
            if r1.first_token_time is not None
            else (time.perf_counter() - t0) * 1000.0
        )
        while eng.has_work():
            eng.step()
    single_ttfts.sort()
    p50_single_ttft = single_ttfts[len(single_ttfts) // 2]
    outs = [r.output_tokens for r in reqs]
    total_new = sum(len(o) for o in outs)
    toks_per_s = total_new / dt

    # p50 time-to-first-token across the batch (BASELINE.md north star:
    # "p50 TTFT, single-session chat")
    ttfts = sorted(
        (r.first_token_time - r.submit_time) * 1000.0
        for r in reqs
        if r.first_token_time is not None
    )
    p50_ttft_ms = ttfts[len(ttfts) // 2] if ttfts else 0.0

    result = {
        "metric": "llama3_8b_decode_tokens_per_sec_per_chip"
        if on_tpu
        else "tiny_decode_tokens_per_sec_cpu_smoke",
        "value": round(toks_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(toks_per_s / A100_VLLM_LLAMA3_8B_TOKS, 4)
        if on_tpu
        else 0.0,
        "p50_ttft_ms": round(p50_ttft_ms, 1),
        "p50_single_ttft_ms": round(p50_single_ttft, 1),
        "batch": batch,
        "prompt_len": prompt_len,
        "gen_len": gen_len,
        "kv_cache_dtype": eng.cache_cfg.dtype,
    }
    # saturation snapshot (ISSUE 4): BENCH_r* tracks efficiency, not just
    # raw tokens/s — peak KV occupancy, decode-slot utilization across the
    # timed pass, prefix hit rate (0 here: APC is off for comparability),
    # padding waste and goodput
    kv_cap = getattr(eng, "kv_pages_capacity", max(1, num_pages - 1))
    pc_hits = eng.prefix_cache.hits if eng.prefix_cache else 0
    pc_misses = eng.prefix_cache.misses if eng.prefix_cache else 0
    result["saturation"] = {
        "peak_kv_pages_used": eng.allocator.peak_used,
        "kv_pages_capacity": kv_cap,
        "peak_kv_occupancy": round(eng.allocator.peak_used / kv_cap, 4),
        "decode_slot_utilization": round(
            bench_decode_toks / max(1, bench_steps * batch), 4
        ),
        "prefix_hit_rate": round(
            pc_hits / (pc_hits + pc_misses), 4
        ) if pc_hits + pc_misses else 0.0,
        "prefill_padding_tokens": eng.num_prefill_padding_tokens,
        "goodput_tokens_per_sec": round(toks_per_s, 2),
    }
    if other_toks_per_s is not None:
        # same batch, same prompts, other KV storage dtype — the
        # apples-to-apples decode-throughput comparison
        result["other_kv_dtype_tokens_per_sec"] = round(
            other_toks_per_s, 2
        )
        result["kv_speedup_vs_other"] = round(
            toks_per_s / max(other_toks_per_s, 1e-9), 4
        )
    # page capacity under the same HBM budget: the int8 admission win.
    # Always accounted against the HEADLINE serving geometry (Llama-3-8B,
    # head_dim 128) — it is a static byte calculation, and the CPU smoke's
    # tiny head_dim would misstate the ratio the real config gets.
    from helix_tpu.engine.kv_cache import CacheConfig
    from helix_tpu.models.common import LLAMA3_8B

    kv_budget = CacheConfig(
        num_pages=2048, page_size=16, dtype="bfloat16"
    ).total_bytes(LLAMA3_8B)
    bf16_pages = CacheConfig.fit_hbm(LLAMA3_8B, kv_budget).num_pages
    int8_pages = CacheConfig.fit_hbm(
        LLAMA3_8B, kv_budget, dtype="int8"
    ).num_pages
    result["pages_per_hbm_budget"] = {
        "bfloat16": bf16_pages,
        "int8": int8_pages,
        "ratio": round(int8_pages / bf16_pages, 4),
    }
    # speculative decoding (ISSUE 5): spec on vs off over a repetitive-
    # suffix prompt set (unique head so prefills differ, repeated tail so
    # prompt-lookup drafting has n-grams to hit — the code/RAG/extraction
    # shape).  decode_tokens / device_steps is the headline: every point
    # above 1.0 per slot is a forward pass the accepted drafts saved.
    # The primary engine is freed first so two page pools never coexist
    # in HBM.
    del eng, reqs, outs
    rep_unit = [3, 1, 4, 1, 5, 9, 2, 6]
    head_len = max(prompt_len // 2, len(rep_unit))
    spec_prompts = [
        [(11 * i + j) % (cfg.vocab_size - 2) + 1 for j in range(head_len)]
        + rep_unit * max(head_len // len(rep_unit), 2)
        for i in range(batch)
    ]

    # drafting feeds on the sequence's OWN repetition (prompt tail +
    # whatever loops the model's output falls into), so the spec passes
    # need enough generation length for acceptance to show — the tiny
    # CPU smoke's 8 tokens are not it
    spec_sampling = SamplingParams(
        temperature=0.0, max_tokens=max(gen_len, 32)
    )

    def spec_pass(enable: bool):
        eng2 = make_engine(
            kv_dtype, enable_spec_decode=enable, spec_tokens=4
        )

        def drive(tag: str):
            rr = [
                Request(
                    id=f"{tag}-{i}", prompt_tokens=list(p),
                    sampling=spec_sampling,
                )
                for i, p in enumerate(spec_prompts)
            ]
            d0 = eng2.num_decode_tokens
            s0 = eng2.num_decode_device_steps
            t0 = time.perf_counter()
            for r in rr:
                eng2.add_request(r)
            while eng2.has_work():
                eng2.step()
            dt = time.perf_counter() - t0
            return (
                rr, dt,
                eng2.num_decode_device_steps - s0,
                eng2.num_decode_tokens - d0,
            )

        drive(f"spec-warm-{enable}")   # compiles verify + decode shapes
        rr, dt, steps, dtoks = drive(f"spec-bench-{enable}")
        toks = sum(len(r.output_tokens) for r in rr)
        return eng2, toks / dt, steps, dtoks

    off_eng, off_tps, off_steps, off_toks = spec_pass(False)
    del off_eng
    on_eng, on_tps, on_steps, on_toks = spec_pass(True)
    drafted = on_eng.num_spec_drafted_tokens
    accepted = on_eng.num_spec_accepted_tokens
    result["speculation"] = {
        "spec_tokens": 4,
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "acceptance_ratio": (
            round(accepted / drafted, 4) if drafted else 0.0
        ),
        "decode_tokens_per_device_step": round(
            on_toks / max(1, on_steps), 4
        ),
        "baseline_tokens_per_device_step": round(
            off_toks / max(1, off_steps), 4
        ),
        # >1.0 = the speculation win in forwards saved per slot (the
        # plain engine's ceiling is exactly 1.0 at full utilization)
        "tokens_per_device_step_per_slot": round(
            on_toks / max(1, on_steps * batch), 4
        ),
        "tokens_per_sec_spec_on": round(on_tps, 2),
        "tokens_per_sec_spec_off": round(off_tps, 2),
        "speedup": round(on_tps / max(off_tps, 1e-9), 4),
    }
    del on_eng

    # KV tiering (ISSUE 6): a system-prompt-heavy workload against a
    # device pool too small to keep every prefix resident.  Two fleets'
    # system prompts alternate, so the device prefix cache thrashes:
    # WITHOUT the host tier every eviction is a re-prefill (request hit
    # rate collapses); WITH it the evicted pages spill to host RAM and
    # restore on the next shared-prefix arrival.  CPU-smoke comparable
    # like the speculation block — the hit-rate delta and pages
    # restored are hardware-independent; restore latency is indicative
    # only off-TPU.
    from helix_tpu.engine.residency import host_tier_pages

    ps_t = 16
    sys_prompts = [
        [(13 * s + j) % (cfg.vocab_size - 2) + 1 for j in range(6 * ps_t)]
        for s in range(2)
    ]   # two 6-page system prefixes: the 12-page pool holds only ONE
    # fleet's prefix at a time, so alternating traffic thrashes it
    tier_sampling = SamplingParams(temperature=0.0, max_tokens=8)

    def tiering_pass(host_bytes: int):
        eng3 = Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=2, page_size=ps_t, num_pages=13,
                max_pages_per_seq=8,
                max_prefill_len=512 if on_tpu else 64,
                enable_prefix_cache=True,
                kv_cache_dtype=kv_dtype,
                host_pool_bytes=host_bytes,
            ),
        )

        def drive(tag, n):
            for i in range(n):
                req = Request(
                    id=f"{tag}-{i}",
                    prompt_tokens=sys_prompts[i % 2]
                    + [(31 * i + j) % 200 + 1 for j in range(17)],
                    sampling=tier_sampling,
                )
                eng3.add_request(req)
                while eng3.has_work():
                    eng3.step()

        drive("tier-warm", 2)   # compiles packed + chunk-hit shapes
        h0, m0 = eng3.prefix_cache_hits, eng3.prefix_cache_misses
        drive("tier-bench", 12)
        hits = eng3.prefix_cache_hits - h0
        misses = eng3.prefix_cache_misses - m0
        return eng3, hits / max(1, hits + misses)

    off3, tier_off_rate = tiering_pass(0)
    del off3
    on3, tier_on_rate = tiering_pass(64 << 20)
    # snapshot the prefix-restore numbers BEFORE the preempt exercise —
    # its resume also restores pages and banks restore_seconds, which
    # would skew the per-page figure
    restored = on3.host_pool.restored_pages
    tier_restore_s = on3.restore_seconds
    # preempt/resume round trip on the same engine: park a running
    # decoder to host and swap it back (the graceful-degradation rung)
    pr = Request(
        id="tier-preempt", prompt_tokens=sys_prompts[0][: 2 * ps_t],
        sampling=SamplingParams(temperature=0.0, max_tokens=48),
    )
    eng3 = on3
    eng3.add_request(pr)
    while len(pr.output_tokens) < 4:
        eng3.step()
    t_pre = time.perf_counter()
    preempt_ok = eng3.preempt(pr.id)
    preempt_ms = (time.perf_counter() - t_pre) * 1000.0
    t_res = time.perf_counter()
    while eng3.preempted:
        eng3.step()   # resumes immediately: pages are free
    resume_ms = (time.perf_counter() - t_res) * 1000.0
    while eng3.has_work():
        eng3.step()
    result["kv_tiering"] = {
        "host_pool_bytes": 64 << 20,
        "prefix_request_hit_rate_host_on": round(tier_on_rate, 4),
        "prefix_request_hit_rate_host_off": round(tier_off_rate, 4),
        "spilled_pages": eng3.host_pool.spilled_pages,
        "restored_pages": restored,
        "host_tier_pages": host_tier_pages(
            cfg, eng3.cache_cfg, 64 << 20
        ),
        "restore_ms_per_page": round(
            tier_restore_s * 1000.0 / max(1, restored), 3
        ),
        "preemptions": eng3.num_preemptions,
        "preempt_ok": bool(preempt_ok),
        "preempt_ms": round(preempt_ms, 3),
        "resume_ms": round(resume_ms, 3),
    }
    del eng3, on3

    # cross-runner migration (ISSUE 11): export a mid-generation
    # request as a portable snapshot, ship it through the wire format,
    # import into a second engine and finish there.  The continuation
    # must be bit-identical to an uninterrupted run (tokens_lost == 0
    # is asserted, not just reported); snapshot bytes/request and the
    # export+import round-trip cost are the capacity-planning numbers a
    # rolling restart pays per in-flight request.
    from helix_tpu.serving import migration as _migration

    mig_a = make_engine(kv_dtype)
    mig_b = make_engine(kv_dtype)
    mig_ref = make_engine(kv_dtype)
    mig_prompt = [(17 * j) % (cfg.vocab_size - 2) + 1 for j in range(48)]
    mig_sampling = SamplingParams(temperature=0.0, max_tokens=32)
    ref_req = Request(
        id="mig-ref", prompt_tokens=list(mig_prompt),
        sampling=mig_sampling,
    )
    mig_ref.add_request(ref_req)
    while not ref_req.finished:
        mig_ref.step()
    mig_req = Request(
        id="mig-bench", prompt_tokens=list(mig_prompt),
        sampling=mig_sampling,
    )
    mig_a.add_request(mig_req)
    while len(mig_req.output_tokens) < 12 and mig_a.has_work():
        mig_a.step()
    cut = len(mig_req.output_tokens)
    t_exp = time.perf_counter()
    mig_snap = mig_a.export_request("mig-bench")
    mig_wire = _migration.snapshot_to_wire(mig_snap)
    export_ms = (time.perf_counter() - t_exp) * 1000.0
    wire_bytes = len(json.dumps(mig_wire).encode())
    t_imp = time.perf_counter()
    mig_cont = mig_b.import_request(
        _migration.wire_to_snapshot(mig_wire)
    )
    while not mig_cont.finished:
        mig_b.step()
    import_ms = (time.perf_counter() - t_imp) * 1000.0
    combined = mig_req.output_tokens[:cut] + mig_cont.output_tokens[cut:]
    tokens_lost = len(ref_req.output_tokens) - len(combined)
    assert combined == ref_req.output_tokens, (
        "migrated continuation diverged from the uninterrupted run"
    )
    result["migration"] = {
        "snapshot_pages": len(mig_snap.pages),
        "snapshot_kv_bytes": mig_snap.kv_bytes(),
        "snapshot_wire_bytes": wire_bytes,
        "export_ms": round(export_ms, 3),
        "import_and_finish_ms": round(import_ms, 3),
        "tokens_before_migration": cut,
        "tokens_after_migration": len(mig_cont.output_tokens) - cut,
        # asserted zero above — recorded so regressions are visible in
        # the JSON even when assertions are stripped
        "tokens_lost": tokens_lost,
        "bit_identical": combined == ref_req.output_tokens,
    }
    del mig_a, mig_b, mig_ref

    # disaggregated prefill/decode (ISSUE 14): does splitting the pools
    # protect decode TTFT from a concurrent long prefill?  Two passes
    # over the same workload — a long chunked prompt + a burst of short
    # decode requests: (a) COLOCATED, everything on one mixed engine
    # loop; (b) SPLIT, the long prompt lands on a prefill-pool loop,
    # exports at prefill completion and ships (in-process, through the
    # real wire format + checksum-validated import) to the decode-pool
    # loop that serves the shorts.  Recorded: short-request TTFT p95
    # both ways, transfer ms/page, and the filestore tier's
    # warm-restart hit (a fresh engine serving a cached prefix without
    # recomputing it).
    import tempfile as _tempfile
    import threading as _threading2

    from helix_tpu.serving.engine_loop import EngineLoop as _Loop
    from helix_tpu.serving import migration as _mig2

    short_sampling = SamplingParams(temperature=0.0, max_tokens=6)
    long_sampling = SamplingParams(temperature=0.0, max_tokens=4)
    long_len = 4096 if on_tpu else 480   # >> max_prefill_len: chunks
    long_prompt = [
        (11 * j) % (cfg.vocab_size - 2) + 1 for j in range(long_len)
    ]
    short_prompts = [
        [(7 * j + i) % (cfg.vocab_size - 2) + 1
         for j in range(prompt_len)]
        for i in range(6)
    ]

    def ttft_probe(loop_short, submit_long, tag):
        """Submit the long prefill, then the short burst; return the
        shorts' TTFTs (seconds)."""
        submit_long()
        waits = []
        for i, p in enumerate(short_prompts):
            ev = _threading2.Event()
            first: dict = {}
            t0 = time.perf_counter()

            def cb(e, _ev=ev, _f=first, _t0=t0):
                if "t" not in _f and e.token_id >= 0:
                    _f["t"] = time.perf_counter() - _t0
                if e.finished:
                    _ev.set()

            loop_short.submit(
                Request(
                    id=f"{tag}-short-{i}", prompt_tokens=list(p),
                    sampling=short_sampling,
                ),
                cb,
            )
            waits.append((ev, first))
        out = []
        for ev, first in waits:
            ev.wait(timeout=300)
            out.append(first.get("t", float("inf")))
        return out

    def p95(xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

    def submit_long_to(loop, tag, cb=None):
        ev = _threading2.Event()

        def done(e, _ev=ev):
            if cb is not None:
                cb(e)
            if e.finished:
                _ev.set()

        loop.submit(
            Request(
                id=f"{tag}-long", prompt_tokens=list(long_prompt),
                sampling=long_sampling,
            ),
            done,
        )
        return ev

    # -- colocated baseline (warm pass first: compiles stay out) ----------
    colo_loop = _Loop(make_engine(kv_dtype), name="bench-disagg-colo")
    colo_loop.start()
    submit_long_to(colo_loop, "warm").wait(timeout=600)
    ttft_probe(colo_loop, lambda: None, "warm")
    long_done = [None]
    colo_ttfts = ttft_probe(
        colo_loop,
        lambda: long_done.__setitem__(
            0, submit_long_to(colo_loop, "colo")
        ),
        "colo",
    )
    if long_done[0] is not None:
        long_done[0].wait(timeout=600)
    colo_loop.stop(join=True)

    # -- split pools: prefill loop hands off to the decode loop -----------
    pre_loop = _Loop(make_engine(kv_dtype), name="bench-disagg-pre")
    dec_loop = _Loop(make_engine(kv_dtype), name="bench-disagg-dec")
    pre_loop.start()
    dec_loop.start()
    xfer_ms = [0.0]
    xfer_pages = [0]
    handoff_ok = [False]
    long_finished = _threading2.Event()

    def on_remote_event(e):
        if e.finished:
            long_finished.set()

    def on_local_long_event(e):
        # a failed/skipped handoff finishes the long request HERE —
        # without this the 600 s wait below would stall on a fault
        # (handoff_ok stays False, which already marks the split
        # comparison invalid).  On a CONFIRMED handoff the local abort
        # also finishes the request, but handoff_ok is set before the
        # abort fires, so the remote side owns the event then.
        if e.finished and not handoff_ok[0]:
            long_finished.set()

    def on_export(kind, wire):
        # runs on the prefill loop's engine thread — fine for a bench
        if kind != "snapshot":
            return
        t0 = time.perf_counter()
        snap2 = _mig2.wire_to_snapshot(wire)
        res: list = []
        dec_loop.submit_import(
            snap2, on_remote_event,
            on_result=lambda e, c: res.append(e),
        )
        deadline = time.monotonic() + 60.0
        while not res and time.monotonic() < deadline:
            time.sleep(0.002)
        if res and res[0] is None:
            xfer_ms[0] = (time.perf_counter() - t0) * 1000.0
            xfer_pages[0] = len(wire.get("pages") or [])
            handoff_ok[0] = True
            pre_loop.abort(f"split-long")

    def submit_split_long():
        pre_loop.stage_disagg_export("split-long", on_export)
        pre_loop.submit(
            Request(
                id="split-long", prompt_tokens=list(long_prompt),
                sampling=long_sampling,
            ),
            on_local_long_event,
        )

    split_ttfts = ttft_probe(dec_loop, submit_split_long, "split")
    long_finished.wait(timeout=600)
    pre_loop.stop(join=True)
    dec_loop.stop(join=True)

    # -- filestore warm restart (cross-process prompt caching) ------------
    from helix_tpu.serving.kv_filestore import filestore_for_engine

    fs_dir = _tempfile.mkdtemp(prefix="helix-bench-kvfs-")
    fs_prompt = [
        (13 * j) % (cfg.vocab_size - 2) + 1 for j in range(52)
    ]
    fs_sampling = SamplingParams(temperature=0.0, max_tokens=8)

    def fs_run(tag):
        # prefix cache ON here (the tier feeds it), own engine per run
        e = Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=batch, page_size=16,
                num_pages=num_pages, max_pages_per_seq=64,
                max_prefill_len=512 if on_tpu else 32,
                decode_steps_per_sync=16 if on_tpu else 1,
                kv_cache_dtype=kv_dtype,
            ),
        )
        e.kv_filestore = filestore_for_engine(fs_dir, cfg, e.cache_cfg)
        r = Request(
            id=f"fs-{tag}", prompt_tokens=list(fs_prompt),
            sampling=fs_sampling,
        )
        e.add_request(r)
        while not r.finished:
            e.step()
        e.kv_filestore.flush()   # async write-through: land the blobs
        return e, r

    cold_e, cold_r = fs_run("cold")
    warm_e, warm_r = fs_run("warm")
    assert warm_r.output_tokens == cold_r.output_tokens, (
        "filestore-warm restart diverged from the cold run"
    )
    result["disagg"] = {
        "colo_short_ttft_p95_ms": round(p95(colo_ttfts) * 1000.0, 3),
        "split_short_ttft_p95_ms": round(p95(split_ttfts) * 1000.0, 3),
        # the acceptance read: pools split must not be worse than the
        # colocated mixed engine for decode TTFT under a long prefill
        "split_no_worse": p95(split_ttfts) <= p95(colo_ttfts) * 1.25,
        "handoff_ok": bool(handoff_ok[0]),
        "transfer_ms_per_page": round(
            xfer_ms[0] / max(1, xfer_pages[0]), 3
        ),
        "transfer_pages": xfer_pages[0],
        "filestore": {
            "cold_stores": cold_e.kv_filestore.stores,
            "warm_hit_pages": warm_e.kv_filestore.hits,
            "warm_cached_tokens": warm_r.cached_tokens,
            "warm_restored_pages": warm_e.filestore_restored_pages,
            "hit_rate": round(
                warm_e.kv_filestore.hits
                / max(
                    1,
                    warm_e.kv_filestore.hits
                    + warm_e.kv_filestore.misses,
                ),
                4,
            ),
            "bit_identical": warm_r.output_tokens == cold_r.output_tokens,
        },
    }
    del colo_loop, pre_loop, dec_loop, cold_e, warm_e

    # per-tenant SLO baseline (ISSUE 7): a two-tenant mixed load through
    # the real EngineLoop (the layer that owns TTFT/queue-wait
    # accounting), so the item-5 scheduler PR has a recorded
    # latency/goodput split to beat.  Tenants alternate request-for-
    # request on one engine — the "fair" baseline a fairness scheduler
    # must not regress.
    import threading as _threading

    from helix_tpu.obs.slo import SLOObserver
    from helix_tpu.serving.engine_loop import EngineLoop

    slo_eng = make_engine(kv_dtype)
    slo_loop = EngineLoop(slo_eng, name="bench-slo").start()
    slo_sampling = SamplingParams(
        temperature=0.0, max_tokens=min(gen_len, 16)
    )

    def slo_pass(tag: str):
        done = []
        for i in range(2 * batch):
            ev = _threading.Event()
            done.append(ev)

            def cb(e, _ev=ev):
                if e.finished:
                    _ev.set()

            slo_loop.submit(
                Request(
                    id=f"{tag}-{i}",
                    prompt_tokens=list(prompts[i % batch]),
                    sampling=slo_sampling,
                    tenant="tenant-a" if i % 2 == 0 else "tenant-b",
                ),
                cb,
            )
        for ev in done:
            ev.wait(timeout=300)

    slo_pass("slo-warm")   # compile wave stays out of the baseline
    slo_loop.slo = SLOObserver(top_k=4)
    slo_pass("slo-bench")
    result["slo"] = slo_loop.slo.summary()
    slo_loop.stop(join=True)
    del slo_loop, slo_eng

    # FIFO vs WFQ fairness (ISSUE 9): a flooding batch tenant vs an
    # interactive tenant over the PR 7 two-tenant baseline.  The claim
    # under test: with the WFQ scheduler the interactive tenant's TTFT
    # p95 stays within ~2x of its uncontended value while the FIFO
    # baseline (interactive queued behind the whole flood) blows past
    # it, total goodput stays within ~10% of FIFO (ordering changes,
    # work doesn't), and greedy outputs are bit-identical to the
    # unscheduled engine for every completed request.
    fair_slots = 2   # few slots so the flood actually queues
    fair_kw = dict(
        max_decode_batch=fair_slots, page_size=16, num_pages=num_pages,
        max_pages_per_seq=64, max_prefill_len=512 if on_tpu else 32,
        enable_prefix_cache=False, kv_cache_dtype=kv_dtype,
    )
    flood_n, chat_n = 6 * fair_slots, 4
    fair_sampling = SamplingParams(
        temperature=0.0, max_tokens=min(gen_len, 8)
    )

    def fair_prompts(tag, n, seed):
        return {
            f"{tag}-{i}": [
                (seed * 131 + 17 * i + j) % (cfg.vocab_size - 2) + 1
                for j in range(prompt_len)
            ]
            for i in range(n)
        }

    bulk_prompts = fair_prompts("bulk", flood_n, 3)
    chat_prompts = fair_prompts("chat", chat_n, 11)

    def fair_req(rid, prompt, tenant="", klass=""):
        return Request(
            id=rid, prompt_tokens=list(prompt), sampling=fair_sampling,
            tenant=tenant or "bulk", sched_class=klass,
        )

    # unscheduled reference: the same requests stepped straight through
    # a bare engine — the scheduler may only change ORDER, not tokens
    ref_eng = Engine(cfg, params, EngineConfig(**fair_kw))
    ref_reqs = [
        fair_req(rid, p)
        for rid, p in {**bulk_prompts, **chat_prompts}.items()
    ]
    for r in ref_reqs:
        ref_eng.add_request(r)
    while ref_eng.has_work():
        ref_eng.step()
    ref_out = {r.id: list(r.output_tokens) for r in ref_reqs}
    del ref_eng

    def fair_pass(policy: str, contended: bool):
        eng_f = Engine(cfg, params, EngineConfig(**fair_kw))
        loop_f = EngineLoop(
            eng_f, name=f"bench-fair-{policy}",
            sched_config={"sched": {"policy": policy}},
        ).start()

        def drive(reqs):
            done = []
            for r in reqs:
                ev = _threading.Event()
                done.append(ev)

                def cb(e, _ev=ev):
                    if e.finished:
                        _ev.set()

                loop_f.submit(r, cb)
            for ev in done:
                ev.wait(timeout=600)

        # warm pass: every compiled shape lands before the clock starts
        drive([
            fair_req(f"warm-{i}", bulk_prompts[f"bulk-{i}"])
            for i in range(fair_slots)
        ])
        loop_f.slo = SLOObserver(top_k=4)
        reqs = []
        if contended:
            reqs += [
                fair_req(rid, p, tenant="bulk", klass="batch")
                for rid, p in bulk_prompts.items()
            ]
        reqs += [
            fair_req(rid, p, tenant="chat", klass="interactive")
            for rid, p in chat_prompts.items()
        ]
        t0 = time.perf_counter()
        drive(reqs)
        elapsed = time.perf_counter() - t0
        summary = loop_f.slo.summary()
        outputs = {r.id: list(r.output_tokens) for r in reqs}
        loop_f.stop(join=True)
        del loop_f, eng_f
        toks = sum(len(v) for v in outputs.values())
        return {
            "interactive_ttft_p95_seconds": summary["tenants"]
            .get("chat", {})
            .get("ttft_p95_seconds", 0.0),
            "goodput_tokens_per_second": round(
                toks / max(elapsed, 1e-9), 2
            ),
            "tenant_generated_tokens": {
                t: d["generated_tokens"]
                for t, d in summary["tenants"].items()
            },
        }, outputs

    uncontended, _ = fair_pass("fifo", contended=False)
    fifo, fifo_out = fair_pass("fifo", contended=True)
    wfq, wfq_out = fair_pass("wfq", contended=True)
    base_ttft = max(
        uncontended["interactive_ttft_p95_seconds"], 1e-9
    )
    result["fairness"] = {
        "flood_requests": flood_n,
        "interactive_requests": chat_n,
        "decode_slots": fair_slots,
        "uncontended_interactive_ttft_p95_seconds": uncontended[
            "interactive_ttft_p95_seconds"
        ],
        "fifo": fifo,
        "wfq": wfq,
        "wfq_ttft_vs_uncontended": round(
            wfq["interactive_ttft_p95_seconds"] / base_ttft, 2
        ),
        "fifo_ttft_vs_uncontended": round(
            fifo["interactive_ttft_p95_seconds"] / base_ttft, 2
        ),
        "goodput_ratio_wfq_vs_fifo": round(
            wfq["goodput_tokens_per_second"]
            / max(fifo["goodput_tokens_per_second"], 1e-9),
            3,
        ),
        # bit-identity vs the unscheduled engine (greedy): the
        # scheduler reorders admissions, it never changes tokens
        "outputs_bit_identical": bool(
            all(fifo_out[rid] == ref_out[rid] for rid in fifo_out)
            and all(wfq_out[rid] == ref_out[rid] for rid in wfq_out)
        ),
    }

    # --- routing (ISSUE 12): prefix-affinity vs RR on a two-runner CPU
    # smoke.  Shared-system-prompt traffic through the REAL router: with
    # affinity each prompt head settles on one runner whose PrefixCache
    # already holds its pages (request-level hit rate climbs and TTFT
    # drops); RR spreads every head across both runners and re-prefills.
    from helix_tpu.control.router import (
        InferenceRouter,
        RouterPolicy,
        prefix_digest,
    )

    route_ps = 4
    route_prefix_pages = 4
    # an ODD head count: under pure RR each head alternates runners
    # (re-prefilling on both), while affinity parks each head on one —
    # an even count would phase-lock RR into accidental affinity
    route_prefixes = [
        [(40 * (p + 1) + j) % (cfg.vocab_size - 2) + 1
         for j in range(route_ps * route_prefix_pages)]
        for p in range(3)
    ]

    def routing_pass(policy: RouterPolicy) -> dict:
        loops = {}
        for rid in ("r1", "r2"):
            eng_r = Engine(cfg, params, EngineConfig(
                max_decode_batch=2, page_size=route_ps, num_pages=128,
                max_pages_per_seq=32, max_prefill_len=32,
                enable_prefix_cache=True, kv_cache_dtype=kv_dtype,
            ))
            loops[rid] = EngineLoop(eng_r, f"route-{rid}").start()
        router = InferenceRouter(policy=policy)
        # shape warm-up OUTSIDE the measurement: same length buckets,
        # disjoint content (must not pre-seed the bench prefixes).  Two
        # identical submissions per runner so the prefix-HIT admission
        # shape compiles here, not inside a measured TTFT
        for rid, loop in loops.items():
            for rep in range(2):
                ev = _threading.Event()
                loop.submit(
                    Request(
                        id=f"route-warm-{rid}-{rep}",
                        prompt_tokens=[(7 * j) % 250 + 260
                                       for j in range(20)],
                        sampling=SamplingParams(
                            temperature=0.0, max_tokens=4
                        ),
                    ),
                    lambda e, _ev=ev: _ev.set() if e.finished else None,
                )
                ev.wait(timeout=300)
        base = {
            rid: (loop.engine.prefix_cache_hits,
                  loop.engine.prefix_cache_misses)
            for rid, loop in loops.items()
        }
        ttfts = []
        for i in range(15):
            prefix = route_prefixes[i % 3]
            for rid, loop in loops.items():
                router.upsert_from_heartbeat(
                    rid, models=["m"], profile_status="running",
                    saturation=loop.saturation(),
                )
            key = prefix_digest("m", str(prefix))
            st = router.pick_runner("m", affinity_key=key)
            first = _threading.Event()
            done = _threading.Event()

            def cb(e, _f=first, _d=done):
                if e.token_id >= 0:
                    _f.set()
                if e.finished:
                    _d.set()

            t0 = time.perf_counter()
            loops[st.id].submit(
                Request(
                    id=f"route-{policy.policy}-{i}",
                    prompt_tokens=prefix + [261 + i],
                    sampling=SamplingParams(
                        temperature=0.0, max_tokens=4
                    ),
                ),
                cb,
            )
            first.wait(timeout=300)
            ttfts.append(time.perf_counter() - t0)
            done.wait(timeout=300)
        hits = misses = 0
        for rid, loop in loops.items():
            h0, m0 = base[rid]
            hits += loop.engine.prefix_cache_hits - h0
            misses += loop.engine.prefix_cache_misses - m0
            loop.stop(join=True)
        return {
            "prefix_request_hit_rate": round(
                hits / max(1, hits + misses), 4
            ),
            "ttft_mean_seconds": round(
                sum(ttfts) / len(ttfts), 4
            ),
            "affinity_hits": router.route_affinity_hits,
            "affinity_yields": router.route_affinity_yields,
        }

    rr_pass = routing_pass(RouterPolicy())
    aff_pass = routing_pass(
        RouterPolicy(policy="scored", affinity=True)
    )
    result["routing"] = {
        "runners": 2,
        "distinct_prompt_heads": 3,
        "requests": 15,
        "rr": rr_pass,
        "affinity": aff_pass,
        "affinity_hit_rate_vs_rr": round(
            aff_pass["prefix_request_hit_rate"]
            - rr_pass["prefix_request_hit_rate"], 4
        ),
        "ttft_ratio_affinity_vs_rr": round(
            aff_pass["ttft_mean_seconds"]
            / max(rr_pass["ttft_mean_seconds"], 1e-9), 3
        ),
    }

    # --- unified ragged kernel (ISSUE 10): shape count, warmup, padding,
    # tokens per device step — CPU-smoke-runnable --------------------------
    kern_slots = 4
    kern_C = 64
    # page_size distinct from the main bench engines: the compiled-shape
    # registry is shared per (model, page geometry) exactly like the
    # traces, so a distinct geometry gives this block a clean count
    kern_ps = 8
    eng_k = Engine(cfg, params, EngineConfig(
        max_decode_batch=kern_slots, page_size=kern_ps, num_pages=256,
        max_pages_per_seq=32, max_prefill_len=kern_C,
        enable_prefix_cache=True, enable_spec_decode=True, spec_tokens=3,
        enable_mixed_step=True, decode_steps_per_sync=4,
        kv_cache_dtype=kv_dtype,
    ))
    t0 = time.perf_counter()
    eng_k.warmup()
    kern_warmup_s = time.perf_counter() - t0
    warmed_shapes = eng_k.compiled_step_shapes
    gen = SamplingParams(temperature=0.0, max_tokens=24)
    sys_prefix = [(13 * i) % (cfg.vocab_size - 2) + 1 for i in range(32)]
    shorts = [sys_prefix + [40 + i, 41, 42 + i] for i in range(3)]
    rep = [(5, 9, 7, 3) * 10][0]
    long_p = [(7 * i) % (cfg.vocab_size - 2) + 1 for i in range(3 * kern_C)]
    p0 = eng_k.num_prefill_tokens
    pad0 = eng_k.num_prefill_padding_tokens
    d0, c0 = eng_k.num_decode_tokens, eng_k.num_device_calls
    # phase 1: cold shorts + spec-friendly repetitive prompt (packed wave
    # + verify rows); phase 2: same prefixes again (cache-hit rows pack
    # the SAME wave as cold rows — the padding win); phase 3: a long
    # prompt admitted mid-decode (chunk + mixed rows)
    for i, r in enumerate(
        [Request(id=f"k1-{j}", prompt_tokens=list(p), sampling=gen)
         for j, p in enumerate(shorts + [list(rep)])]
    ):
        eng_k.add_request(r)
    while eng_k.has_work():
        eng_k.step()
    hit_reqs = [
        Request(id=f"k2-{j}", prompt_tokens=list(p), sampling=gen)
        for j, p in enumerate(shorts)
    ]
    hit_rems = []
    for r in hit_reqs:
        eng_k.add_request(r)
    for _ in range(2):
        eng_k.step()
    eng_k.add_request(
        Request(id="k-long", prompt_tokens=list(long_p), sampling=gen)
    )
    while eng_k.has_work():
        eng_k.step()
    hit_rems = [
        len(r.prompt_tokens) - r.cached_tokens for r in hit_reqs
    ]
    k_prefill = eng_k.num_prefill_tokens - p0
    k_pad = eng_k.num_prefill_padding_tokens - pad0
    k_decode = eng_k.num_decode_tokens - d0
    k_calls = eng_k.num_device_calls - c0

    def _pow2(n, lo, hi):
        b = lo
        while b < n:
            b *= 2
        return min(b, hi)

    # what the pre-unification zoo would have compiled / padded for the
    # SAME workload (lower-bound ESTIMATE, replaying the old bucketing
    # rules): packed pow2 buckets, per-request chunk-hit calls with
    # pow2(remainder) × pow2-history pairs, chunk + mixed (C × hist)
    # pairs, per-window decode scans, verify width×hist×tail triples
    legacy_shapes = set()
    for p in shorts + [list(rep)]:
        legacy_shapes.add(("packed", _pow2(len(p), kern_ps, kern_C)))
    for rem, r in zip(hit_rems, hit_reqs):
        m = kern_C
        while m < r.cached_tokens:
            m *= 2
        legacy_shapes.add(("chunk_hit", _pow2(max(rem, kern_ps), kern_ps,
                                              kern_C), m))
    for start in range(0, len(long_p), kern_C):
        m = 0 if start == 0 else max(kern_C, _pow2(start, kern_C, 1 << 20))
        legacy_shapes.add(("chunk", kern_C, m))
        legacy_shapes.add(("mixed", kern_C, m))   # compiled separately
    for n in (1, 2, 4):                           # fused windows used
        legacy_shapes.add(("decode", n))
    for tail in (0, 1, 3):                        # verify tails per window
        legacy_shapes.add(("verify", 4, tail))
    legacy_hit_pad = sum(
        _pow2(max(rem, kern_ps), kern_ps, kern_C) - rem
        for rem in hit_rems
    )
    hit_wave_pad = (
        _pow2(max(sum(hit_rems), kern_ps), kern_ps, kern_C)
        - sum(hit_rems)
    )
    result["kernel"] = {
        "compiled_step_shapes": eng_k.compiled_step_shapes,
        "compiled_step_shapes_warmup": warmed_shapes,
        "warmup_seconds": round(kern_warmup_s, 2),
        "prefill_tokens": k_prefill,
        "padding_tokens": k_pad,
        "padding_ratio": round(k_pad / max(k_pad + k_prefill, 1), 4),
        "tokens_per_device_step": round(
            (k_prefill + k_decode) / max(k_calls, 1), 2
        ),
        "decode_tokens": k_decode,
        "device_step_calls": k_calls,
        "spec_steps": eng_k.num_spec_steps,
        "mixed_steps": eng_k.num_mixed_steps,
        "prefix_hits": eng_k.prefix_cache_hits,
        # pre-unification comparators (estimates replaying the old
        # bucketing rules on this exact workload): per-request chunk-hit
        # calls each padded their own pow2 bucket where the unified wave
        # packs them into one, and each hit was its own device call
        "legacy_step_shapes_estimate": len(legacy_shapes),
        "legacy_padding_ratio_estimate": round(
            (k_pad - hit_wave_pad + legacy_hit_pad)
            / max(k_pad - hit_wave_pad + legacy_hit_pad + k_prefill, 1),
            4,
        ),
        "legacy_device_step_calls_estimate": (
            k_calls + max(0, len(hit_rems) - 1)
        ),
        "legacy_chunk_hit_padding_tokens": legacy_hit_pad,
    }
    del eng_k

    # --- asynchronous pipelined engine loop (ISSUE 13): host-overlap
    # before/after.  Runs the ENGINE LOOP, not engine.generate — the
    # quantity under test is the loop's host shadow (scheduling, flight
    # accounting, token emission) between device dispatches.  Per-token
    # cadence (decode_steps_per_sync=1) is the loop-shadow-heaviest
    # case, so this is the number the pipeline exists to move.
    import threading as _threading

    from helix_tpu.serving.engine_loop import EngineLoop

    hov_reqs = batch if on_tpu else 4
    # CPU smoke: long enough that the steady-state rate dominates loop/
    # thread startup (a 4x24-token pass is ~40 ms of wall — pure noise)
    hov_gen = 64 if on_tpu else 96
    hov_plen = prompt_len if on_tpu else 8

    def _host_overlap_pass(async_on: bool) -> dict:
        eng_h = Engine(cfg, params, EngineConfig(
            max_decode_batch=hov_reqs,
            page_size=16 if on_tpu else 8,
            num_pages=num_pages,
            max_pages_per_seq=64 if on_tpu else 16,
            max_prefill_len=512 if on_tpu else 32,
            kv_cache_dtype=kv_dtype,
            decode_steps_per_sync=1,
            enable_prefix_cache=False,
            enable_async_loop=async_on,
        ))
        # compile outside the timed pass (both passes share the trace
        # cache, so whichever ran first would otherwise eat XLA time)
        eng_h.warmup()
        loop = EngineLoop(
            eng_h, name="hov-async" if async_on else "hov-sync"
        )
        loop.flight.reset_baseline()
        dones, toks = [], [0]
        for j in range(hov_reqs):
            done = _threading.Event()
            dones.append(done)

            def cb(ev, done=done):
                if ev.token_id >= 0:
                    toks[0] += 1
                if ev.finished:
                    done.set()

            loop.submit(
                Request(
                    id=f"hov-{j}",
                    prompt_tokens=[
                        (11 * (j + 1) + i) % (cfg.vocab_size - 2) + 1
                        for i in range(hov_plen)
                    ],
                    sampling=SamplingParams(
                        temperature=0.0, max_tokens=hov_gen
                    ),
                ),
                cb,
            )
        # submissions queued before the thread starts: the timed window
        # is pure serving, not loop spin-up
        t0 = time.perf_counter()
        loop.start()
        for done in dones:
            done.wait(timeout=600)
        wall = time.perf_counter() - t0
        recs = [
            r for r in loop.flight.snapshot(recent=512)["recent"]
            if "wall_s" in r
        ]
        nsteps = max(1, len(recs))

        def _tot(k):
            return sum(float(r.get(k, 0.0) or 0.0) for r in recs)

        st = loop.stats()["async_loop"]
        steps = loop.steps
        loop.stop(join=True)
        return {
            "tokens_per_sec": round(toks[0] / max(wall, 1e-9), 2),
            "device_idle_ratio": st["device_idle_ratio"],
            "host_build_ms_per_step": round(
                1e3 * _tot("host_build_s") / nsteps, 3
            ),
            "device_wait_ms_per_step": round(
                1e3 * _tot("device_wait_s") / nsteps, 3
            ),
            "emit_ms_per_step": round(1e3 * _tot("emit_s") / nsteps, 3),
            "idle_gap_ms_per_step": round(
                1e3 * _tot("idle_gap_s") / nsteps, 3
            ),
            "pipelined_steps": st["pipelined_steps"],
            "steps": steps,
        }

    hov_sync = _host_overlap_pass(False)
    hov_async = _host_overlap_pass(True)
    result["host_overlap"] = {
        "requests": hov_reqs,
        "gen_tokens_per_request": hov_gen,
        "sync": hov_sync,
        "async": hov_async,
        # the before/after this PR claims: the async loop keeps the
        # device busier (idle ratio strictly lower) at no goodput cost
        "idle_ratio_delta": round(
            hov_async["device_idle_ratio"] - hov_sync["device_idle_ratio"],
            4,
        ),
        "tokens_per_sec_ratio_async_vs_sync": round(
            hov_async["tokens_per_sec"]
            / max(hov_sync["tokens_per_sec"], 1e-9),
            3,
        ),
    }

    # -- continuous multi-LoRA serving (ISSUE 15) --------------------------
    # K interleaved adapters through ONE pool-enabled engine (mixed-
    # adapter waves pack one device call) vs the pre-ISSUE-15 story: one
    # merged-model copy per adapter, rebuilt (the hot-swap compile wave)
    # whenever the served adapter changes.  CPU smoke: values are not
    # hardware-comparable, but tokens/device-step and the HBM-bytes
    # ratio are structural.
    from helix_tpu.training.lora import (
        LoraConfig,
        init_lora_params,
        merge_lora_into_params,
    )

    ml_K = 3
    ml_rank = 8
    ml_gen = 16 if not on_tpu else 64
    ml_plen = 8 if not on_tpu else prompt_len
    ml_per = 2     # requests per adapter (+ ml_per adapter-free)

    def _ml_adapter(seed):
        lp = init_lora_params(
            cfg, LoraConfig(rank=ml_rank), jax.random.PRNGKey(seed)
        )
        for t in lp:
            lp[t]["lora_b"] = (
                jax.random.normal(
                    jax.random.fold_in(jax.random.PRNGKey(seed), 1),
                    lp[t]["lora_b"].shape, jnp.float32,
                ) * 0.01
            )
        return lp

    ml_adapters = {f"ml{j}": _ml_adapter(100 + j) for j in range(ml_K)}
    ml_sampling = SamplingParams(temperature=0.0, max_tokens=ml_gen)

    def _ml_prompt(i):
        return [
            (13 * (i + 1) + j) % (cfg.vocab_size - 2) + 1
            for j in range(ml_plen)
        ]

    def _ml_p95(xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * 0.95))] if xs else 0.0

    def _ml_drain(eng, reqs):
        for r in reqs:
            eng.add_request(r)
        while eng.has_work():
            eng.step()

    # interleaved: one engine, K adapters + adapter-free, mixed waves
    ml_eng = make_engine(
        kv_dtype, adapter_pool_slots=ml_K + 1, adapter_rank=ml_rank,
    )
    ml_eng.warmup()
    for aid, lp in ml_adapters.items():
        ml_eng.publish_adapter(aid, lp, 2.0)
    # warm pass covers every adapter's slot load + the pool program
    _ml_drain(ml_eng, [
        Request(id=f"mlw-{j}", prompt_tokens=_ml_prompt(j),
                sampling=ml_sampling, adapter=f"ml{j}")
        for j in range(ml_K)
    ])
    p0 = ml_eng.num_prefill_tokens + ml_eng.num_decode_tokens
    c0 = ml_eng.num_device_calls
    ml_reqs = []
    for i in range(ml_per * (ml_K + 1)):
        aid = "" if i % (ml_K + 1) == ml_K else f"ml{i % (ml_K + 1)}"
        ml_reqs.append(Request(
            id=f"mli-{i}", prompt_tokens=_ml_prompt(i),
            sampling=ml_sampling, adapter=aid,
        ))
    t0 = time.perf_counter()
    _ml_drain(ml_eng, ml_reqs)
    ml_wall = time.perf_counter() - t0
    ml_tpds = (
        ml_eng.num_prefill_tokens + ml_eng.num_decode_tokens - p0
    ) / max(1, ml_eng.num_device_calls - c0)
    ml_ttft = _ml_p95([
        (r.first_token_time or 0) - r.submit_time for r in ml_reqs
    ])
    adapter_hbm = ml_eng.adapter_pool.hbm_bytes()

    # merged hot-swap baseline: serving a different adapter = building
    # a merged engine (the swap + compile wave charges the waiting
    # requests' TTFT — requests are created BEFORE the swap starts,
    # exactly like traffic queued behind a profile re-apply)
    base_bytes = sum(
        int(x.nbytes) for x in jax.tree.leaves(params)
        if hasattr(x, "nbytes")
    )
    sw_ttfts, sw_tokens, sw_calls, sw_swap = [], 0, 0, 0.0
    t_base = time.perf_counter()
    for j, (aid, lp) in enumerate(ml_adapters.items()):
        reqs = [
            Request(id=f"mls-{j}-{i}", prompt_tokens=_ml_prompt(i),
                    sampling=ml_sampling)
            for i in range(ml_per)
        ]
        ts = time.perf_counter()
        sw_eng = Engine(
            cfg, merge_lora_into_params(params, lp, 2.0),
            EngineConfig(
                max_decode_batch=batch, page_size=16,
                num_pages=num_pages, max_pages_per_seq=64,
                max_prefill_len=512 if on_tpu else 32,
                enable_prefix_cache=False, kv_cache_dtype=kv_dtype,
            ),
        )
        sw_eng.warmup()
        sw_swap += time.perf_counter() - ts
        p0s = sw_eng.num_prefill_tokens + sw_eng.num_decode_tokens
        c0s = sw_eng.num_device_calls
        _ml_drain(sw_eng, reqs)
        sw_tokens += (
            sw_eng.num_prefill_tokens + sw_eng.num_decode_tokens - p0s
        )
        sw_calls += sw_eng.num_device_calls - c0s
        sw_ttfts += [
            (r.first_token_time or 0) - r.submit_time for r in reqs
        ]
    sw_wall = time.perf_counter() - t_base
    result["multi_lora"] = {
        "adapters": ml_K,
        "rank": ml_rank,
        "requests": len(ml_reqs),
        "gen_tokens_per_request": ml_gen,
        "interleaved": {
            "wall_seconds": round(ml_wall, 3),
            "tokens_per_device_step": round(ml_tpds, 2),
            "ttft_p95_seconds": round(ml_ttft, 4),
            "adapter_hbm_bytes": adapter_hbm,
            "distinct_adapters_served": ml_K,
        },
        "merged_hot_swap": {
            "wall_seconds": round(sw_wall, 3),
            "tokens_per_device_step": round(
                sw_tokens / max(1, sw_calls), 2
            ),
            "ttft_p95_seconds": round(_ml_p95(sw_ttfts), 4),
            "swap_seconds_total": round(sw_swap, 3),
            "model_copies_hbm_bytes": ml_K * base_bytes,
        },
        # the structural wins: adapter state costs a fraction of K full
        # model copies, and adapter churn costs a slot load instead of
        # an engine rebuild + compile wave
        "hbm_bytes_ratio_adapters_vs_copies": round(
            adapter_hbm / max(1, ml_K * base_bytes), 6
        ),
    }

    # -- multi-host plan broadcast (ISSUE 16) -----------------------------
    # The leader's only extra work per step is recording host decisions
    # and publishing one compact JSON plan; measured against the bare
    # engine on the same workload the broadcast must cost ~nothing
    # (acceptance: within 10%).  The follower number is the pure
    # plan-apply overhead per step (its device steps reuse the compiled
    # fns from this process's registry, isolating the host-side cost).
    from helix_tpu.serving.multihost_serving import (
        FollowerLoop,
        PlanLeader,
    )

    def _mh_reqs(tag):
        return [
            Request(id=f"mh-{tag}-{i}", prompt_tokens=list(p),
                    sampling=sampling)
            for i, p in enumerate(prompts)
        ]

    def _mh_drain(obj):
        steps = 0
        while obj.has_work():   # PlanLeader passes through to the engine
            obj.step()
            steps += 1
        return steps

    mh_single = make_engine(kv_dtype)
    mh_leader = PlanLeader(make_engine(kv_dtype))
    for warm in ("w0", "w1"):   # warm pass compiles every shape first
        for r in _mh_reqs(f"{warm}s"):
            mh_single.add_request(r)
        _mh_drain(mh_single)
        for r in _mh_reqs(f"{warm}l"):
            mh_leader.add_request(r)
        _mh_drain(mh_leader)
    for r in _mh_reqs("s"):
        mh_single.add_request(r)
    t0 = time.perf_counter()
    st_single = _mh_drain(mh_single)
    single_wall = time.perf_counter() - t0
    for r in _mh_reqs("l"):
        mh_leader.add_request(r)
    t0 = time.perf_counter()
    st_leader = _mh_drain(mh_leader)
    leader_wall = time.perf_counter() - t0

    mh_follower = make_engine(kv_dtype)
    mh_fol = FollowerLoop(mh_follower, mh_leader.journal,
                          poll_timeout=0.1)
    t0 = time.perf_counter()
    while mh_fol.run_once():
        pass
    fol_wall = time.perf_counter() - t0

    result["multihost"] = {
        "plans_published": mh_leader.plans_published,
        # plan size is the DCN budget: bounded by the admission wave, not
        # by history (steady-state decode plans carry no admits/drafts)
        "plan_bytes_avg": round(
            mh_leader.plan_bytes_total
            / max(1, mh_leader.plans_published), 1
        ),
        "plan_bytes_max": mh_leader.plan_bytes_max,
        "leader_steps_per_sec": round(
            st_leader / max(leader_wall, 1e-9), 2
        ),
        "single_host_steps_per_sec": round(
            st_single / max(single_wall, 1e-9), 2
        ),
        "broadcast_overhead_pct": round(
            (leader_wall / max(single_wall, 1e-9) - 1.0) * 100.0, 2
        ),
        "follower_apply_ms_per_step": round(
            1000.0 * fol_wall / max(1, mh_fol.plans_applied), 3
        ),
        "follower_plans_applied": mh_fol.plans_applied,
        "follower_digest_mismatches": (
            mh_fol.stats()["digest_mismatches"]
        ),
    }

    # -- N-follower fan-out + leader failover (ISSUE 17) ------------------
    # Fan-out: three registered replicas replay the same journal through
    # LocalFeed (so the leader's health registry sees them); the number
    # to watch is that per-follower apply cost stays flat as the mesh
    # widens — the leader publishes once regardless of N.  Failover: a
    # standby is promoted through a real filestore checkpoint + log-tail
    # replay; blackout is the full promote path (validate checksums,
    # park at the boundary, rebuild the journal, republish).
    from helix_tpu.serving.multihost_serving import (
        CheckpointStore,
        LocalFeed,
        promote_follower,
    )

    fan = [
        FollowerLoop(make_engine(kv_dtype),
                     LocalFeed(mh_leader, f"bench-f{i}"))
        for i in range(3)
    ]
    fan_walls = []
    for f in fan:
        t0 = time.perf_counter()
        while f.run_once(timeout=0.0):
            pass
        fan_walls.append(time.perf_counter() - t0)
    health = mh_leader.follower_health()

    to_dir = _tempfile.mkdtemp(prefix="helix-bench-mhckpt-")
    to_store = CheckpointStore(to_dir)
    # failover parks in-flight requests at the boundary through the
    # host KV tier, so the takeover pair runs with it enabled
    _mh_pool = dict(host_pool_bytes=1 << 28)
    to_leader = PlanLeader(make_engine(kv_dtype, **_mh_pool),
                           checkpoint_store=to_store, name="bench")
    to_standby = FollowerLoop(
        make_engine(kv_dtype, **_mh_pool),
        LocalFeed(to_leader, "bench-sb"),
        name="bench", standby=True, checkpoint_store=to_store,
    )
    for r in _mh_reqs("to"):
        to_leader.add_request(r)
    for _ in range(4):             # leave work in flight at the kill
        if to_leader.has_work():
            to_leader.step()
    _ref, _nbytes = to_store.save("bench", to_leader._capture_state())
    while to_standby.run_once(timeout=0.0):
        pass
    to_new = promote_follower(to_standby, store=to_store, name="bench")
    _mh_drain(to_new)

    result["multihost"].update({
        "followers": {
            "replicas": len(fan),
            "states": dict(
                mh_leader.mh_stats()["follower_states"]
            ),
            "apply_ms_per_step_avg": round(
                1000.0 * sum(fan_walls)
                / max(1, sum(f.plans_applied for f in fan)), 3
            ),
            "max_lag_steps": max(
                (st["lag_steps"] for st in health.values()), default=0
            ),
        },
        "takeover_blackout_ms": round(float(to_new.takeover_ms), 1),
        "checkpoint_bytes": int(_nbytes),
    })

    # -- trace federation (ISSUE 18): span overhead + spans/request ----
    # Host-side by construction (the device step records nothing), so
    # the numbers to watch are the runner's per-span record tax with
    # federation on vs off, the cp's per-span ingest cost, and how many
    # spans each serving flow actually emits at the engine plane.
    import threading as _obs_th

    from helix_tpu.obs.trace import TraceFederation as _TraceFed
    from helix_tpu.obs.trace import TraceStore as _TraceStore
    from helix_tpu.serving.engine_loop import EngineLoop as _ObsLoop
    from helix_tpu.serving.migration import (
        snapshot_to_wire as _snap_to_wire,
    )
    from helix_tpu.serving.migration import (
        wire_to_snapshot as _wire_to_snap,
    )

    _SPAN_N = 20000
    _mono = time.monotonic()

    def _record_pass(store):
        t0 = time.perf_counter()
        for i in range(_SPAN_N):
            store.record(
                f"bench-trace-{i & 127:06d}", "bench span", _mono,
                _mono + 1e-4, plane="engine", request_id="r", step=i,
            )
        return (time.perf_counter() - t0) / _SPAN_N * 1e9

    _obs_off_ns = _record_pass(_TraceStore(max_traces=256))
    _st_on = _TraceStore(max_traces=256)
    _st_on.enable_export(cap=65536)
    _obs_on_ns = _record_pass(_st_on)
    _obs_batch = {"spans": _st_on.drain_export(limit=4096)}
    _obs_fed = _TraceFed(local=_TraceStore(), max_traces=4096)
    _t0 = time.perf_counter()
    _obs_fed.ingest("bench-runner", _obs_batch)
    _obs_ing_ns = (
        (time.perf_counter() - _t0)
        / max(1, len(_obs_batch["spans"])) * 1e9
    )

    # spans per request at the always-on engine plane, counted from
    # real EngineLoop flows with per-"host" stores (the HTTP planes
    # stack their dispatch/handoff spans on top of these)
    def _obs_loop(tag):
        st = _TraceStore()
        lp = _ObsLoop(make_engine(kv_dtype), name=f"bench-obs-{tag}")
        lp._trace = st
        lp.start()
        return lp, st

    _obs_prompt = [(13 * j) % (cfg.vocab_size - 2) + 1
                   for j in range(24)]
    _obs_sampling = SamplingParams(temperature=0.0, max_tokens=16)

    def _obs_span_count(tid, *stores):
        total = 0
        for st in stores:
            doc = st.get(tid)
            total += len(doc["spans"]) if doc else 0
        return total

    def _obs_submit(lp, tid, rid):
        ev = _obs_th.Event()

        def cb(e):
            if e.finished:
                ev.set()

        lp.submit(
            Request(id=rid, prompt_tokens=list(_obs_prompt),
                    sampling=_obs_sampling, trace_id=tid),
            cb,
        )
        return ev

    # plain: one colocated streamed request
    _lp_plain, _st_plain = _obs_loop("plain")
    _obs_submit(_lp_plain, "bench-plain-00001", "obs-plain").wait(120)
    spans_plain = _obs_span_count("bench-plain-00001", _st_plain)
    _lp_plain.stop(join=True)

    # disagg: staged prefill export on one loop, checksum-validated
    # import + decode on the other, source aborted on confirmed ship
    _lp_pre, _st_pre = _obs_loop("pre")
    _lp_dec, _st_dec = _obs_loop("dec")
    _snap_box = {}
    _ev_snap = _obs_th.Event()

    def _on_export(kind, wire):
        _snap_box["kind"], _snap_box["wire"] = kind, wire
        _ev_snap.set()

    _lp_pre.stage_disagg_export("obs-disagg", _on_export)
    _ev_fin = _obs_submit(_lp_pre, "bench-disagg-0001", "obs-disagg")
    assert _ev_snap.wait(120)
    spans_disagg = None
    if _snap_box["kind"] == "snapshot":
        _ev_imp = _obs_th.Event()
        _ev_dec = _obs_th.Event()

        def _dec_cb(e):
            if e.finished:
                _ev_dec.set()

        _lp_dec.submit_import(
            _wire_to_snap(_snap_box["wire"]), _dec_cb,
            on_result=lambda err, code: _ev_imp.set(),
        )
        assert _ev_imp.wait(120)
        _lp_pre.abort("obs-disagg")
        assert _ev_dec.wait(120)
        spans_disagg = _obs_span_count(
            "bench-disagg-0001", _st_pre, _st_dec
        )
    else:
        _ev_fin.wait(120)   # short-generation fallback: served locally
        spans_disagg = _obs_span_count("bench-disagg-0001", _st_pre)

    # migrated: mid-decode snapshot through the real wire format,
    # continuation on the peer loop
    _mig_eng = make_engine(kv_dtype)
    _mig_req = Request(
        id="obs-mig", prompt_tokens=list(_obs_prompt),
        sampling=_obs_sampling, trace_id="bench-migrate-001",
    )
    _mig_eng.add_request(_mig_req)
    while len(_mig_req.output_tokens) < 4 and _mig_eng.has_work():
        _mig_eng.step()
    _mig_wire = _snap_to_wire(_mig_eng.export_request("obs-mig"))
    _ev_mimp, _ev_mdec = _obs_th.Event(), _obs_th.Event()

    def _mig_cb(e):
        if e.finished:
            _ev_mdec.set()

    _lp_dec.submit_import(
        _wire_to_snap(_mig_wire), _mig_cb,
        on_result=lambda err, code: _ev_mimp.set(),
    )
    assert _ev_mimp.wait(120) and _ev_mdec.wait(120)
    spans_migrated = _obs_span_count("bench-migrate-001", _st_dec)
    _lp_pre.stop(join=True)
    _lp_dec.stop(join=True)
    del _mig_eng

    result["observability"] = {
        "span_record_ns": round(_obs_off_ns, 1),
        "span_record_federated_ns": round(_obs_on_ns, 1),
        "federation_overhead_ns_per_span": round(
            _obs_on_ns - _obs_off_ns, 1
        ),
        "cp_ingest_ns_per_span": round(_obs_ing_ns, 1),
        "export_batch_spans": len(_obs_batch["spans"]),
        "spans_per_request_engine_plane": {
            "plain": spans_plain,
            "disagg": spans_disagg,
            "migrated": spans_migrated,
        },
    }

    # ---- correctness canaries (ISSUE 19) -------------------------------
    # probe overhead (device steps per probe round, foreground TTFT p95
    # with the prober on vs off) and detection latency for an injected
    # silent-corruption fault — the numbers an operator weighs before
    # opting into HELIX_CANARY=1
    from helix_tpu.obs.canary import CanaryProber as _Canary
    from helix_tpu.serving.registry import ServedModel as _CanServed
    from helix_tpu.serving.tokenizer import ByteTokenizer as _CanTok
    from helix_tpu.testing import faults as _can_faults

    _can_lp = _ObsLoop(make_engine(kv_dtype), name="bench-canary")
    _can_lp.start()
    _can_served = _CanServed(
        name="bench-canary-m", loop=_can_lp, tokenizer=_CanTok(),
        context_length=256,
    )
    _can = _Canary(
        runner_id="bench", models_fn=lambda: [_can_served],
        interval=9999, failures=2, backoff=9999,
    )
    _t0 = time.perf_counter()
    _can_probes = _can.mint_models([_can_served])
    _can_mint_s = time.perf_counter() - _t0

    _steps0 = _can_lp.flight.steps_recorded
    _t0 = time.perf_counter()
    _can.probe_round()
    _can_round_s = time.perf_counter() - _t0
    _can_round_steps = _can_lp.flight.steps_recorded - _steps0

    def _can_ttft_p95(n, tag):
        tts = []
        for i in range(n):
            ev = _obs_th.Event()
            t0 = time.perf_counter()
            box = [0.0]

            def cb(e, box=box, t0=t0, ev=ev):
                if e.token_id >= 0 and box[0] == 0.0:
                    box[0] = time.perf_counter() - t0
                if e.finished:
                    ev.set()

            _can_lp.submit(
                Request(id=f"bench-can-{tag}-{i}",
                        prompt_tokens=list(_obs_prompt),
                        sampling=_obs_sampling),
                cb,
            )
            assert ev.wait(120)
            tts.append(box[0])
        tts.sort()
        return tts[min(len(tts) - 1, int(0.95 * len(tts)))]

    _can_ttft_off = _can_ttft_p95(8, "off")
    _can_stop = _obs_th.Event()

    def _can_probe_bg():
        while not _can_stop.is_set():
            _can.probe_round()

    _can_bg = _obs_th.Thread(target=_can_probe_bg, daemon=True)
    _can_bg.start()
    _can_ttft_on = _can_ttft_p95(8, "on")
    _can_stop.set()
    _can_bg.join(timeout=120)

    # detection latency: inject silent output corruption, count probe
    # rounds until the health rung flips to failing
    _can_faults.arm(rules=[{
        "point": "corrupt_output", "engine": "bench-canary",
        "offset": 1,
    }])
    _t0 = time.perf_counter()
    _det_rounds = 0
    while _can.state != "failing" and _det_rounds < 10:
        _can.probe_round()
        _det_rounds += 1
    _det_s = time.perf_counter() - _t0
    _can_faults.disarm()
    _can_lp.stop(join=True)

    result["canary"] = {
        "probes_minted": _can_probes,
        "mint_seconds": round(_can_mint_s, 4),
        "device_steps_per_probe_round": _can_round_steps,
        "probe_round_seconds": round(_can_round_s, 4),
        "foreground_ttft_p95_prober_off_s": round(_can_ttft_off, 4),
        "foreground_ttft_p95_prober_on_s": round(_can_ttft_on, 4),
        "detection_rounds_injected_corruption": _det_rounds,
        "detection_seconds": round(_det_s, 4),
        "state_after_detection": _can.state,
    }

    # Tiered long-context streaming (ISSUE 20): peak HBM residency and
    # TTFT vs context length, cold middle streamed from host RAM vs
    # fully device-resident.  Runs a deliberately tiny single-layer
    # model on BOTH platforms so the 32k -> 256k ladder stays tractable
    # — the capacity story (resident peak pages grow linearly with
    # context while the streamed peak stays flat at hot tail + prefill
    # window) is hardware-independent, like the tiering block above.
    # TTFT is indicative only off-TPU: the streamed arm pays XLA:CPU
    # cold-chunk bucket compiles inside the measured window.
    from helix_tpu.models.common import ModelConfig as _MC

    lc_cfg = _MC.tiny(
        vocab_size=64, hidden_size=16, num_layers=1, num_heads=1,
        num_kv_heads=1, head_dim=8, intermediate_size=32,
        rope_theta=500000.0, dtype="float32", name="tiny-lc",
    )
    lc_params = init_params(lc_cfg, jax.random.PRNGKey(0))
    lc_ps = 32
    lc_hot, lc_stream = 8, 32   # 8-page hot tail, 1k-token stream chunks
    lc_ladder = [32768, 65536]
    lc_top = 262144
    lc_sampling = SamplingParams(temperature=0.0, max_tokens=2)

    def lc_engine(cap_tokens: int, streamed: bool):
        # BOTH arms size their table for exactly this rung's context so
        # TTFT compares apples-to-apples (the reference backend's hot
        # path scans the masked table width); only num_pages differs —
        # the streamed arm's device pool is a small constant, an order
        # of magnitude under one rung's pages, and fitting at all is
        # the result under test
        return Engine(
            lc_cfg, lc_params,
            EngineConfig(
                max_decode_batch=1, page_size=lc_ps,
                num_pages=160 if streamed else cap_tokens // lc_ps + 128,
                max_pages_per_seq=cap_tokens // lc_ps + 2,
                max_prefill_len=2048,
                enable_prefix_cache=False,
                attn_backend="reference",
                **(dict(host_pool_bytes=256 << 20, ctx_hot_pages=lc_hot,
                        ctx_stream_pages=lc_stream) if streamed else {}),
            ),
        )

    def lc_prompt(n):
        return [(5 * j) % (lc_cfg.vocab_size - 2) + 1 for j in range(n)]

    def lc_run(eng, tag, prompt_tokens):
        req = Request(id=tag, prompt_tokens=prompt_tokens,
                      sampling=lc_sampling)
        t0 = time.perf_counter()
        eng.add_request(req)
        while not req.output_tokens:
            eng.step()
        ttft = time.perf_counter() - t0
        while eng.has_work():
            eng.step()
        return req.output_tokens, ttft

    lc_rows = []
    for n_ctx in lc_ladder + [lc_top]:
        row = {"context_tokens": n_ctx}
        r_toks = None
        if n_ctx <= max(lc_ladder):
            lc_res = lc_engine(n_ctx, False)
            lc_run(lc_res, "lc-warm-res", lc_prompt(2 * 2048))
            lc_res.allocator.peak_used = lc_res.allocator.used_pages
            r_toks, r_ttft = lc_run(
                lc_res, f"lc-res-{n_ctx}", lc_prompt(n_ctx)
            )
            row["resident"] = {
                "ttft_s": round(r_ttft, 3),
                "peak_hbm_pages": lc_res.allocator.peak_used,
            }
            del lc_res
        lc_str = lc_engine(n_ctx, True)
        lc_run(lc_str, "lc-warm-str", lc_prompt(2 * 2048))
        lc_str.allocator.peak_used = lc_str.allocator.used_pages
        lc_d0 = lc_str.num_ctx_demoted_pages
        lc_c0 = lc_str.num_ctx_stream_chunks
        s_toks, s_ttft = lc_run(lc_str, f"lc-str-{n_ctx}", lc_prompt(n_ctx))
        row["streamed"] = {
            "ttft_s": round(s_ttft, 3),
            "peak_hbm_pages": lc_str.allocator.peak_used,
            "demoted_pages": lc_str.num_ctx_demoted_pages - lc_d0,
            "stream_chunks": lc_str.num_ctx_stream_chunks - lc_c0,
        }
        if r_toks is not None:
            row["outputs_match"] = bool(r_toks == s_toks)
        lc_rows.append(row)
        del lc_str

    # context-cache hit (the /v1/context flow): persist a prompt prefix
    # as a content-addressed handle, then serve a request that
    # references the handle — the cached span's prefill is served from
    # the device prefix cache instead of recomputed, which is the TTFT
    # win the API exists for.
    import shutil
    import tempfile

    from helix_tpu.serving.context_cache import context_cache_for

    cc_root = tempfile.mkdtemp(prefix="bench-ctx-")
    cc_cache = context_cache_for(cc_root)
    cc_prefix = lc_prompt(8192)
    cc_handle = cc_cache.put(cc_prefix, tenant="bench")
    cc_eng = Engine(
        lc_cfg, lc_params,
        EngineConfig(
            max_decode_batch=1, page_size=lc_ps, num_pages=640,
            max_pages_per_seq=288, max_prefill_len=2048,
            enable_prefix_cache=True, attn_backend="reference",
        ),
    )
    cc_warm = [(7 * j) % 62 + 1 for j in range(2048)]
    lc_run(cc_eng, "cc-warm-0", list(cc_warm))   # packed-prefill shapes
    lc_run(cc_eng, "cc-warm-1", list(cc_warm))   # chunk-hit shapes
    # creation pass — what POST /v1/context pays once per handle
    _, cc_ttft_create = lc_run(cc_eng, "cc-create", list(cc_prefix))
    # hit pass — a request referencing the handle: resolved prefix +
    # fresh suffix, cached span served from the prefix cache
    cc_h0 = cc_eng.prefix_cache_hits
    cc_suffix = [(11 * j) % 62 + 1 for j in range(64)]
    _, cc_ttft_hit = lc_run(
        cc_eng, "cc-hit", list(cc_cache.get(cc_handle)) + cc_suffix
    )
    cc_hit = cc_eng.prefix_cache_hits - cc_h0

    result["long_context"] = {
        "model": "tiny-lc(L=1,H=1,KVH=1,D=8)",
        "page_size": lc_ps,
        "hot_pages": lc_hot,
        "stream_pages": lc_stream,
        "ladder": lc_rows,
        "context_cache": {
            "handle": cc_handle,
            "context_tokens": len(cc_prefix),
            "ttft_create_s": round(cc_ttft_create, 3),
            "ttft_hit_s": round(cc_ttft_hit, 3),
            "ttft_speedup": round(
                cc_ttft_create / max(cc_ttft_hit, 1e-9), 2
            ),
            "cached_span_hit": bool(cc_hit >= 1),
        },
    }
    del cc_eng
    shutil.rmtree(cc_root, ignore_errors=True)

    if on_tpu:
        # decode-side model FLOPs utilisation: each generated token moves
        # ~2 FLOPs per active parameter through the MXU; a v5e chip peaks
        # at 197 TFLOP/s bf16 (394 TOPS int8 — we report against bf16, the
        # conservative denominator for int8 weight-only which computes in
        # bf16).
        V5E_PEAK_BF16_FLOPS = 197e12
        LLAMA3_8B_PARAMS = 8.03e9
        result["mfu_est"] = round(
            toks_per_s * 2 * LLAMA3_8B_PARAMS / V5E_PEAK_BF16_FLOPS, 4
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
