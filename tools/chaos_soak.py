"""Randomized (but seeded) chaos soak of the in-process serving stack.

Builds a tiny engine + EngineLoop with admission bounds, arms the fault
injector with a probabilistic engine-step fault plus persistent poisoned
requests, then pumps seeded random traffic for N seconds.  The exit
assertion is the serving spine's core robustness contract: **zero stuck
requests** — every submission reaches a terminal event (tokens+finish,
quarantine eviction, shed, or timeout), the engine thread never dies, and
the loop keeps accepting work afterwards.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 10 --seed 42

Also imported by the slow lane of ``tests/test_chaos.py``.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
)


def run_soak(seconds: float = 10.0, seed: int = 42,
             step_fault_p: float = 0.02, poison_every: int = 7) -> dict:
    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.tokenizer import ByteTokenizer
    from helix_tpu.testing import faults

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = Engine(
        cfg, params,
        EngineConfig(
            max_decode_batch=4, page_size=4, num_pages=256,
            max_pages_per_seq=32, max_prefill_len=64,
            attn_backend="reference", eos_token_ids=tok.eos_ids,
        ),
    )
    faults.arm(
        seed=seed,
        rules=[
            # transient step faults: retry-once should absorb most
            {"point": "engine_step", "p": step_fault_p},
            # persistent poison: every step that schedules such a request
            # fails until quarantine evicts it
            {"point": "engine_step", "request_id_contains": "poison"},
        ],
    )
    loop = EngineLoop(
        engine, "soak", max_queue_seconds=20.0,
        max_queue_depth=32, max_queued_tokens=4096,
    ).start()

    rng = random.Random(seed)
    outcomes: dict[str, str] = {}
    terminal: dict[str, bool] = {}

    def on_event_for(rid):
        def on_event(ev):
            if ev.finished:
                terminal[rid] = True
                outcomes[rid] = (
                    "error:" + ev.error.split(":")[0]
                    if ev.error
                    else (ev.finish_reason or "stop")
                )
        return on_event

    t0 = time.monotonic()
    n = 0
    try:
        while time.monotonic() - t0 < seconds:
            n += 1
            rid = (
                f"poison-{n}" if n % poison_every == 0 else f"req-{n}"
            )
            req = Request(
                id=rid,
                prompt_tokens=[rng.randrange(4, 260)
                               for _ in range(rng.randrange(4, 48))],
                sampling=SamplingParams(
                    max_tokens=rng.randrange(2, 16), seed=n
                ),
                stop_token_ids=tok.eos_ids,
            )
            terminal[rid] = False
            loop.submit(req, on_event_for(rid))
            time.sleep(rng.uniform(0.0, 0.05))
        # drain: give every in-flight request time to reach a terminal
        # event (quarantine/shed/finish), then a final health probe
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not all(terminal.values()):
            time.sleep(0.1)
        faults.disarm()
        probe_done = [False]
        loop.submit(
            Request(
                id="final-probe", prompt_tokens=[5, 6, 7, 8],
                sampling=SamplingParams(max_tokens=2),
                stop_token_ids=tok.eos_ids,
            ),
            lambda ev: probe_done.__setitem__(0, ev.finished or probe_done[0]),
        )
        pdeadline = time.monotonic() + 30.0
        while time.monotonic() < pdeadline and not probe_done[0]:
            time.sleep(0.05)
    finally:
        faults.disarm()
        loop.stop(join=False)

    stuck = sorted(r for r, done in terminal.items() if not done)
    counts: dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    return {
        "submitted": n,
        "stuck": stuck,
        "outcomes": counts,
        "healthy_after": probe_done[0],
        "stats": loop.stats(),
    }


# the system-prompt-heavy fleet shape (ISSUE 6's headline workload):
# every soak request opens with one of two fixed 16-token system
# prefixes (4 full shareable pages each), so pages evicted-and-spilled
# under pressure get RESTORED for later arrivals instead of re-prefilled
_MEM_SOAK_PREFIXES = (
    [20 + j for j in range(16)],
    [60 + j for j in range(16)],
)


def run_memory_pressure(seconds: float = 10.0, seed: int = 42) -> dict:
    """ISSUE 6 scenario: a page pool sized WELL below sustained demand
    (long prompts, generous token budgets), host tier + the full
    degradation ladder armed.  Traffic keeps admission KV-starved, so
    the loop must spill prefix pages, preempt-by-swap running decoders,
    and shed the over-deadline tail with typed kv_exhausted errors.
    Exit contract: zero stuck requests, every outcome terminal
    (finish / kv_exhausted / queue_full), the engine healthy afterwards,
    and the spill/restore counters actually moving."""
    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = Engine(
        cfg, params,
        EngineConfig(
            # 32 allocatable pages of 4 tokens: ~2.5 concurrent requests
            # worth of KV for a 4-slot batch under the traffic below
            max_decode_batch=4, page_size=4, num_pages=33,
            max_pages_per_seq=24, max_prefill_len=32,
            attn_backend="reference", eos_token_ids=tok.eos_ids,
            host_pool_bytes=1 << 22,
        ),
    )
    # compile every shape the traffic can hit BEFORE the timed window —
    # an 8-second soak must measure the ladder, not the XLA compile
    # wave: generic warmup, then one full request per system prefix
    # (below) so the packed-prefill bucket AND the prefix-hit chunk
    # shape are both hot
    engine.warmup()
    for i, prefix in enumerate(_MEM_SOAK_PREFIXES):
        engine.add_request(
            Request(
                id=f"shape-warm-{i}",
                prompt_tokens=prefix + [100 + j for j in range(4)],
                sampling=SamplingParams(max_tokens=4),
                stop_token_ids=tok.eos_ids,
            )
        )
        while engine.has_work():
            engine.step()
    loop = EngineLoop(
        engine, "mem-soak", max_queue_seconds=30.0,
        max_queue_depth=32, max_queued_tokens=4096,
        admission_timeout=3.0, preempt_stall_seconds=0.1,
    ).start()

    rng = random.Random(seed)
    outcomes: dict[str, str] = {}
    terminal: dict[str, bool] = {}

    def on_event_for(rid):
        def on_event(ev):
            if ev.finished:
                terminal[rid] = True
                outcomes[rid] = (
                    "error:" + ev.error.split(":")[0]
                    if ev.error
                    else (ev.finish_reason or "stop")
                )
        return on_event

    t0 = time.monotonic()
    n = 0
    try:
        while time.monotonic() - t0 < seconds:
            n += 1
            rid = f"mem-{n}"
            # every ~4th request is a hog (large token budget -> large
            # page claim); the rest are short interactive shapes.  The
            # random TAIL varies content, not length — constant shapes
            # keep the run compile-free after the warmers above
            hog = n % 4 == 0
            req = Request(
                id=rid,
                prompt_tokens=_MEM_SOAK_PREFIXES[n % 2]
                + [rng.randrange(4, 260) for _ in range(4)],
                sampling=SamplingParams(
                    max_tokens=rng.randrange(60, 90) if hog
                    else rng.randrange(4, 12),
                    seed=n,
                ),
                stop_token_ids=tok.eos_ids,
            )
            terminal[rid] = False
            loop.submit(req, on_event_for(rid))
            time.sleep(rng.uniform(0.0, 0.04))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not all(terminal.values()):
            time.sleep(0.1)
        probe_done = [False]
        loop.submit(
            Request(
                id="final-probe", prompt_tokens=[5, 6, 7, 8],
                sampling=SamplingParams(max_tokens=2),
                stop_token_ids=tok.eos_ids,
            ),
            lambda ev: probe_done.__setitem__(0, ev.finished or probe_done[0]),
        )
        pdeadline = time.monotonic() + 30.0
        while time.monotonic() < pdeadline and not probe_done[0]:
            time.sleep(0.05)
    finally:
        loop.stop(join=False)

    stuck = sorted(r for r, done in terminal.items() if not done)
    counts: dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    stats = loop.stats()
    return {
        "submitted": n,
        "stuck": stuck,
        "outcomes": counts,
        "healthy_after": probe_done[0],
        "stats": stats,
        "tiering_moved": bool(
            stats["host_pool"]
            and stats["host_pool"]["spilled_pages"] > 0
            and (
                stats["host_pool"]["restored_pages"] > 0
                or stats["resumes"] > 0
            )
        ),
    }


def run_crash(seconds: float = 10.0, seed: int = 42,
              crash_every: float = 1.5) -> dict:
    """ISSUE 11 scenario: a runner dies mid-stream, over and over.

    Two engine loops share one set of weights: the ACTIVE loop takes
    seeded greedy traffic and is crash-drained (near-zero drain window —
    in-flight work survives only by snapshot export) every
    ``crash_every`` seconds with the STANDBY loop as the migration
    target; a fresh active loop replaces it and the cycle repeats.
    Clients accumulate tokens across the migration.

    Exit contract: **zero stuck requests**, at least one real migration,
    and — the crash-tolerance headline — every migrated greedy request's
    combined token stream (active-loop part + standby-loop continuation)
    is BIT-IDENTICAL to an uninterrupted reference run: no duplicated,
    missing, or diverged tokens."""
    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.migration import wire_to_snapshot
    from helix_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def build_engine():
        return Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=4, page_size=4, num_pages=256,
                max_pages_per_seq=64, max_prefill_len=64,
                attn_backend="reference", eos_token_ids=tok.eos_ids,
            ),
        )

    rng = random.Random(seed)
    tokens: dict[str, list] = {}     # rid -> combined token stream
    terminal: dict[str, bool] = {}
    outcomes: dict[str, str] = {}
    migrated: set = set()
    prompts: dict[str, tuple] = {}   # rid -> (prompt, max_tokens)

    def on_event_for(rid):
        def on_event(ev):
            if ev.token_id >= 0:
                tokens[rid].append(ev.token_id)
            if ev.finished and not ev.error:
                terminal[rid] = True
                outcomes[rid] = ev.finish_reason or "stop"
            elif ev.finished and ev.error:
                if ev.error.startswith("migrated"):
                    migrated.add(rid)   # continuation lands via standby
                else:
                    terminal[rid] = True
                    outcomes[rid] = "error:" + ev.error.split(":")[0]
        return on_event

    standby = EngineLoop(build_engine(), "standby").start()

    def exporter(wire):
        snap = wire_to_snapshot(wire)
        res: list = []
        standby.submit_import(
            snap, on_event_for(snap.request_id),
            on_result=lambda e, c: res.append(e),
        )
        deadline = time.monotonic() + 30.0
        while not res and time.monotonic() < deadline:
            time.sleep(0.005)
        if not res or res[0] is not None:
            raise RuntimeError(f"standby rejected import: {res}")
        return "standby"

    t0 = time.monotonic()
    n = 0
    crashes = 0
    try:
        while time.monotonic() - t0 < seconds:
            active = EngineLoop(
                build_engine(), f"active-{crashes}"
            ).start()
            active.exporter = exporter
            cycle_end = min(
                time.monotonic() + crash_every, t0 + seconds
            )
            while time.monotonic() < cycle_end:
                n += 1
                rid = f"crash-{n}"
                prompt = [rng.randrange(4, 260)
                          for _ in range(rng.randrange(6, 24))]
                max_toks = rng.randrange(40, 120)
                prompts[rid] = (prompt, max_toks)
                tokens[rid] = []
                terminal[rid] = False
                active.submit(
                    Request(
                        id=rid, prompt_tokens=prompt,
                        sampling=SamplingParams(
                            temperature=0.0, max_tokens=max_toks,
                        ),
                        stop_token_ids=tok.eos_ids,
                    ),
                    on_event_for(rid),
                )
                time.sleep(rng.uniform(0.005, 0.04))
            # crash: near-zero drain — survivors live or die by export
            crashes += 1
            active.stop(drain=0.01, join=True)
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline and not all(terminal.values()):
            time.sleep(0.1)
    finally:
        standby.stop(join=False)

    stuck = sorted(r for r, done in terminal.items() if not done)
    # bit-identity: every migrated request's combined stream must equal
    # an uninterrupted reference run of the same prompt
    ref_engine = build_engine()
    mismatches = []
    for rid in sorted(migrated):
        if rid in stuck or outcomes.get(rid, "").startswith("error"):
            continue
        prompt, max_toks = prompts[rid]
        ref = Request(
            id=f"ref-{rid}", prompt_tokens=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=max_toks),
            stop_token_ids=tok.eos_ids,
        )
        ref_engine.add_request(ref)
        while not ref.finished:
            ref_engine.step()
        if tokens[rid] != ref.output_tokens:
            mismatches.append(rid)
    counts: dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    return {
        "submitted": n,
        "crashes": crashes,
        "migrated": len(migrated),
        "stuck": stuck,
        "mismatches": mismatches,
        "outcomes": counts,
        "healthy_after": not stuck,
        "stats": standby.stats(),
    }


def run_scale(seconds: float = 10.0, seed: int = 42,
              scale_every: float = 2.0) -> dict:
    """ISSUE 12 scenario: the autoscaler keeps scaling the cluster down
    under load.

    A permanent "floor" loop and a rotating "scaled" loop share traffic
    round-robin.  Every ``scale_every`` seconds the autoscaler's D6 arm
    fires on the scaled loop: it drains GRACEFULLY (a real drain window,
    unlike run_crash's near-zero one) with the floor loop as its
    migration target, its thread exits (the host would now be
    terminated), and a replacement is "provisioned".  Clients accumulate
    tokens across every migration.

    Exit contract: **zero stuck requests**, zero lost tokens — every
    migrated greedy stream's combined tokens are BIT-IDENTICAL to an
    uninterrupted reference run — and at least one request actually
    rode the migration path (a drain window long enough to finish
    everything would prove nothing)."""
    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.migration import wire_to_snapshot
    from helix_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def build_engine():
        return Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=4, page_size=4, num_pages=256,
                max_pages_per_seq=64, max_prefill_len=64,
                attn_backend="reference", eos_token_ids=tok.eos_ids,
            ),
        )

    rng = random.Random(seed)
    tokens: dict[str, list] = {}
    terminal: dict[str, bool] = {}
    outcomes: dict[str, str] = {}
    migrated: set = set()
    prompts: dict[str, tuple] = {}

    def on_event_for(rid):
        def on_event(ev):
            if ev.token_id >= 0:
                tokens[rid].append(ev.token_id)
            if ev.finished and not ev.error:
                terminal[rid] = True
                outcomes[rid] = ev.finish_reason or "stop"
            elif ev.finished and ev.error:
                if ev.error.startswith("migrated"):
                    migrated.add(rid)   # continuation lands on floor
                else:
                    terminal[rid] = True
                    outcomes[rid] = "error:" + ev.error.split(":")[0]
        return on_event

    floor = EngineLoop(build_engine(), "floor").start()

    def exporter(wire):
        snap = wire_to_snapshot(wire)
        res: list = []
        floor.submit_import(
            snap, on_event_for(snap.request_id),
            on_result=lambda e, c: res.append(e),
        )
        deadline = time.monotonic() + 30.0
        while not res and time.monotonic() < deadline:
            time.sleep(0.005)
        if not res or res[0] is not None:
            raise RuntimeError(f"floor rejected import: {res}")
        return "floor"

    t0 = time.monotonic()
    n = 0
    scale_downs = 0
    try:
        while time.monotonic() - t0 < seconds:
            # "scale up": a replacement node joins the pool
            scaled = EngineLoop(
                build_engine(), f"scaled-{scale_downs}"
            ).start()
            scaled.exporter = exporter
            pool = [floor, scaled]
            cycle_end = min(
                time.monotonic() + scale_every, t0 + seconds
            )
            while time.monotonic() < cycle_end:
                n += 1
                rid = f"scale-{n}"
                prompt = [rng.randrange(4, 260)
                          for _ in range(rng.randrange(6, 24))]
                max_toks = rng.randrange(40, 120)
                prompts[rid] = (prompt, max_toks)
                tokens[rid] = []
                terminal[rid] = False
                pool[n % 2].submit(
                    Request(
                        id=rid, prompt_tokens=prompt,
                        sampling=SamplingParams(
                            temperature=0.0, max_tokens=max_toks,
                        ),
                        stop_token_ids=tok.eos_ids,
                    ),
                    on_event_for(rid),
                )
                time.sleep(rng.uniform(0.005, 0.04))
            # D6: graceful drain-then-terminate — a REAL window (short
            # requests finish in place; long ones migrate), then the
            # thread must be down before the "host" is reclaimed
            scale_downs += 1
            scaled.stop(drain=0.5, join=True)
            t = getattr(scaled, "_thread", None)
            assert t is None or not t.is_alive(), (
                "scale-down left the drained loop running"
            )
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline and not all(terminal.values()):
            time.sleep(0.1)
    finally:
        floor.stop(join=False)

    stuck = sorted(r for r, done in terminal.items() if not done)
    ref_engine = build_engine()
    mismatches = []
    lost_tokens = 0
    for rid in sorted(migrated):
        if rid in stuck or outcomes.get(rid, "").startswith("error"):
            continue
        prompt, max_toks = prompts[rid]
        ref = Request(
            id=f"ref-{rid}", prompt_tokens=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=max_toks),
            stop_token_ids=tok.eos_ids,
        )
        ref_engine.add_request(ref)
        while not ref.finished:
            ref_engine.step()
        if tokens[rid] != ref.output_tokens:
            mismatches.append(rid)
            lost_tokens += max(
                0, len(ref.output_tokens) - len(tokens[rid])
            )
    counts: dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    return {
        "submitted": n,
        "scale_downs": scale_downs,
        "migrated": len(migrated),
        "stuck": stuck,
        "mismatches": mismatches,
        "lost_tokens": lost_tokens,
        "outcomes": counts,
        "healthy_after": not stuck,
        "stats": floor.stats(),
    }


def run_disagg(seconds: float = 10.0, seed: int = 42) -> dict:
    """ISSUE 14 scenario: disaggregated prefill/decode under injected
    transfer faults.

    A prefill-pool loop takes every prompt with a staged
    export-at-prefill-completion; a shipping worker (the HTTP handler's
    stand-in) ships each snapshot to the decode-pool loop through a
    REAL ``PeerShipper`` — so the armed ``transfer`` fault rules
    (drop / corrupt / slow / partial) hit the exact production retry/
    backoff/checksum path.  A confirmed ship aborts the local request
    and the decode loop continues it; a failed ship degrades to local
    serving on the prefill loop (the bottom rung of the ladder).

    Exit contract: **zero stuck requests**, **zero wrong tokens** —
    every request's committed stream (snapshot prior + decode-pool
    continuation for handoffs, the local stream otherwise) is
    BIT-IDENTICAL to an uninterrupted colocated reference — and the
    fault mix actually exercised ≥1 handoff AND ≥1 fallback."""
    import queue as _queue
    import threading

    import jax

    from helix_tpu.engine.engine import (
        Engine,
        EngineConfig,
        Request,
        SnapshotError,
    )
    from helix_tpu.testing import faults
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.migration import (
        PeerShipper,
        XferConfig,
        wire_to_snapshot,
    )
    from helix_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def build_engine():
        return Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=4, page_size=4, num_pages=256,
                max_pages_per_seq=64, max_prefill_len=64,
                attn_backend="reference", eos_token_ids=tok.eos_ids,
            ),
        )

    faults.arm(
        seed=seed,
        rules=[
            {"point": "transfer", "peer": "decode", "mode": "drop",
             "p": 0.2},
            {"point": "transfer", "peer": "decode", "mode": "corrupt",
             "p": 0.15, "page": 1},
            {"point": "transfer", "peer": "decode", "mode": "partial",
             "p": 0.1},
            {"point": "transfer", "peer": "decode", "mode": "slow",
             "p": 0.2, "delay": 0.02},
        ],
    )

    rng = random.Random(seed)
    # per-request committed streams: local (prefill-pool) events and
    # remote (decode-pool) events are kept APART — a handoff commits
    # snapshot-prior + remote, a fallback commits local (exactly the
    # HTTP handler's exactly-once discipline)
    local: dict[str, list] = {}
    remote: dict[str, list] = {}
    prior: dict[str, list] = {}      # rid -> snapshot prior output tokens
    local_done: dict[str, str] = {}
    remote_done: dict[str, str] = {}
    handed: set = set()
    prompts: dict[str, tuple] = {}
    fallbacks = [0]

    def on_local(rid):
        def on_event(ev):
            if ev.token_id >= 0:
                local[rid].append(ev.token_id)
            if ev.finished:
                local_done[rid] = (
                    "error:" + ev.error.split(":")[0] if ev.error
                    else (ev.finish_reason or "stop")
                )
        return on_event

    def on_remote(rid):
        def on_event(ev):
            if ev.token_id >= 0:
                remote[rid].append(ev.token_id)
            if ev.finished:
                remote_done[rid] = (
                    "error:" + ev.error.split(":")[0] if ev.error
                    else (ev.finish_reason or "stop")
                )
        return on_event

    prefill = EngineLoop(build_engine(), "prefill-pool").start()
    decode = EngineLoop(build_engine(), "decode-pool").start()

    class _Resp:
        def __init__(self, status_code):
            self.status_code = status_code

    def fake_post(url, json=None, headers=None, timeout=None):
        """The decode runner's /v1/migrate/import, in-process: decode +
        engine-thread validation with the real pre-mutation checksum
        path, answering the typed statuses the HTTP surface would."""
        try:
            snap = wire_to_snapshot(json)
        except SnapshotError:
            return _Resp(422)
        res: list = []
        decode.submit_import(
            snap, on_remote(snap.request_id),
            on_result=lambda e, c: res.append((e, c)),
        )
        deadline = time.monotonic() + 30.0
        while not res and time.monotonic() < deadline:
            time.sleep(0.002)
        if not res:
            return _Resp(504)
        err, code = res[0]
        if err is None:
            return _Resp(200)
        return _Resp(503 if code == "shutting_down" else 422)

    ship_q: "_queue.Queue" = _queue.Queue()
    stop_shipping = threading.Event()

    def shipping_worker():
        xfer = XferConfig(
            attempt_timeout=5.0, max_attempts=2,
            backoff_base=0.01, backoff_cap=0.05, deadline=10.0,
        )
        while not stop_shipping.is_set():
            try:
                rid, wire = ship_q.get(timeout=0.05)
            except _queue.Empty:
                continue
            shipper = PeerShipper(
                targets=[{"id": "decode", "address": "http://decode"}],
                config=xfer, post=fake_post, prefill=True,
            )
            try:
                try:
                    shipper(wire)
                except Exception:  # noqa: BLE001 — the ladder: serve locally
                    fallbacks[0] += 1
                    continue
                prior[rid] = [
                    int(t) for t in wire.get("output_tokens", [])
                ]
                handed.add(rid)
                prefill.abort(rid)
            finally:
                # task_done AFTER the outcome is recorded: settled()
                # keys off unfinished_tasks, so an in-flight ship (the
                # worker popped it but is still retrying) still counts
                # as pending
                ship_q.task_done()

    shipper_t = threading.Thread(target=shipping_worker, daemon=True)
    shipper_t.start()

    def on_export_for(rid):
        def cb(kind, wire):
            if kind == "snapshot":
                ship_q.put((rid, wire))
            # completed/local/gone: the stream stays on the prefill loop
        return cb

    t0 = time.monotonic()
    n = 0
    try:
        while time.monotonic() - t0 < seconds:
            n += 1
            rid = f"disagg-{n}"
            prompt = [rng.randrange(4, 260)
                      for _ in range(rng.randrange(8, 28))]
            max_toks = rng.randrange(30, 90)
            prompts[rid] = (prompt, max_toks)
            local[rid] = []
            remote[rid] = []
            prefill.stage_disagg_export(rid, on_export_for(rid))
            prefill.submit(
                Request(
                    id=rid, prompt_tokens=prompt,
                    sampling=SamplingParams(
                        temperature=0.0, max_tokens=max_toks,
                    ),
                    stop_token_ids=tok.eos_ids,
                ),
                on_local(rid),
            )
            time.sleep(rng.uniform(0.01, 0.05))

        def settled(rid):
            if rid in handed:
                return rid in remote_done
            # not handed off (yet): local finish settles it once every
            # queued AND in-flight ship has resolved (unfinished_tasks
            # covers a popped-but-still-retrying ship that could yet
            # flip this request to handed)
            return rid in local_done and ship_q.unfinished_tasks == 0

        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline and not all(
            settled(r) for r in prompts
        ):
            time.sleep(0.1)
    finally:
        stop_shipping.set()
        prefill.stop(join=False)
        decode.stop(join=False)
        faults.disarm()

    stuck = sorted(r for r in prompts if not settled(r))
    ref_engine = build_engine()
    mismatches = []
    for rid in sorted(prompts):
        if rid in stuck:
            continue
        if rid in handed:
            committed = prior.get(rid, []) + remote[rid]
            outcome = remote_done.get(rid, "")
        else:
            committed = local[rid]
            outcome = local_done.get(rid, "")
        if outcome.startswith("error"):
            mismatches.append((rid, "errored: " + outcome))
            continue
        prompt, max_toks = prompts[rid]
        ref = Request(
            id=f"ref-{rid}", prompt_tokens=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=max_toks),
            stop_token_ids=tok.eos_ids,
        )
        ref_engine.add_request(ref)
        while not ref.finished:
            ref_engine.step()
        if committed != ref.output_tokens:
            mismatches.append((rid, "diverged"))
    counts: dict[str, int] = {
        "handoff": len(handed),
        "local": len(prompts) - len(handed),
    }
    return {
        "submitted": n,
        "handoffs": len(handed),
        "fallbacks": fallbacks[0],
        "migrated": len(handed),
        "stuck": stuck,
        "mismatches": mismatches,
        "outcomes": counts,
        "healthy_after": not stuck,
        "stats": decode.stats(),
    }


def run_multihost(seconds: float = 10.0, seed: int = 42) -> dict:
    """ISSUE 17 scenario: the plan-broadcast leader dies, over and over.

    A leader + hot standby + ordinary follower mesh serves a continuous
    mixed stream (greedy AND seeded sampled).  Every few seconds the
    leader is killed mid-stream: the standby is promoted through the
    filestore checkpoint + CommandLog-tail replay (the real
    ``promote_follower`` path, digest-verified), the surviving follower
    re-points its feed across the handoff record, and a FRESH standby
    bootstraps from the handoff checkpoint so the mesh is always one
    kill away from another takeover.

    Exit contract: **zero stuck requests**, ≥1 real takeover, and every
    request's committed stream on the final leader AND on the surviving
    follower replica is BIT-IDENTICAL to an uninterrupted single-host
    reference run (explicit per-request sampling seeds make the
    reference exact across takeovers)."""
    import tempfile

    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.multihost_serving import (
        CheckpointStore,
        FollowerLoop,
        LocalFeed,
        PlanLeader,
        ResyncRequired,
        promote_follower,
    )

    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def build_engine():
        return Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=4, page_size=4, num_pages=256,
                max_pages_per_seq=64, max_prefill_len=64,
                attn_backend="reference",
                # failover parks in-flight requests at the takeover
                # boundary through the host tier
                host_pool_bytes=1 << 22,
            ),
        )

    rng = random.Random(seed)
    prompts: dict[str, tuple] = {}   # rid -> (prompt, max_toks, temp, seed)
    takeover_ms: list = []
    resyncs = [0]

    def pump(f):
        while f.run_once(timeout=0.0):
            pass

    prev_ckpt = os.environ.get("HELIX_MH_CHECKPOINT_SECONDS")
    os.environ["HELIX_MH_CHECKPOINT_SECONDS"] = "0.05"
    tmp = tempfile.mkdtemp(prefix="mh-soak-")
    try:
        store = CheckpointStore(tmp)
        leader = PlanLeader(build_engine(), checkpoint_store=store,
                            name="m")
        standby = FollowerLoop(build_engine(), LocalFeed(leader, "sb-0"),
                               name="m", standby=True,
                               checkpoint_store=store)
        peer = FollowerLoop(build_engine(), LocalFeed(leader, "peer"),
                            name="m", checkpoint_store=store)
        kill_every = max(1.5, seconds / 3.0)
        t0 = time.monotonic()
        next_kill = t0 + kill_every
        n = 0
        gen = 0
        while time.monotonic() - t0 < seconds:
            if n == 0 or rng.random() < 0.5:
                n += 1
                rid = f"mh-{n}"
                prompt = [rng.randrange(4, 260)
                          for _ in range(rng.randrange(4, 20))]
                max_toks = rng.randrange(8, 40)
                temp = rng.choice([0.0, 0.8])
                sp_seed = rng.randrange(1 << 30)
                prompts[rid] = (prompt, max_toks, temp, sp_seed)
                leader.add_request(Request(
                    id=rid, prompt_tokens=prompt,
                    sampling=SamplingParams(
                        temperature=temp, max_tokens=max_toks,
                        seed=sp_seed,
                    ),
                ))
            if leader.engine.has_work():
                leader.step()
            leader.checkpoint_tick()
            pump(standby)
            try:
                pump(peer)
            except ResyncRequired:
                # the operator ladder: behind the handoff boundary ->
                # full resync (fresh replica bootstraps from the ring)
                resyncs[0] += 1
                peer = FollowerLoop(
                    build_engine(), LocalFeed(leader, "peer"),
                    name="m", checkpoint_store=store,
                )
                pump(peer)
            if time.monotonic() >= next_kill:
                # KILL the leader: it publishes nothing further.  The
                # standby takes over through checkpoint + log tail.
                store.flush(10.0)
                gen += 1
                leader = promote_follower(standby, store=store,
                                          name="m")
                takeover_ms.append(float(leader.takeover_ms))
                try:
                    peer.feed.retarget(leader)
                    pump(peer)
                except ResyncRequired:
                    resyncs[0] += 1
                    peer = FollowerLoop(
                        build_engine(), LocalFeed(leader, "peer"),
                        name="m", checkpoint_store=store,
                    )
                    pump(peer)
                standby = FollowerLoop(
                    build_engine(), LocalFeed(leader, f"sb-{gen}"),
                    name="m", standby=True, checkpoint_store=store,
                )
                pump(standby)   # bootstraps from the handoff checkpoint
                next_kill = time.monotonic() + kill_every
        # drain: finish everything on the final leader, replicas follow
        deadline = time.monotonic() + 90.0
        while leader.engine.has_work() and time.monotonic() < deadline:
            leader.step()
            leader.checkpoint_tick()
            pump(standby)
            pump(peer)
        pump(standby)
        pump(peer)
        mh = leader.mh_stats()
    finally:
        if prev_ckpt is None:
            os.environ.pop("HELIX_MH_CHECKPOINT_SECONDS", None)
        else:
            os.environ["HELIX_MH_CHECKPOINT_SECONDS"] = prev_ckpt

    stuck = sorted(
        rid for rid in prompts
        if rid not in leader.engine._requests
        or not leader.engine._requests[rid].finished
    )
    # bit-identity: solo replay of every request on a fresh engine —
    # explicit seeds mean batching and takeovers cannot change streams
    ref_engine = build_engine()
    mismatches = []
    for rid in sorted(prompts):
        if rid in stuck:
            continue
        prompt, max_toks, temp, sp_seed = prompts[rid]
        ref = Request(
            id=f"ref-{rid}", prompt_tokens=list(prompt),
            sampling=SamplingParams(temperature=temp,
                                    max_tokens=max_toks, seed=sp_seed),
        )
        ref_engine.add_request(ref)
        while not ref.finished:
            ref_engine.step()
        got = leader.engine._requests[rid].output_tokens
        if got != ref.output_tokens:
            mismatches.append((rid, "leader diverged"))
        pr = peer.engine._requests.get(rid)
        if pr is not None and pr.output_tokens != ref.output_tokens:
            mismatches.append((rid, "follower replica diverged"))
    counts: dict[str, int] = {"finished": len(prompts) - len(stuck)}
    return {
        "submitted": n,
        "takeovers": len(takeover_ms),
        "takeover_blackout_ms": takeover_ms,
        "checkpoints": int(mh.get("checkpoints_captured", 0)),
        "peer_handoffs": int(peer.handoffs),
        "peer_resyncs": resyncs[0],
        "migrated": len(prompts) - len(stuck),
        "stuck": stuck,
        "mismatches": mismatches,
        "outcomes": counts,
        "healthy_after": not stuck and not mismatches,
        "stats": mh,
    }


def run_corruption(seconds: float = 10.0, seed: int = 42) -> dict:
    """Silent output corruption on one of two runners (ISSUE 19).

    Two EngineLoops serve the same model behind a corruption-aware
    router; after golden minting, a ``corrupt_output`` fault silently
    offsets every token one runner emits — latency and throughput look
    perfectly healthy.  Per-runner canary probers run on a short
    cadence under sustained seeded foreground load while heartbeats
    federate their health into the router.

    Exit contract: **zero stuck requests**, the canary detects the
    corruption within its rung threshold worth of probe rounds, and
    every foreground request served AFTER detection streams
    bit-identical to the healthy runner's golden output (the router
    steered around the corrupted peer)."""
    import threading

    import jax

    from helix_tpu.control.router import InferenceRouter, RouterPolicy
    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.obs.canary import CANARY_FAILING, CanaryProber
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.registry import ServedModel
    from helix_tpu.serving.tokenizer import ByteTokenizer
    from helix_tpu.testing import faults

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32", name="m1")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def build(side):
        engine = Engine(
            cfg, params,
            EngineConfig(
                max_decode_batch=4, page_size=4, num_pages=256,
                max_pages_per_seq=32, max_prefill_len=64,
                attn_backend="reference", eos_token_ids=tok.eos_ids,
            ),
        )
        loop = EngineLoop(
            engine, f"m1@{side}", max_queue_seconds=30.0,
            max_queue_depth=64, max_queued_tokens=8192,
        ).start()
        served = ServedModel(
            name="m1", loop=loop, tokenizer=tok, context_length=256
        )
        # short probes (4 tokens) keep the probe round cheap so
        # detection lands early in the soak window
        prober = CanaryProber(
            runner_id=side, models_fn=lambda s=served: [s],
            interval=9999, failures=2, backoff=9999,
            probe_tokens=4, probe_timeout=60.0,
        )
        return {"loop": loop, "served": served, "prober": prober}

    sides = {s: build(s) for s in ("r1", "r2")}
    for s in sides.values():
        s["prober"].mint_models([s["served"]])

    router = InferenceRouter(policy=RouterPolicy(canary_avoid=True))

    def beat(side):
        router.upsert_from_heartbeat(
            side, models=["m1"], profile_name="p",
            profile_status="running",
            canary=sides[side]["prober"].summary(),
        )

    beat("r1")
    beat("r2")

    # a small fixed prompt set so every foreground stream has a golden
    # to compare against (greedy + fixed prompts = deterministic)
    prompts = [
        [10 + 3 * j for j in range(8)],
        [40 + 5 * j for j in range(12)],
        [200 + j for j in range(6)],
    ]

    def collect(loop, rid, prompt):
        done = threading.Event()
        toks: list = []
        err = [None]

        def cb(ev):
            if ev.error:
                err[0] = ev.error
            elif ev.token_id >= 0:
                toks.append(ev.token_id)
            if ev.finished:
                done.set()

        loop.submit(
            Request(id=rid, prompt_tokens=list(prompt),
                    sampling=SamplingParams(
                        temperature=0.0, max_tokens=12),
                    stop_token_ids=tok.eos_ids),
            cb,
        )
        return done, toks, err

    # goldens from the healthy runner BEFORE the fault is armed
    goldens = []
    for i, p in enumerate(prompts):
        done, toks, err = collect(sides["r1"]["loop"], f"golden-{i}", p)
        assert done.wait(120) and err[0] is None
        goldens.append(list(toks))

    faults.arm(seed=seed, rules=[{
        "point": "corrupt_output", "engine": "m1@r2", "offset": 1,
    }])

    stop = threading.Event()
    detection = {"rounds": 0, "detected_at": 0}

    def canary_pump():
        while not stop.is_set():
            for side in ("r1", "r2"):
                sides[side]["prober"].probe_round()
                beat(side)
            detection["rounds"] += 1
            if (
                not detection["detected_at"]
                and sides["r2"]["prober"].state == CANARY_FAILING
            ):
                detection["detected_at"] = detection["rounds"]
            if stop.wait(0.25):
                return

    pump = threading.Thread(target=canary_pump, daemon=True)
    pump.start()

    rng = random.Random(seed)
    inflight = []  # (rid, prompt_idx, runner, done, toks, err, post)
    t0 = time.monotonic()
    n = 0
    detected_at_wall = [0.0]
    try:
        while True:
            now = time.monotonic()
            if detection["detected_at"] and not detected_at_wall[0]:
                detected_at_wall[0] = now
            if now - t0 >= seconds:
                # the probe cadence shares the device with foreground
                # load; extend the soak (bounded) until detection has
                # happened AND at least a short post-detection window
                # has exercised the steer — otherwise the bit-identity
                # assertion would be vacuous on a slow machine
                if not detection["detected_at"]:
                    if now - t0 > seconds + 60.0:
                        break
                elif now - detected_at_wall[0] > 2.0:
                    break
            n += 1
            pi = rng.randrange(len(prompts))
            st = router.pick_runner("m1", trace_id=f"soak-{n}")
            assert st is not None
            post = bool(detection["detected_at"])
            done, toks, err = collect(
                sides[st.id]["loop"], f"req-{n}", prompts[pi]
            )
            inflight.append(
                (f"req-{n}", pi, st.id, done, toks, err, post)
            )
            time.sleep(rng.uniform(0.0, 0.05))
        stop.set()
        pump.join(timeout=120)
        deadline = time.monotonic() + 90.0
        for _, _, _, done, _, _, _ in inflight:
            done.wait(max(0.0, deadline - time.monotonic()))
    finally:
        stop.set()
        faults.disarm()
        for s in sides.values():
            s["loop"].stop(join=False)

    stuck = sorted(
        rid for rid, _, _, done, _, _, _ in inflight
        if not done.is_set()
    )
    corrupted_before = wrong_after = served_r2_after = sheds = 0
    for rid, pi, runner, done, toks, err, post in inflight:
        if rid in stuck:
            continue
        if err[0] is not None:
            # a shed is a CAPACITY outcome (bounded admission doing its
            # job under the steered load) — not a correctness violation
            sheds += 1
            continue
        ok = list(toks) == goldens[pi]
        if post:
            if runner == "r2":
                served_r2_after += 1
            if not ok:
                wrong_after += 1
        elif not ok:
            corrupted_before += 1
    counts = {
        "finished": len(inflight) - len(stuck),
        "sheds": sheds,
        "corrupted_before_detection": corrupted_before,
    }
    detected = bool(detection["detected_at"])
    return {
        "submitted": n,
        "stuck": stuck,
        "outcomes": counts,
        "stats": {s: sides[s]["loop"].stats() for s in sides},
        "healthy_after": not stuck and detected,
        "detected": detected,
        "detection_rounds": detection["detected_at"],
        "probe_rounds": detection["rounds"],
        "r2_state": sides["r2"]["prober"].state,
        "corrupted_before_detection": corrupted_before,
        "wrong_after_detection": wrong_after,
        "served_r2_after_detection": served_r2_after,
        "route_canary_avoided": router.route_canary_avoided,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--step-fault-p", type=float, default=0.02)
    ap.add_argument(
        "--scenario",
        choices=("faults", "memory", "crash", "scale", "disagg",
                 "multihost", "corruption"),
        default="faults",
        help="faults: injected step/dispatch faults (ISSUE 2); memory: "
        "sustained KV exhaustion against the tiering/preemption ladder "
        "(ISSUE 6); crash: repeated runner crash-drains with snapshot "
        "migration to a standby — combined streams must be bit-identical "
        "to uninterrupted runs (ISSUE 11); scale: repeated autoscaler "
        "scale-downs (graceful drain-then-terminate) under load — zero "
        "stuck, zero lost tokens via the migration path (ISSUE 12); "
        "disagg: prefill/decode handoffs under injected transfer faults "
        "(drop/corrupt/slow/partial) — zero stuck, zero wrong tokens, "
        "every failure degrades to local serving (ISSUE 14); "
        "multihost: repeated plan-leader kills with digest-verified "
        "standby takeover through the filestore checkpoint — zero "
        "stuck, every stream bit-identical across handoffs (ISSUE 17); "
        "corruption: silent output corruption on one of two runners — "
        "the correctness canary detects within bounded probe rounds "
        "and the router steers foreground to the healthy peer, zero "
        "stuck (ISSUE 19)",
    )
    args = ap.parse_args(argv)
    if args.scenario == "memory":
        res = run_memory_pressure(seconds=args.seconds, seed=args.seed)
    elif args.scenario == "crash":
        res = run_crash(seconds=args.seconds, seed=args.seed)
    elif args.scenario == "scale":
        res = run_scale(seconds=args.seconds, seed=args.seed)
    elif args.scenario == "disagg":
        res = run_disagg(seconds=args.seconds, seed=args.seed)
    elif args.scenario == "multihost":
        res = run_multihost(seconds=args.seconds, seed=args.seed)
    elif args.scenario == "corruption":
        res = run_corruption(seconds=args.seconds, seed=args.seed)
    else:
        res = run_soak(
            seconds=args.seconds, seed=args.seed,
            step_fault_p=args.step_fault_p,
        )
    print(f"submitted:     {res['submitted']}")
    print(f"outcomes:      {res['outcomes']}")
    print(f"loop stats:    {res['stats']}")
    print(f"healthy after: {res['healthy_after']}")
    if res["stuck"]:
        print(f"STUCK REQUESTS: {res['stuck']}", file=sys.stderr)
        return 1
    if not res["healthy_after"]:
        print("ENGINE UNHEALTHY AFTER SOAK", file=sys.stderr)
        return 1
    if args.scenario == "memory" and not res.get("tiering_moved"):
        print("KV TIERING COUNTERS DID NOT MOVE", file=sys.stderr)
        return 1
    if args.scenario == "multihost":
        if res.get("mismatches"):
            print(
                f"STREAMS DIVERGED ACROSS TAKEOVER: {res['mismatches']}",
                file=sys.stderr,
            )
            return 1
        if not res.get("takeovers"):
            print("NO LEADER KILL ACTUALLY EXERCISED A TAKEOVER",
                  file=sys.stderr)
            return 1
        blackouts = ", ".join(
            f"{ms:.0f}" for ms in res["takeover_blackout_ms"]
        )
        print(
            f"multihost takeovers: {res['takeovers']} "
            f"(blackout ms: [{blackouts}]), checkpoints: "
            f"{res['checkpoints']}, peer handoffs: "
            f"{res['peer_handoffs']} (resyncs: {res['peer_resyncs']}) — "
            "all streams bit-identical to an uninterrupted run"
        )
    if args.scenario in ("crash", "scale", "disagg"):
        if res.get("mismatches"):
            print(
                f"MIGRATED STREAMS DIVERGED: {res['mismatches']} "
                f"(lost_tokens={res.get('lost_tokens', '?')})",
                file=sys.stderr,
            )
            return 1
        if not res.get("migrated"):
            print("NO REQUEST ACTUALLY MIGRATED", file=sys.stderr)
            return 1
        if args.scenario == "disagg" and not res.get("fallbacks"):
            print(
                "NO TRANSFER FAULT ACTUALLY FORCED A FALLBACK",
                file=sys.stderr,
            )
            return 1
        events = res.get(
            "crashes", res.get("scale_downs", res.get("handoffs"))
        )
        print(
            f"{args.scenario} events: {events}, migrated: "
            f"{res['migrated']} — zero lost tokens, all combined "
            "streams bit-identical to uninterrupted runs"
        )
    if args.scenario == "corruption":
        if not res.get("detected"):
            print("CORRUPTION NEVER DETECTED BY THE CANARY",
                  file=sys.stderr)
            return 1
        if res.get("wrong_after_detection"):
            print(
                "FOREGROUND SERVED WRONG TOKENS AFTER DETECTION: "
                f"{res['wrong_after_detection']}",
                file=sys.stderr,
            )
            return 1
        print(
            f"corruption detected in {res['detection_rounds']} probe "
            f"round(s) (r2 state: {res['r2_state']}); corrupted "
            f"foreground served pre-detection: "
            f"{res['corrupted_before_detection']}; picks steered "
            f"around the corrupted runner: {res['route_canary_avoided']}"
            " — all post-detection streams bit-identical to the "
            "healthy golden"
        )
    print("zero stuck requests — soak passed")
    return 0


if __name__ == "__main__":
    rc = main()
    # the engine-loop daemon thread may still be inside a JAX dispatch;
    # normal interpreter teardown then aborts (std::terminate) AFTER the
    # verdict printed, clobbering the exit code CI keys on.  Flush and
    # leave without running destructors.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
