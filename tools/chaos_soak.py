"""Randomized (but seeded) chaos soak of the in-process serving stack.

Builds a tiny engine + EngineLoop with admission bounds, arms the fault
injector with a probabilistic engine-step fault plus persistent poisoned
requests, then pumps seeded random traffic for N seconds.  The exit
assertion is the serving spine's core robustness contract: **zero stuck
requests** — every submission reaches a terminal event (tokens+finish,
quarantine eviction, shed, or timeout), the engine thread never dies, and
the loop keeps accepting work afterwards.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 10 --seed 42

Also imported by the slow lane of ``tests/test_chaos.py``.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
)


def run_soak(seconds: float = 10.0, seed: int = 42,
             step_fault_p: float = 0.02, poison_every: int = 7) -> dict:
    import jax

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import ModelConfig
    from helix_tpu.models.llama import init_params
    from helix_tpu.serving.engine_loop import EngineLoop
    from helix_tpu.serving.tokenizer import ByteTokenizer
    from helix_tpu.testing import faults

    tok = ByteTokenizer()
    cfg = ModelConfig.tiny(vocab_size=512, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = Engine(
        cfg, params,
        EngineConfig(
            max_decode_batch=4, page_size=4, num_pages=256,
            max_pages_per_seq=32, max_prefill_len=64,
            attn_backend="reference", eos_token_ids=tok.eos_ids,
        ),
    )
    faults.arm(
        seed=seed,
        rules=[
            # transient step faults: retry-once should absorb most
            {"point": "engine_step", "p": step_fault_p},
            # persistent poison: every step that schedules such a request
            # fails until quarantine evicts it
            {"point": "engine_step", "request_id_contains": "poison"},
        ],
    )
    loop = EngineLoop(
        engine, "soak", max_queue_seconds=20.0,
        max_queue_depth=32, max_queued_tokens=4096,
    ).start()

    rng = random.Random(seed)
    outcomes: dict[str, str] = {}
    terminal: dict[str, bool] = {}

    def on_event_for(rid):
        def on_event(ev):
            if ev.finished:
                terminal[rid] = True
                outcomes[rid] = (
                    "error:" + ev.error.split(":")[0]
                    if ev.error
                    else (ev.finish_reason or "stop")
                )
        return on_event

    t0 = time.monotonic()
    n = 0
    try:
        while time.monotonic() - t0 < seconds:
            n += 1
            rid = (
                f"poison-{n}" if n % poison_every == 0 else f"req-{n}"
            )
            req = Request(
                id=rid,
                prompt_tokens=[rng.randrange(4, 260)
                               for _ in range(rng.randrange(4, 48))],
                sampling=SamplingParams(
                    max_tokens=rng.randrange(2, 16), seed=n
                ),
                stop_token_ids=tok.eos_ids,
            )
            terminal[rid] = False
            loop.submit(req, on_event_for(rid))
            time.sleep(rng.uniform(0.0, 0.05))
        # drain: give every in-flight request time to reach a terminal
        # event (quarantine/shed/finish), then a final health probe
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not all(terminal.values()):
            time.sleep(0.1)
        faults.disarm()
        probe_done = [False]
        loop.submit(
            Request(
                id="final-probe", prompt_tokens=[5, 6, 7, 8],
                sampling=SamplingParams(max_tokens=2),
                stop_token_ids=tok.eos_ids,
            ),
            lambda ev: probe_done.__setitem__(0, ev.finished or probe_done[0]),
        )
        pdeadline = time.monotonic() + 30.0
        while time.monotonic() < pdeadline and not probe_done[0]:
            time.sleep(0.05)
    finally:
        faults.disarm()
        loop.stop(join=False)

    stuck = sorted(r for r, done in terminal.items() if not done)
    counts: dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    return {
        "submitted": n,
        "stuck": stuck,
        "outcomes": counts,
        "healthy_after": probe_done[0],
        "stats": loop.stats(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--step-fault-p", type=float, default=0.02)
    args = ap.parse_args(argv)
    res = run_soak(
        seconds=args.seconds, seed=args.seed,
        step_fault_p=args.step_fault_p,
    )
    print(f"submitted:     {res['submitted']}")
    print(f"outcomes:      {res['outcomes']}")
    print(f"loop stats:    {res['stats']}")
    print(f"healthy after: {res['healthy_after']}")
    if res["stuck"]:
        print(f"STUCK REQUESTS: {res['stuck']}", file=sys.stderr)
        return 1
    if not res["healthy_after"]:
        print("ENGINE UNHEALTHY AFTER SOAK", file=sys.stderr)
        return 1
    print("zero stuck requests — soak passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
