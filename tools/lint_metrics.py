#!/usr/bin/env python
"""Metric-name + exposition-drift linter for the helix serving spine.

Contracts, enforced repo-wide (wired into tier-1 via
``tests/test_observability.py``):

1. **Naming**: every metric-name string literal (``"helix_..."``) must
   be lowercase snake_case (``helix_[a-z0-9_]+``) with base-unit
   suffixes only — ``_total`` for counters, ``_seconds`` / ``_bytes``
   for units; ``_ms`` / ``_cnt``-style suffixes are rejected (the PR 1
   ``_ms`` allowlist is gone: those series are renamed to ``_seconds``).
2. **No ad-hoc exposition**: Prometheus text formatting (f-strings that
   build ``helix_...`` sample lines, or ``# TYPE`` literals) may exist
   ONLY inside ``helix_tpu/obs/`` — everything else feeds the shared
   registry.  PR 1/2 grew three hand-rolled ``/metrics`` builders that
   drifted apart; this keeps it at zero.
3. **One saturation schema**: the heartbeat saturation summary and the
   control plane's ``helix_cp_runner_saturation_*`` gauges both derive
   from ``helix_tpu.obs.flight.SATURATION_KEYS``.  The linter fails if
   either side stops importing the shared tuple, or if any hard-coded
   ``helix_cp_runner_saturation_<key>`` literal names a key outside it.
4. **Bounded tenant labels**: any metric emitted with a ``tenant``
   label must come from ``helix_tpu/obs/slo.py``'s bounded top-K
   accounting — the linter rejects ``helix_tenant_*`` /
   ``helix_slo_*`` / ``helix_cp_slo_*`` name literals and
   ``tenant``-labelled collector/metric calls anywhere else, so ad-hoc
   unbounded tenant label cardinality can't drift in later.  The
   federation sides (node agent emits, control plane consumes) must
   keep importing the shared ``TENANT_KEYS`` entry schema.
5. **One scheduler vocabulary**: ``helix_sched_*`` metric names and the
   scheduler-decision audit reasons (the ``SCHED_AUDIT_REASONS`` tuple)
   may only be minted by ``helix_tpu/serving/sched.py`` — everywhere
   else must import the shared constants, so the admission audit ring's
   reason vocabulary and the scheduler metric family each have exactly
   one owner.  The engine loop must keep building its scheduler through
   ``make_scheduler`` and the OpenAI surface must keep adopting
   ``CLASS_HEADER`` (the contracts 3/4 importer pattern).
6. **One migration vocabulary**: the cross-runner migration series —
   ``helix_migrations_*`` / ``helix_migration_*`` runner counters, the
   ``helix_cp_midstream_*`` failover counters and the
   ``helix_cp_runner_draining`` drain-state gauge — are minted ONLY by
   ``helix_tpu/serving/migration.py``; the runner metrics collector and
   the control plane must keep calling its collector helpers
   (``collect_runner_migration`` / ``collect_cp_migration``), the
   contracts 3/4/5 importer pattern.
7. **One compiled step entry point**: the engine's device step compiles
   through ONE lru-cached builder (``_build_ragged_step_fn``) plus the
   two grandfathered VL paths — a NEW ``@functools.lru_cache`` step
   builder anywhere under ``helix_tpu/engine/`` fails the build, so the
   trace zoo the ragged unification collapsed (six shape families ×
   their bucket grids) cannot regrow one helper at a time.  The
   ``helix_compiled_step_shapes`` gauge would expose it at runtime;
   this catches it at review time.
8. **One routing/autoscale vocabulary**: the control plane's placement
   and capacity series — ``helix_cp_route_*`` (scored routing, prefix
   affinity, saturation sheds) and ``helix_cp_autoscale_*`` (provision/
   drain/deprovision lifecycle) — are minted ONLY by
   ``helix_tpu/control/router.py`` and ``helix_tpu/control/compute.py``
   respectively; the control plane must keep calling their collector
   helpers (``collect_cp_routing`` / ``collect_cp_autoscale``), the
   contracts 3-6 importer pattern.
9. **Engine-loop host-sync discipline** (ISSUE 13): the asynchronous
   pipelined loop keeps every device fetch inside the engine's
   ``step_complete`` reconcile — ``serving/engine_loop.py`` itself must
   contain NO ``jax.device_get`` / ``block_until_ready`` /
   ``np.asarray`` call.  A future helper that quietly fetches per step
   would re-serialize the pipeline without failing any functional test;
   this fails the build instead.  A genuinely designated reconcile/emit
   site is allowlisted by carrying a ``host-sync-ok: <why>`` marker on
   the same line.
10. **One transfer/pool/filestore vocabulary** (ISSUE 14): the
   disaggregation families each have exactly one owner —
   ``helix_xfer_*`` (KV snapshot ship outcomes) is minted only by
   ``helix_tpu/serving/migration.py``, ``helix_filestore_kv_*`` (the
   persistent KV tier) only by ``helix_tpu/serving/kv_filestore.py``,
   and ``helix_cp_pool_*`` (pool roles + handoff outcomes) only by
   ``helix_tpu/control/router.py``.  The runner's scrape surface must
   keep calling ``collect_xfer`` + ``collect_filestore_kv`` and the
   control plane ``collect_cp_pools`` (the contracts 3-8 importer
   pattern).

11. **One adapter vocabulary** (ISSUE 15): the continuous multi-LoRA
   serving series — ``helix_adapter_*`` (HBM pool residency, loads/
   evictions/load-seconds, host-tier occupancy, prefetches, bounded
   per-adapter rows-applied) — are minted ONLY by
   ``helix_tpu/engine/adapters.py``.  The runner's scrape surface must
   keep calling ``collect_adapter_metrics``, the node agent must build
   its heartbeat residency block via ``adapter_residency_summary``, and
   the control plane must clamp runner-supplied blocks through
   ``validate_adapter_block`` (the contracts 3-10 importer pattern).

12. **No multihost feature forks** (ISSUE 16): the plan-broadcast
   rewrite deleted every "inert for lockstep" downgrade — spec decode,
   adapters, WFQ, preemption, the async pipeline and filestore prefix
   hits all run on multi-host meshes because the leader's plan pins
   them as data.  Under ``helix_tpu/engine/`` and ``helix_tpu/serving/``
   (``serving/multihost_serving.py`` itself exempt), CODE — comments
   and docstrings may discuss the topology freely — that sniffs the
   leader journal (``hasattr``/``getattr(..., "journal")``) or
   branches on a ``lockstep``/``multihost`` token fails the build: a
   new guard would quietly regrow the single-host/multi-host feature
   fork the rewrite collapsed.  Role wiring lives in
   ``multihost_serving.py`` and the control plane (not scanned); a
   genuine transport site carries a ``multihost-ok: <why>`` marker on
   the line or in a comment within the two lines above it.

   ISSUE 17 extends the fence to the mesh-health vocabulary: the
   ``helix_mh_*`` metric family is minted ONLY by
   ``serving/multihost_serving.py`` (quoted literal anywhere else in
   ``helix_tpu/`` fails), the follower state-machine and resync-reason
   literals (``"healthy"``/``"lagging"``/``"lost"``,
   ``"ring_overflow"``/``"leader_restart"``/``"handoff_mismatch"``/
   ``"checkpoint_rejected"``) stay quoted only there — consumers under
   the guarded dirs import ``FOLLOWER_*``/``RESYNC_*`` instead of
   re-minting strings that would silently fork the state machine — and
   the scrape/heartbeat surfaces keep routing through the module's
   helpers (``collect_mh_metrics``, ``mh_heartbeat_block``,
   ``validate_mh_block``: the contracts 3-11 importer pattern).

13. **Trace federation is one subsystem** (ISSUE 18).  Every
   ``helix_trace_*`` / ``helix_cp_trace*`` series — the runner
   span-loss counter, the control plane's federation-ingest counters,
   and ``helix_cp_traces_stored`` — is minted ONLY by
   ``helix_tpu/obs/trace.py``; a quoted literal anywhere else in
   ``helix_tpu/`` or ``tools/`` fails.  The scrape surfaces route
   through its collectors (``collect_trace_metrics`` on the runner,
   ``collect_cp_trace_ingest`` on the cp) and the heartbeat push
   drains through ``drain_export`` — the same importer pattern as
   contracts 3-12.

14. **Correctness canaries are one subsystem** (ISSUE 19).  Every
   ``helix_canary_*`` / ``helix_cp_canary_*`` series — the runner
   health rung + probe/mismatch counters and the control plane's
   federated per-runner family + router avoid counters — is minted
   ONLY by ``helix_tpu/obs/canary.py``; a quoted literal anywhere else
   in ``helix_tpu/`` or ``tools/`` fails.  The node agent runs probing
   through ``CanaryProber``, the control plane clamps runner-supplied
   health blocks through ``validate_canary_block``, and the router
   steers on ``canary_failing`` — the same importer pattern as
   contracts 3-13.

Usage: ``python tools/lint_metrics.py [repo_root]`` — exits 1 with one
line per violation.
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize

# the naming contract (keep in sync with helix_tpu.obs.metrics):
# lowercase snake_case under the helix_ prefix...
NAME_RE = re.compile(r"helix_[a-z0-9_]+")
# ...with base units only: counters end _total, durations are _seconds,
# sizes are _bytes.  Non-base-unit suffixes are rejected so new series
# can't drift into _ms/_cnt style.
_BAD_SUFFIXES = ("_ms", "_us", "_millis", "_msec", "_cnt", "_num")

# any quoted string that *starts* with helix_ is treated as a metric-name
# candidate (module paths use dots / dashes and never match)
_NAME_LITERAL = re.compile(r"""["'](helix_[A-Za-z0-9_]*)["']""")

# exposition built outside the registry: an f-string whose text starts
# with a metric name (f"helix_foo{tag} {value}"), or a "# TYPE" literal
_ADHOC_FSTRING = re.compile(r"""f["']helix_""")
_ADHOC_TYPE = re.compile(r"""["']\# TYPE """)

# suffixes the registry appends itself; a literal carrying one would
# double-suffix the exposition
_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")


def _iter_py_files(root: str):
    for base in ("helix_tpu", "tools"):
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _in_obs(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel.startswith(os.path.join("helix_tpu", "obs") + os.sep)


def _is_self(path: str) -> bool:
    return os.path.basename(path) == "lint_metrics.py"


# the heartbeat saturation-summary schema lives in obs/flight.py as a
# tuple literal closed by a ")" at column 0 — parsed textually so the
# linter never has to import the package
_SAT_KEYS_RE = re.compile(
    r"SATURATION_KEYS\s*=\s*\((.*?)^\)", re.S | re.M
)
_SAT_KEY_LITERAL = re.compile(r"""["']([a-z0-9_]+)["']""")
_SAT_GAUGE_RE = re.compile(r"helix_cp_runner_saturation_([a-z0-9_]+)")
# every producer/consumer of the saturation summary must import the
# shared schema tuple: the engine loop (per-engine summary), the node
# agent (per-node rollup it heartbeats) and the control plane (the
# helix_cp_runner_saturation_* gauges it renders) — three sites that
# PR 6's kv_host_occupancy/preempted_requests keys must reach in
# lockstep
_SAT_IMPORTERS = (
    os.path.join("helix_tpu", "serving", "engine_loop.py"),
    os.path.join("helix_tpu", "control", "node_agent.py"),
    os.path.join("helix_tpu", "control", "server.py"),
)

# -- contract 4: bounded tenant labels --------------------------------------
# Tenant-labelled series may only be minted by obs/slo.py's bounded
# accounting (top-K + __other__, LRU demotion): a `tenant` label applied
# anywhere else is unbounded cardinality waiting to happen.  Two textual
# detectors, same style as contract 3:
#   - name literals in the tenant/SLO families outside obs/slo.py
#   - a collector/metric call passing a "tenant" label key
# quoted literals only: prose in docstrings may NAME the families, but
# an actual emission site passes the name as a string literal
_TENANT_NAME_RE = re.compile(
    r"[\"']helix_(?:cp_)?(?:tenant_[a-z0-9_]+|slo_[a-z0-9_]+"
    r"|worst_tenant_[a-z0-9_]+)[\"']"
)
_TENANT_LABEL_CALL = re.compile(
    r"\.(?:gauge|counter|histogram|metric|labels)\("
    r"[^#]*[\"']tenant[\"']"
)
# the federation schema both planes must share (TENANT_KEYS entries):
# the node agent builds the heartbeat `tenants` block from it and the
# control plane filters/renders with it
_TENANT_IMPORTERS = (
    os.path.join("helix_tpu", "control", "node_agent.py"),
    os.path.join("helix_tpu", "control", "server.py"),
)


def _is_slo(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "obs", "slo.py")


# -- contract 5: one scheduler vocabulary -----------------------------------
# helix_sched_* series and the scheduler-decision audit reasons are
# minted only by serving/sched.py; other modules import the constants.
_SCHED_NAME_RE = re.compile(r"""["']helix_sched_[a-z0-9_]*["']""")
# the reason vocabulary: module-level `NAME = "sched_..."` constant
# assignments (collected into the SCHED_AUDIT_REASONS tuple) — NOT every
# sched_* string (e.g. the "sched_class" attribute name is not a reason)
_SCHED_REASON_LITERAL = re.compile(
    r"""^[A-Z][A-Z0-9_]*\s*=\s*["'](sched_[a-z0-9_]+)["']""", re.M
)
# (file, required symbol): the loop must build its scheduler through the
# factory; the OpenAI surface must adopt the shared class header
_SCHED_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "engine_loop.py"),
        "make_scheduler",
    ),
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "CLASS_HEADER",
    ),
)


def _is_sched(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "serving", "sched.py")


# -- contract 6: one migration vocabulary -----------------------------------
# Cross-runner migration series (runner export/import counters, the
# drain-state gauge, and the control plane's mid-stream failover
# counters) are minted only by serving/migration.py; the runner and the
# control plane call its collector helpers.
_MIGRATION_NAME_RE = re.compile(
    r"""["']helix_(?:migrations?_[a-z0-9_]+"""
    r"""|cp_midstream_[a-z0-9_]*|cp_runner_draining)["']"""
)
# (file, required symbol): both scrape surfaces must keep routing
# through the migration module's collectors
_MIGRATION_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_runner_migration",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "collect_cp_migration",
    ),
)


def _is_migration(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "serving", "migration.py")


def _migration_schema_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, "helix_tpu", "serving", "migration.py")
    if not os.path.isfile(mod):
        return [
            "helix_tpu/serving/migration.py: missing — the migration "
            "metric vocabulary must live there"
        ]
    for rel, symbol in _MIGRATION_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from the migration "
                    "module (helix_tpu/serving/migration.py)"
                )
    return violations


def _load_sched_schema(root: str):
    """Contract 5 setup: the audit-reason vocabulary from
    serving/sched.py (textual parse, like SATURATION_KEYS) plus
    schema-level violations (missing tuple, an importer that stopped
    referencing its required symbol)."""
    violations: list = []
    sched = os.path.join(root, "helix_tpu", "serving", "sched.py")
    if not os.path.isfile(sched):
        return (), [
            "helix_tpu/serving/sched.py: missing — the scheduler "
            "vocabulary (SCHED_AUDIT_REASONS) must live there"
        ]
    with open(sched, encoding="utf-8", errors="replace") as f:
        src = f.read()
    if "SCHED_AUDIT_REASONS" not in src:
        return (), [
            "helix_tpu/serving/sched.py: SCHED_AUDIT_REASONS tuple "
            "not found"
        ]
    reasons = tuple(sorted(set(_SCHED_REASON_LITERAL.findall(src))))
    if not reasons:
        return (), [
            "helix_tpu/serving/sched.py: no sched_* audit-reason "
            "literals found"
        ]
    for rel, symbol in _SCHED_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not reference {symbol} from the "
                    "scheduler module (helix_tpu/serving/sched.py)"
                )
    return reasons, violations


def _load_saturation_schema(root: str):
    """Contract 3 setup: the shared SATURATION_KEYS set from
    obs/flight.py plus any schema-level violations (missing tuple, a
    heartbeat side that stopped importing it).  The per-line
    ``helix_cp_runner_saturation_<key>`` check runs inside ``run()``'s
    single file walk."""
    violations: list = []
    flight = os.path.join(root, "helix_tpu", "obs", "flight.py")
    if not os.path.isfile(flight):
        return set(), [
            "helix_tpu/obs/flight.py: missing — SATURATION_KEYS schema "
            "must live there"
        ]
    with open(flight, encoding="utf-8", errors="replace") as f:
        m = _SAT_KEYS_RE.search(f.read())
    if not m:
        return set(), [
            "helix_tpu/obs/flight.py: SATURATION_KEYS tuple literal not "
            "found"
        ]
    keys = {k for k in _SAT_KEY_LITERAL.findall(m.group(1))}
    if not keys:
        return set(), ["helix_tpu/obs/flight.py: SATURATION_KEYS is empty"]
    for rel in _SAT_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if "SATURATION_KEYS" not in f.read():
                violations.append(
                    f"{rel}: does not use the shared heartbeat schema "
                    "(import obs.flight.SATURATION_KEYS)"
                )
    return keys, violations


def _tenant_schema_violations(root: str) -> list:
    """Contract 4 setup: both federation sides must keep referencing the
    shared TENANT_KEYS entry schema (the SATURATION_KEYS importer
    rule)."""
    violations = []
    for rel in _TENANT_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if "TENANT_KEYS" not in f.read():
                violations.append(
                    f"{rel}: does not use the shared tenant rollup "
                    "schema (obs.slo.TENANT_KEYS)"
                )
    return violations


# -- contract 8: one routing/autoscale vocabulary ----------------------------
# helix_cp_route_* series are minted only by control/router.py (the
# scored-policy module) and helix_cp_autoscale_* only by
# control/compute.py (the pool autoscaler); the control plane renders
# both through their collector helpers.
_ROUTE_NAME_RE = re.compile(r"""["']helix_cp_route_[a-z0-9_]*["']""")
_AUTOSCALE_NAME_RE = re.compile(
    r"""["']helix_cp_autoscale_[a-z0-9_]*["']"""
)
# (file, required symbol): the cp scrape surface must keep routing
# through both modules' collectors
_ROUTING_IMPORTERS = (
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "collect_cp_routing",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "collect_cp_autoscale",
    ),
)


def _is_route(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "control", "router.py")


def _is_autoscale(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "control", "compute.py")


def _routing_schema_violations(root: str) -> list:
    violations = []
    for rel, mod in (
        (os.path.join("helix_tpu", "control", "router.py"),
         "routing"),
        (os.path.join("helix_tpu", "control", "compute.py"),
         "autoscale"),
    ):
        if not os.path.isfile(os.path.join(root, rel)):
            violations.append(
                f"{rel}: missing — the {mod} metric vocabulary must "
                "live there"
            )
    for rel, symbol in _ROUTING_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} (the routing/"
                    "autoscale collector importer pattern)"
                )
    return violations


# -- contract 10: one transfer/pool/filestore vocabulary ---------------------
# Disaggregated prefill/decode (ISSUE 14): KV-ship outcomes are minted
# only by serving/migration.py, the persistent KV tier's series only by
# serving/kv_filestore.py, and the cp's pool-role/handoff series only by
# control/router.py.
_XFER_NAME_RE = re.compile(r"""["']helix_xfer_[a-z0-9_]*["']""")
_FILESTORE_KV_NAME_RE = re.compile(
    r"""["']helix_filestore_kv_[a-z0-9_]*["']"""
)
_POOL_NAME_RE = re.compile(r"""["']helix_cp_pool_[a-z0-9_]*["']""")
# (file, required symbol): both scrape surfaces keep routing through
# the owning modules' collector helpers
_DISAGG_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_xfer",
    ),
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_filestore_kv",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "collect_cp_pools",
    ),
)


def _is_kv_filestore(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "serving", "kv_filestore.py")


def _disagg_schema_violations(root: str) -> list:
    violations = []
    for rel, mod in (
        (os.path.join("helix_tpu", "serving", "kv_filestore.py"),
         "filestore-KV"),
    ):
        if not os.path.isfile(os.path.join(root, rel)):
            violations.append(
                f"{rel}: missing — the {mod} metric vocabulary must "
                "live there"
            )
    for rel, symbol in _DISAGG_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} (the transfer/pool/"
                    "filestore collector importer pattern)"
                )
    return violations


# -- contract 11: one adapter vocabulary --------------------------------------
# Continuous multi-LoRA serving (ISSUE 15): helix_adapter_* series are
# minted only by engine/adapters.py; the runner scrape surface, the
# node agent's heartbeat block and the control plane's heartbeat
# validation all route through its helpers.
_ADAPTER_NAME_RE = re.compile(r"""["']helix_adapter_[a-z0-9_]*["']""")
# (file, required symbol): the importer pattern
_ADAPTER_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_adapter_metrics",
    ),
    (
        os.path.join("helix_tpu", "control", "node_agent.py"),
        "adapter_residency_summary",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "validate_adapter_block",
    ),
)


def _is_adapters(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == os.path.join("helix_tpu", "engine", "adapters.py")


def _adapter_schema_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, "helix_tpu", "engine", "adapters.py")
    if not os.path.isfile(mod):
        return [
            "helix_tpu/engine/adapters.py: missing — the adapter "
            "metric vocabulary must live there"
        ]
    for rel, symbol in _ADAPTER_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from the adapter "
                    "module (helix_tpu/engine/adapters.py)"
                )
    return violations


# -- contract 7: one compiled step entry point -------------------------------
# The unified ragged step is THE device-step builder; these existing
# names are the only lru-cached ``_build_*`` functions allowed under
# helix_tpu/engine/ — a new one is a new trace family and fails here.
_ALLOWED_STEP_BUILDERS = frozenset({
    "_build_ragged_step_fn",     # THE unified device-step entry point
    "_build_prefill_fn_mrope",   # VL single-shot prefill (image buckets)
    "_build_embed_splice_fn",    # VL embed splice
    "_build_page_restore_fn",    # host-tier restore scatter, not a step
})
_LRU_DECOR = re.compile(r"@functools\.(partial\(\s*)?lru_cache")
_DEF_NAME = re.compile(r"\s*def\s+([A-Za-z_][A-Za-z0-9_]*)")


def _step_builder_violations(root: str) -> list:
    """Contract 7: flag any lru-cached ``_build_*`` function under
    helix_tpu/engine/ that is not in the allowlist."""
    violations = []
    eng_dir = os.path.join(root, "helix_tpu", "engine")
    if not os.path.isdir(eng_dir):
        return violations
    for fn in sorted(os.listdir(eng_dir)):
        if not fn.endswith(".py"):
            continue
        path = os.path.join(eng_dir, fn)
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().splitlines()
        pending_lru = False
        for i, line in enumerate(lines, 1):
            if _LRU_DECOR.search(line):
                pending_lru = True
                continue
            stripped = line.strip()
            if pending_lru and stripped.startswith("@"):
                continue  # stacked decorators
            if pending_lru:
                m = _DEF_NAME.match(line)
                if m:
                    name = m.group(1)
                    if (
                        name.startswith("_build_")
                        and name not in _ALLOWED_STEP_BUILDERS
                    ):
                        rel = os.path.relpath(path, root)
                        violations.append(
                            f"{rel}:{i}: new lru-cached step builder "
                            f"{name!r} outside the unified ragged entry "
                            "point — route the shape through "
                            "_build_ragged_step_fn (or argue for an "
                            "allowlist entry in tools/lint_metrics.py)"
                        )
                if stripped and not stripped.startswith("#"):
                    pending_lru = False
    return violations


# -- contract 9: engine-loop host-sync discipline ----------------------------
# The async pipeline lives or dies on the loop never blocking on the
# device outside the engine's reconcile: one stray per-step fetch added
# to engine_loop.py re-serializes everything without failing a test.
_HOST_SYNC_RE = re.compile(
    r"jax\.device_get|block_until_ready|np\.asarray\("
)
# a designated reconcile/emit site carries this marker on the same line
_HOST_SYNC_OK = "host-sync-ok"


def _host_sync_violations(root: str) -> list:
    """Contract 9: no host-device synchronization primitives in
    serving/engine_loop.py outside marker-allowlisted sites."""
    path = os.path.join(root, "helix_tpu", "serving", "engine_loop.py")
    if not os.path.isfile(path):
        return []
    violations = []
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8", errors="replace") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines, 1):
        if _HOST_SYNC_RE.search(line) and _HOST_SYNC_OK not in line:
            violations.append(
                f"{rel}:{i}: host-device sync in the engine loop — "
                "fetches belong in Engine.step_complete (the reconcile "
                "point); a per-step fetch here re-serializes the async "
                "pipeline.  If this IS a designated reconcile/emit "
                "site, mark the line 'host-sync-ok: <why>'"
            )
    return violations


# -- contract 12: no multihost feature forks ----------------------------------
# Guard detection runs on code only: comments and docstrings are blanked
# first, so prose may name the topology while an `if pm.multihost:` or a
# journal-attribute sniff in live code fails the build.
_MH_GUARD_ATTR = re.compile(r"""(?:has|get)attr\([^)]*["']journal["']""")
# bare lockstep/multihost tokens in code (attribute guards like
# `pm.multihost`, flags, branch conditions); \b keeps identifiers such
# as multihost_serving / multihost_commands out of scope
_MH_GUARD_TOKEN = re.compile(r"\b(?:lockstep|multihost)\b")
_MH_GUARD_OK = "multihost-ok"
_MH_GUARD_DIRS = (
    os.path.join("helix_tpu", "engine"),
    os.path.join("helix_tpu", "serving"),
)
_MH_GUARD_EXEMPT = os.path.join(
    "helix_tpu", "serving", "multihost_serving.py"
)
# ISSUE 17: the mesh-health vocabulary is part of the same fence.
# Quoted helix_mh_* metric names anywhere else in helix_tpu/ re-mint
# the family; quoted follower-state / resync-reason literals under the
# guarded dirs fork the state machine (import FOLLOWER_*/RESYNC_*
# from multihost_serving instead).
_MH_NAME_RE = re.compile(r"""["']helix_mh_[a-z0-9_]*["']""")
_MH_STATE_RE = re.compile(
    r"""["'](?:healthy|lagging|lost|ring_overflow|leader_restart"""
    r"""|handoff_mismatch|checkpoint_rejected)["']"""
)
# (file, required symbol): scrape + heartbeat surfaces keep routing
# through the owning module's helpers
_MH_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_mh_metrics",
    ),
    (
        os.path.join("helix_tpu", "control", "node_agent.py"),
        "mh_heartbeat_block",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "validate_mh_block",
    ),
)


def _is_mh(path: str, root: str) -> bool:
    rel = os.path.relpath(path, root)
    return rel == _MH_GUARD_EXEMPT


# -- contract 13: trace federation is one subsystem ---------------------------
# ISSUE 18: every ``helix_trace_*`` / ``helix_cp_trace*`` series (the
# runner span-loss counter, the cp federation-ingest counters, and
# ``helix_cp_traces_stored``) is minted ONLY by helix_tpu/obs/trace.py;
# the serving plane, the control plane, and the heartbeat push all
# route through its collector/drain helpers.  A second minting site
# would fork the federation accounting the way ad-hoc saturation
# gauges forked contract 1.
_TRACE_NAME_RE = re.compile(
    r"""["']helix_(?:trace_[a-z0-9_]*|cp_trace[a-z0-9_]*)["']"""
)
_TRACE_MOD = os.path.join("helix_tpu", "obs", "trace.py")
# (file, required symbol): the scrape surfaces call the owning
# module's collectors; the heartbeat drains through the export ring
_TRACE_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_trace_metrics",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "collect_cp_trace_ingest",
    ),
    (
        os.path.join("helix_tpu", "control", "node_agent.py"),
        "drain_export",
    ),
)


def _is_trace_mod(path: str, root: str) -> bool:
    return os.path.relpath(path, root) == _TRACE_MOD


# -- contract 14: correctness canaries are one subsystem ----------------------
# ISSUE 19: every ``helix_canary_*`` / ``helix_cp_canary_*`` series (the
# runner health rung + probe/mismatch counters, the cp's federated
# per-runner family, and the router avoid counters) is minted ONLY by
# helix_tpu/obs/canary.py; the node agent, the control plane, and the
# router route through its prober/validator/predicate.  A second
# minting site would fork the correctness accounting the way ad-hoc
# saturation gauges forked contract 1.
_CANARY_NAME_RE = re.compile(
    r"""["']helix_(?:canary_[a-z0-9_]*|cp_canary[a-z0-9_]*)["']"""
)
_CANARY_MOD = os.path.join("helix_tpu", "obs", "canary.py")
# (file, required symbol): probing, heartbeat clamping, and routing all
# route through the owning module
_CANARY_IMPORTERS = (
    (
        os.path.join("helix_tpu", "control", "node_agent.py"),
        "CanaryProber",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "validate_canary_block",
    ),
    (
        os.path.join("helix_tpu", "control", "router.py"),
        "canary_failing",
    ),
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_canary_metrics",
    ),
)


def _is_canary_mod(path: str, root: str) -> bool:
    return os.path.relpath(path, root) == _CANARY_MOD


# -- contract 15: the context cache is one subsystem --------------------------
# ISSUE 20: every ``helix_ctx_*`` series (handle/token gauges, the
# create/hit/miss/quota counters) is minted ONLY by
# helix_tpu/serving/context_cache.py; the OpenAI surface scrapes
# through its collector, the node agent heartbeats the shared per-root
# registry, and the control plane clamps the block with its validator.
# A second minting site would fork the pinned-prefix accounting the way
# ad-hoc saturation gauges forked contract 1.
_CTX_NAME_RE = re.compile(r"""["']helix_ctx_[a-z0-9_]*["']""")
_CTX_MOD = os.path.join("helix_tpu", "serving", "context_cache.py")
# (file, required symbol): creation/resolution metrics, heartbeat
# summary, and wire clamping all route through the owning module
_CTX_IMPORTERS = (
    (
        os.path.join("helix_tpu", "serving", "openai_api.py"),
        "collect_ctx_metrics",
    ),
    (
        os.path.join("helix_tpu", "control", "node_agent.py"),
        "context_cache_for",
    ),
    (
        os.path.join("helix_tpu", "control", "server.py"),
        "validate_ctx_block",
    ),
)


def _is_ctx_mod(path: str, root: str) -> bool:
    return os.path.relpath(path, root) == _CTX_MOD


def _ctx_importer_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, _CTX_MOD)
    if not os.path.isfile(mod):
        return [
            "helix_tpu/serving/context_cache.py: missing — the "
            "context-cache vocabulary must live there"
        ]
    for rel, symbol in _CTX_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from "
                    "helix_tpu/serving/context_cache.py (the "
                    "context-cache importer pattern)"
                )
    return violations


def _canary_importer_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, _CANARY_MOD)
    if not os.path.isfile(mod):
        return [
            "helix_tpu/obs/canary.py: missing — the correctness-canary "
            "vocabulary must live there"
        ]
    for rel, symbol in _CANARY_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from "
                    "helix_tpu/obs/canary.py (the correctness-canary "
                    "importer pattern)"
                )
    return violations


def _trace_importer_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, _TRACE_MOD)
    if not os.path.isfile(mod):
        return [
            "helix_tpu/obs/trace.py: missing — the trace-federation "
            "vocabulary must live there"
        ]
    for rel, symbol in _TRACE_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from "
                    "helix_tpu/obs/trace.py (the trace-federation "
                    "importer pattern)"
                )
    return violations


def _mh_importer_violations(root: str) -> list:
    violations = []
    mod = os.path.join(root, _MH_GUARD_EXEMPT)
    if not os.path.isfile(mod):
        return [
            "helix_tpu/serving/multihost_serving.py: missing — the "
            "mesh-health vocabulary must live there"
        ]
    for rel, symbol in _MH_IMPORTERS:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            if symbol not in f.read():
                violations.append(
                    f"{rel}: does not call {symbol} from "
                    "helix_tpu/serving/multihost_serving.py (the "
                    "mesh-health importer pattern)"
                )
    return violations


def _blank_tokens(src: str, kinds) -> list:
    """Per-line source with the given token kinds blanked out."""
    grid = [list(line) for line in src.splitlines()]

    def blank(srow, scol, erow, ecol):
        for row in range(srow - 1, min(erow, len(grid))):
            lo = scol if row == srow - 1 else 0
            hi = ecol if row == erow - 1 else len(grid[row])
            for col in range(lo, min(hi, len(grid[row]))):
                grid[row][col] = " "

    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type in kinds:
                blank(*tok.start, *tok.end)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # unparseable tail: fall back to the raw remainder
    return ["".join(row) for row in grid]


def _mh_guard_violations(root: str) -> list:
    violations = []
    for path in _iter_py_files(root):
        rel = os.path.relpath(path, root)
        if rel == _MH_GUARD_EXEMPT:
            continue
        if not any(
            rel.startswith(d + os.sep) for d in _MH_GUARD_DIRS
        ):
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            src = f.read()
        raw = src.splitlines()
        # the token check sees pure code (strings blanked: an error
        # MESSAGE may say "multihost"); the journal-sniff check keeps
        # string literals because the "journal" attribute name IS one
        code = _blank_tokens(src, (tokenize.COMMENT, tokenize.STRING))
        no_comments = _blank_tokens(src, (tokenize.COMMENT,))
        for i, line in enumerate(code, 1):
            if _MH_GUARD_ATTR.search(no_comments[i - 1]):
                what = "leader-journal sniff (hasattr/getattr 'journal')"
            elif _MH_GUARD_TOKEN.search(line):
                what = "lockstep/multihost token in code"
            elif _MH_STATE_RE.search(no_comments[i - 1]):
                what = (
                    "re-minted follower-state/resync-reason literal — "
                    "import FOLLOWER_*/RESYNC_* from multihost_serving "
                    "instead of quoting the state machine"
                )
            else:
                continue
            if any(_MH_GUARD_OK in w for w in raw[max(0, i - 3):i]):
                continue
            violations.append(
                f"{rel}:{i}: {what} — multi-host feature guards were "
                "deleted by the plan-broadcast rewrite (every feature "
                "replicates as plan data); role wiring belongs in "
                "helix_tpu/serving/multihost_serving.py, and a genuine "
                "transport site carries 'multihost-ok: <why>'"
            )
    return violations


def run(root: str) -> list:
    """Returns a list of violation strings (empty = clean)."""
    sat_keys, violations = _load_saturation_schema(root)
    violations += _tenant_schema_violations(root)
    violations += _migration_schema_violations(root)
    violations += _step_builder_violations(root)
    violations += _routing_schema_violations(root)
    violations += _disagg_schema_violations(root)
    violations += _adapter_schema_violations(root)
    violations += _host_sync_violations(root)
    violations += _mh_guard_violations(root)
    violations += _mh_importer_violations(root)
    violations += _trace_importer_violations(root)
    violations += _canary_importer_violations(root)
    violations += _ctx_importer_violations(root)
    sched_reasons, sched_violations = _load_sched_schema(root)
    violations += sched_violations
    sched_reason_res = [
        re.compile(r"""["']{}["']""".format(re.escape(r)))
        for r in sched_reasons
    ]
    for path in _iter_py_files(root):
        if _is_self(path):
            continue
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().splitlines()
        allowed_exposition = _in_obs(path, root)
        tenant_emitter = _is_slo(path, root)
        sched_emitter = _is_sched(path, root)
        migration_emitter = _is_migration(path, root)
        route_emitter = _is_route(path, root)
        autoscale_emitter = _is_autoscale(path, root)
        kv_filestore_emitter = _is_kv_filestore(path, root)
        adapter_emitter = _is_adapters(path, root)
        mh_emitter = _is_mh(path, root)
        trace_emitter = _is_trace_mod(path, root)
        canary_emitter = _is_canary_mod(path, root)
        ctx_emitter = _is_ctx_mod(path, root)
        for i, line in enumerate(lines, 1):
            if not ctx_emitter and _CTX_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_ctx_* metric family named "
                    "outside helix_tpu/serving/context_cache.py — "
                    "context-cache series must come from its module"
                )
            if not trace_emitter and _TRACE_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_trace_*/helix_cp_trace* metric "
                    "family named outside helix_tpu/obs/trace.py — "
                    "trace-federation series must come from the span "
                    "store module"
                )
            if not canary_emitter and _CANARY_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_canary_*/helix_cp_canary_* "
                    "metric family named outside helix_tpu/obs/"
                    "canary.py — correctness-canary series must come "
                    "from the prober module"
                )
            if not mh_emitter and _MH_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_mh_* metric family named outside "
                    "helix_tpu/serving/multihost_serving.py — mesh-"
                    "health series must come from the broadcast module"
                )
            if not adapter_emitter and _ADAPTER_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_adapter_* metric family named "
                    "outside helix_tpu/engine/adapters.py — adapter "
                    "series must come from the residency module"
                )
            if not migration_emitter and _XFER_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_xfer_* metric family named "
                    "outside helix_tpu/serving/migration.py — KV "
                    "transfer series must come from the shipper module"
                )
            if not kv_filestore_emitter and _FILESTORE_KV_NAME_RE.search(
                line
            ):
                violations.append(
                    f"{rel}:{i}: helix_filestore_kv_* metric family "
                    "named outside helix_tpu/serving/kv_filestore.py — "
                    "filestore-tier series must come from its module"
                )
            if not route_emitter and _POOL_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_cp_pool_* metric family named "
                    "outside helix_tpu/control/router.py — pool-role "
                    "series must come from the router module"
                )
            if not route_emitter and _ROUTE_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_cp_route_* metric family named "
                    "outside helix_tpu/control/router.py — routing "
                    "series must come from the policy module"
                )
            if not autoscale_emitter and _AUTOSCALE_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: helix_cp_autoscale_* metric family "
                    "named outside helix_tpu/control/compute.py — "
                    "autoscaler series must come from the pool manager"
                )
            if not migration_emitter and _MIGRATION_NAME_RE.search(line):
                violations.append(
                    f"{rel}:{i}: migration/drain metric family named "
                    "outside helix_tpu/serving/migration.py — import "
                    "its collector helpers instead"
                )
            if not sched_emitter:
                if _SCHED_NAME_RE.search(line):
                    violations.append(
                        f"{rel}:{i}: helix_sched_* metric family named "
                        "outside helix_tpu/serving/sched.py — scheduler "
                        "series must come from the policy module"
                    )
                elif any(p.search(line) for p in sched_reason_res):
                    violations.append(
                        f"{rel}:{i}: scheduler audit-reason literal "
                        "outside helix_tpu/serving/sched.py — import "
                        "the shared constant instead"
                    )
            if not tenant_emitter:
                if _TENANT_NAME_RE.search(line):
                    violations.append(
                        f"{rel}:{i}: tenant/SLO metric family named "
                        "outside helix_tpu/obs/slo.py — tenant-labelled "
                        "series must come from its bounded accounting"
                    )
                elif _TENANT_LABEL_CALL.search(line):
                    violations.append(
                        f"{rel}:{i}: ad-hoc 'tenant' metric label "
                        "outside helix_tpu/obs/slo.py — unbounded "
                        "tenant cardinality; route through the bounded "
                        "top-K accounting"
                    )
            for gm in _SAT_GAUGE_RE.finditer(line):
                if sat_keys and gm.group(1) not in sat_keys:
                    violations.append(
                        f"{rel}:{i}: saturation gauge key "
                        f"{gm.group(1)!r} is not in "
                        "obs.flight.SATURATION_KEYS"
                    )
            for m in _NAME_LITERAL.finditer(line):
                name = m.group(1)
                if not NAME_RE.fullmatch(name):
                    violations.append(
                        f"{rel}:{i}: metric name {name!r} violates "
                        "helix_[a-z0-9_]+ (lowercase snake_case)"
                    )
                elif any(name.endswith(s) for s in _BAD_SUFFIXES):
                    violations.append(
                        f"{rel}:{i}: metric name {name!r} uses a "
                        "non-base-unit suffix; use _seconds/_bytes/_total"
                    )
                elif not allowed_exposition and any(
                    name.endswith(s) for s in _RESERVED_SUFFIXES
                ):
                    violations.append(
                        f"{rel}:{i}: metric name {name!r} carries a "
                        "registry-reserved suffix "
                        f"({'/'.join(_RESERVED_SUFFIXES)})"
                    )
            if allowed_exposition:
                continue
            if _ADHOC_FSTRING.search(line):
                violations.append(
                    f"{rel}:{i}: ad-hoc Prometheus exposition (f-string "
                    "building a helix_ sample line) outside "
                    "helix_tpu/obs/ — feed the shared registry instead"
                )
            if _ADHOC_TYPE.search(line):
                violations.append(
                    f"{rel}:{i}: ad-hoc '# TYPE' exposition literal "
                    "outside helix_tpu/obs/ — feed the shared registry "
                    "instead"
                )
    return violations


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    violations = run(root)
    for v in violations:
        print(v)
    if violations:
        print(f"lint_metrics: {len(violations)} violation(s)")
        return 1
    print("lint_metrics: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
