"""Render a federated trace as an ASCII per-host timeline.

Operators without a Chrome-trace viewer get the same story
``/v1/debug/traces/{id}?format=chrome`` tells Perfetto: one stitched
timeline per request across the control plane and every runner
(ISSUE 18), plus two things the Chrome view makes you squint for —
the **critical path** (the chain of spans that actually bounds
end-to-end latency) and the **largest uncovered gap** with the spans
on either side of it (a takeover blackout, a slow ship, a queue wait
nobody instrumented).

Input is the control plane's stitched JSON::

    curl -s $CP/v1/debug/traces/$TID | python tools/trace_report.py -
    python tools/trace_report.py trace.json --width 100

The renderer is a pure function over the stitched doc (``render``),
so the tier-1 unit test feeds it dicts directly.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

_BAR = "="
_MIN_COL = 1


def _spans(doc: dict) -> list:
    """Normalized (host, name, plane, start, end, attrs) tuples in
    start order.  Tolerates the single-store shape (no ``host``)."""
    out = []
    for s in doc.get("spans", []):
        try:
            start = float(s["start_unix"])
            dur = max(0.0, float(s.get("duration_ms", 0.0)) / 1000.0)
        except (KeyError, TypeError, ValueError):
            continue
        out.append((
            str(s.get("host", s.get("plane", "?")) or "?"),
            str(s.get("name", "?")),
            str(s.get("plane", "")),
            start,
            start + dur,
            s.get("attrs") or {},
        ))
    out.sort(key=lambda t: (t[3], t[4]))
    return out


def _fmt_ms(seconds: float) -> str:
    ms = seconds * 1000.0
    if ms >= 1000.0:
        return f"{ms / 1000.0:.2f}s"
    return f"{ms:.1f}ms"


def _bar(start: float, end: float, t0: float, span_s: float,
         width: int) -> str:
    """One proportional ASCII bar inside a ``width``-column window."""
    if span_s <= 0:
        return _BAR * _MIN_COL
    lo = int((start - t0) / span_s * width)
    hi = int((end - t0) / span_s * width)
    hi = max(hi, lo + _MIN_COL)
    return " " * lo + _BAR * (hi - lo)


def _critical_path(spans: list) -> list:
    """Greedy furthest-reach chain from the trace's first span to its
    last covered instant: at each frontier pick the span that starts
    at/before it and extends furthest.  That chain is the set of spans
    that BOUND end-to-end latency — shortening any other span cannot
    shorten the trace."""
    if not spans:
        return []
    chain = []
    frontier = min(s[3] for s in spans)
    end = max(s[4] for s in spans)
    remaining = list(spans)
    while frontier < end:
        best = None
        for s in remaining:
            if s[3] <= frontier and (best is None or s[4] > best[4]):
                best = s
        if best is None or best[4] <= frontier:
            # uncovered gap: jump to the next span start (the gap
            # itself shows up in the gap report, not the path)
            nxt = min(
                (s for s in remaining if s[3] > frontier),
                key=lambda s: s[3], default=None,
            )
            if nxt is None:
                break
            best = nxt
        chain.append(best)
        remaining.remove(best)
        frontier = best[4]
    return chain


def _largest_gap(spans: list) -> Optional[tuple]:
    """The widest instant-free interval strictly inside the trace
    window, with the spans on either side: ``(gap_s, before, after)``
    or None when coverage is continuous."""
    if len(spans) < 2:
        return None
    best = None
    covered_until = spans[0][4]
    prev = spans[0]
    for s in spans[1:]:
        if s[3] > covered_until:
            gap = s[3] - covered_until
            if best is None or gap > best[0]:
                best = (gap, prev, s)
        if s[4] >= covered_until:
            covered_until = s[4]
            prev = s
    return best


def render(doc: dict, width: int = 72) -> str:
    """The full report for one stitched trace doc."""
    spans = _spans(doc)
    lines = [f"trace {doc.get('trace_id', '?')}"]
    if not spans:
        lines.append("  (no spans)")
        return "\n".join(lines)
    t0 = min(s[3] for s in spans)
    t1 = max(s[4] for s in spans)
    span_s = t1 - t0
    lines.append(
        f"  {len(spans)} span(s) over {_fmt_ms(span_s)} across "
        f"{len(set(s[0] for s in spans))} host(s)"
    )
    skew = doc.get("clock_skew_applied_s")
    if skew:
        for host, shift in sorted(skew.items()):
            lines.append(
                f"  clock skew: {host} shifted +{shift:.3f}s to honor "
                "dispatch causality"
            )
    if doc.get("dropped_spans"):
        lines.append(
            f"  WARNING: {doc['dropped_spans']} span(s) dropped to "
            "caps — the timeline below is incomplete"
        )
    # -- per-host timelines -------------------------------------------
    hosts: dict = {}
    for s in spans:
        hosts.setdefault(s[0], []).append(s)
    name_w = min(28, max(len(s[1]) for s in spans))
    for host in sorted(hosts, key=lambda h: min(s[3] for s in hosts[h])):
        lines.append(f"\n  [{host}]")
        for (h, name, plane, start, end, attrs) in hosts[host]:
            bar = _bar(start, end, t0, span_s, width)
            lines.append(
                f"    {name[:name_w]:<{name_w}} "
                f"+{_fmt_ms(start - t0):>8} {_fmt_ms(end - start):>8} "
                f"|{bar:<{width}}|"
            )
    # -- critical path ------------------------------------------------
    chain = _critical_path(spans)
    total = sum(s[4] - s[3] for s in chain)
    lines.append(
        f"\n  critical path ({len(chain)} span(s), "
        f"{_fmt_ms(total)} of {_fmt_ms(span_s)}):"
    )
    for s in chain:
        lines.append(
            f"    {_fmt_ms(s[4] - s[3]):>8}  {s[0]}: {s[1]}"
        )
    # -- largest gap --------------------------------------------------
    gap = _largest_gap(spans)
    if gap is not None:
        gap_s, before, after = gap
        lines.append(
            f"\n  largest gap: {_fmt_ms(gap_s)} between "
            f"{before[0]}: {before[1]!r} and {after[0]}: {after[1]!r}"
        )
        if gap_s > span_s * 0.25:
            lines.append(
                "    (over a quarter of the trace — an uninstrumented "
                "wait, a ship stall, or a takeover blackout)"
            )
    else:
        lines.append("\n  no coverage gaps")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="ASCII per-host timeline for a federated trace"
    )
    p.add_argument(
        "path",
        help="stitched-trace JSON file from /v1/debug/traces/{id} "
        "('-' reads stdin)",
    )
    p.add_argument("--width", type=int, default=72,
                   help="timeline bar width in columns")
    args = p.parse_args(argv)
    try:
        if args.path == "-":
            doc = json.load(sys.stdin)
        else:
            with open(args.path, encoding="utf-8") as f:
                doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"trace_report: cannot read trace: {e}", file=sys.stderr)
        return 1
    if not isinstance(doc, dict):
        print("trace_report: expected a stitched trace JSON object",
              file=sys.stderr)
        return 1
    print(render(doc, width=max(20, args.width)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
