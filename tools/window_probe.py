#!/usr/bin/env python
"""Time bare fused decode windows through the relay: device time vs wall.

Isolates: (a) the decode_fn call itself (device-resident args, donated),
(b) the [n, B] token fetch, (c) engine host bookkeeping.
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/.jax_bench_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from helix_tpu.engine.engine import Engine, EngineConfig, Request
from helix_tpu.engine.sampling import SamplingParams
from helix_tpu.models.common import LLAMA3_8B

import importlib.util
spec = importlib.util.spec_from_file_location("benchmod", "bench.py")


def build_params(cfg):
    import jax.numpy as jnp
    L, E, H, KVH, D, F, V = (
        cfg.num_layers, cfg.hidden_size, cfg.num_heads,
        cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size,
        cfg.vocab_size,
    )

    def qw(shape):
        n = shape[-1]
        w = (
            jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1) % 13
            - 6
        ).astype(jnp.int8)
        scale_shape = (shape[0], 1, n) if len(shape) == 3 else (1, n)
        return {"weight": w,
                "scale": jnp.full(scale_shape, 0.01, jnp.float32)}

    @jax.jit
    def build():
        return {
            "embed": {
                "weight": (
                    jax.lax.broadcasted_iota(jnp.int32, (V, E), 1) % 13 - 6
                ).astype(jnp.int8),
                "embed_scale": jnp.full((V, 1), 0.01, jnp.float32),
            },
            "layers": {
                "attn_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                "mlp_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                "wq": qw((L, E, H * D)),
                "wk": qw((L, E, KVH * D)),
                "wv": qw((L, E, KVH * D)),
                "wo": qw((L, H * D, E)),
                "w_gate": qw((L, E, F)),
                "w_up": qw((L, E, F)),
                "w_down": qw((L, F, E)),
            },
            "final_norm": {"weight": jnp.ones((E,), jnp.bfloat16)},
            "lm_head": qw((E, V)),
        }

    p = build()
    jax.block_until_ready(p)
    return p


def main():
    cfg = LLAMA3_8B
    params = build_params(cfg)
    batch = 32
    eng = Engine(
        cfg, params,
        EngineConfig(
            max_decode_batch=batch, page_size=16, num_pages=2048,
            max_pages_per_seq=64, max_prefill_len=512,
            decode_steps_per_sync=16,
        ),
    )
    sampling = SamplingParams(temperature=0.0, max_tokens=1024)
    prompts = [
        [(7 * i + j) % 1000 + 1 for j in range(128)] for i in range(batch)
    ]
    for i, p in enumerate(prompts):
        eng.add_request(Request(id=f"r{i}", prompt_tokens=list(p),
                                sampling=sampling))
    for _ in range(3):
        eng.step()   # prefill everything, warm the window fns

    def window16():
        # the unified ragged step: zero drafts + a 15-step fused tail is
        # exactly the old 16-step decode window, one compiled shape
        return eng._ragged_step(
            draft_len=eng._zero_rows, n_extra=15,
        )

    # warm this exact shape
    _, toks, _, extra, _ = window16()
    _ = np.asarray(extra)

    # (a) bare window calls, sync only at the end of the run
    t0 = time.perf_counter()
    N = 5
    for _ in range(N):
        _, toks, _, extra, _ = window16()
    jax.block_until_ready(extra)
    dt = (time.perf_counter() - t0) / N
    print(f"bare 16-step window (pipelined): {dt*1000:7.1f} ms "
          f"-> {16*batch/dt:6.0f} tok/s")

    # (b) window + token fetch each time (the engine's actual pattern)
    t0 = time.perf_counter()
    for _ in range(N):
        _, toks, _, extra, _ = window16()
        _ = np.asarray(toks)
        _ = np.asarray(extra)
    dt = (time.perf_counter() - t0) / N
    print(f"window + np.asarray fetch:       {dt*1000:7.1f} ms "
          f"-> {16*batch/dt:6.0f} tok/s")

    # (c) full engine steps
    t0 = time.perf_counter()
    n_before = sum(len(r.output_tokens) for r in eng.slots if r)
    for _ in range(N):
        eng.step()
    n_after = sum(len(r.output_tokens) for r in eng.slots if r)
    dt = (time.perf_counter() - t0) / N
    print(f"full eng.step():                 {dt*1000:7.1f} ms "
          f"-> {(n_after-n_before)/(N*dt)*N:6.0f} tok/s")


if __name__ == "__main__":
    main()
