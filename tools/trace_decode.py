#!/usr/bin/env python
"""Capture an XLA profiler trace of the fused decode window on the real
chip and print the top ops by self time (via xprof's op-stats converter).
"""

import glob
import os
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/.jax_bench_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

TRACE_DIR = "/tmp/helix_trace"


def main():
    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import LLAMA3_8B

    cfg = LLAMA3_8B
    L, E, H, KVH, D, F, V = (
        cfg.num_layers, cfg.hidden_size, cfg.num_heads,
        cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size,
        cfg.vocab_size,
    )

    def qw(shape):
        n = shape[-1]
        w = (
            jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1) % 13
            - 6
        ).astype(jnp.int8)
        scale_shape = (shape[0], 1, n) if len(shape) == 3 else (1, n)
        return {
            "weight": w,
            "scale": jnp.full(scale_shape, 0.01, jnp.float32),
        }

    @jax.jit
    def build():
        return {
            "embed": {
                "weight": (
                    jax.lax.broadcasted_iota(jnp.int32, (V, E), 1) % 13 - 6
                ).astype(jnp.int8),
                "embed_scale": jnp.full((V, 1), 0.01, jnp.float32),
            },
            "layers": {
                "attn_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                "mlp_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                "wq": qw((L, E, H * D)),
                "wk": qw((L, E, KVH * D)),
                "wv": qw((L, E, KVH * D)),
                "wo": qw((L, H * D, E)),
                "w_gate": qw((L, E, F)),
                "w_up": qw((L, E, F)),
                "w_down": qw((L, F, E)),
            },
            "final_norm": {"weight": jnp.ones((E,), jnp.bfloat16)},
            "lm_head": qw((E, V)),
        }

    params = build()
    jax.block_until_ready(params)

    batch, prompt_len = 32, 128
    eng = Engine(
        cfg, params,
        EngineConfig(
            max_decode_batch=batch, page_size=16, num_pages=2048,
            max_pages_per_seq=64, max_prefill_len=512,
            decode_steps_per_sync=16,
        ),
    )
    sampling = SamplingParams(temperature=0.0, max_tokens=64)
    prompts = [
        [(7 * i + j) % (cfg.vocab_size - 2) + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]
    for i, p in enumerate(prompts):
        eng.add_request(Request(id=f"r{i}", prompt_tokens=list(p),
                                sampling=sampling))
    # admit + prefill everything, get into steady decode
    for _ in range(3):
        eng.step()
    print("entering traced window", file=sys.stderr)
    os.makedirs(TRACE_DIR, exist_ok=True)
    with jax.profiler.trace(TRACE_DIR):
        t0 = time.perf_counter()
        eng.step()
        dt = time.perf_counter() - t0
    print(f"traced step: {dt*1000:.1f} ms", file=sys.stderr)
    while eng.has_work():
        eng.step()

    # ---- parse the xplane and print op stats ----
    files = glob.glob(f"{TRACE_DIR}/**/*.xplane.pb", recursive=True)
    print(f"xplane files: {files}", file=sys.stderr)
    if not files:
        return
    path = max(files, key=os.path.getmtime)
    try:
        from xprof.convert import raw_to_tool_data as rtd
        params2 = {"tqx": "out:csv;"}
        data, _ = rtd.xspace_to_tool_data([path], "op_profile", params2)
        print(data[:4000] if isinstance(data, (str, bytes)) else data)
    except Exception as e:  # noqa: BLE001
        print(f"op_profile failed: {e}", file=sys.stderr)
        try:
            from xprof.convert import raw_to_tool_data as rtd
            data, _ = rtd.xspace_to_tool_data(
                [path], "framework_op_stats", {"tqx": "out:csv;"}
            )
            out = data.decode() if isinstance(data, bytes) else str(data)
            lines = out.splitlines()
            print("\n".join(lines[:40]))
        except Exception as e2:  # noqa: BLE001
            print(f"framework_op_stats failed: {e2}", file=sys.stderr)


if __name__ == "__main__":
    main()
