#!/usr/bin/env python
"""Profile the serving hot path on the real chip: where do bench.py's
milliseconds actually go?  Times each phase separately:

- host<->device round-trip (the axon relay tax)
- one packed-prefill call (512-token bucket)
- one fused decode window (n_steps x full batch)
- a full bench-shaped workload with a per-step timeline

Usage: python tools/profile_tpu.py [--steps N]
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/.jax_bench_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def t(fn, n=5):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--window", type=int, default=16)
    args = ap.parse_args()

    from helix_tpu.engine.engine import Engine, EngineConfig, Request
    from helix_tpu.engine.sampling import SamplingParams
    from helix_tpu.models.common import LLAMA3_8B, ModelConfig

    dev = jax.devices()[0]
    print(f"platform={dev.platform} device={dev}", file=sys.stderr)
    on_tpu = dev.platform in ("tpu", "axon")

    # relay tax: tiny transfer each way
    x = jnp.zeros((8,), jnp.int32)
    jax.block_until_ready(x)
    d = t(lambda: jax.device_get(x), 10)
    print(f"device_get(32B) round-trip: {d*1000:.1f} ms")
    small = jax.jit(lambda a: a + 1)
    jax.block_until_ready(small(x))
    d = t(lambda: jax.block_until_ready(small(x)), 10)
    print(f"trivial jit dispatch+sync:  {d*1000:.1f} ms")

    if on_tpu:
        cfg = LLAMA3_8B
        num_pages = 2048
        import importlib
        bench = importlib.import_module("bench")
        # reuse bench's on-device int8 weight builder
        sys.argv = [sys.argv[0]]
        L, E, H, KVH, D, F, V = (
            cfg.num_layers, cfg.hidden_size, cfg.num_heads,
            cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size,
            cfg.vocab_size,
        )

        def qw(shape):
            n = shape[-1]
            w = (
                jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)
                % 13 - 6
            ).astype(jnp.int8)
            scale_shape = (shape[0], 1, n) if len(shape) == 3 else (1, n)
            return {
                "weight": w,
                "scale": jnp.full(scale_shape, 0.01, jnp.float32),
            }

        @jax.jit
        def build():
            return {
                "embed": {
                    "weight": (
                        jax.lax.broadcasted_iota(jnp.int32, (V, E), 1) % 13
                        - 6
                    ).astype(jnp.int8),
                    "embed_scale": jnp.full((V, 1), 0.01, jnp.float32),
                },
                "layers": {
                    "attn_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                    "mlp_norm": {"weight": jnp.ones((L, E), jnp.bfloat16)},
                    "wq": qw((L, E, H * D)),
                    "wk": qw((L, E, KVH * D)),
                    "wv": qw((L, E, KVH * D)),
                    "wo": qw((L, H * D, E)),
                    "w_gate": qw((L, E, F)),
                    "w_up": qw((L, E, F)),
                    "w_down": qw((L, F, E)),
                },
                "final_norm": {"weight": jnp.ones((E,), jnp.bfloat16)},
                "lm_head": qw((E, V)),
            }

        params = build()
        jax.block_until_ready(params)
    else:
        from helix_tpu.models.llama import init_params
        cfg = ModelConfig.tiny(dtype="float32")
        num_pages = 64
        params = init_params(cfg, jax.random.PRNGKey(0))

    batch = args.batch if on_tpu else 2
    prompt_len = 128 if on_tpu else 8
    gen_len = 128 if on_tpu else 8

    eng = Engine(
        cfg, params,
        EngineConfig(
            max_decode_batch=batch,
            page_size=16,
            num_pages=num_pages,
            max_pages_per_seq=64,
            max_prefill_len=512 if on_tpu else 32,
            decode_steps_per_sync=args.window if on_tpu else 1,
        ),
    )

    sampling = SamplingParams(temperature=0.0, max_tokens=gen_len)
    prompts = [
        [(7 * i + j) % (cfg.vocab_size - 2) + 1 for j in range(prompt_len)]
        for i in range(batch)
    ]

    # --- timeline of a bench-shaped workload --------------------------
    def run(tag):
        reqs = [
            Request(id=f"{tag}{i}", prompt_tokens=list(p), sampling=sampling)
            for i, p in enumerate(prompts)
        ]
        for r in reqs:
            eng.add_request(r)
        events = []
        t0 = time.perf_counter()
        while eng.has_work():
            s0 = time.perf_counter()
            before = sum(len(r.output_tokens) for r in reqs)
            eng.step()
            after = sum(len(r.output_tokens) for r in reqs)
            events.append((time.perf_counter() - s0, after - before))
        dt = time.perf_counter() - t0
        return events, dt, reqs

    run("w")  # warmup: compile everything
    events, dt, reqs = run("m")
    total = sum(len(r.output_tokens) for r in reqs)
    print(f"\nworkload: bs={batch} prompt={prompt_len} gen={gen_len}")
    print(f"total {total} tokens in {dt:.2f}s = {total/dt:.1f} tok/s")
    print(f"{len(events)} engine steps; slowest 12:")
    for ms, toks in sorted(events, reverse=True)[:12]:
        print(f"  {ms*1000:8.1f} ms  -> {toks} tokens")
    zero = [e for e in events if e[1] == 0]
    print(f"steps emitting 0 tokens: {len(zero)}  "
          f"({sum(e[0] for e in zero)*1000:.0f} ms total)")
    prefill_ms = sum(e[0] for e in events if e[1] <= batch and e[1] > 0
                     and events.index(e) < len(events) // 2)
    # decode steady state: steps emitting ~batch*window tokens
    big = [e for e in events if e[1] >= batch * max(1, args.window) // 2]
    if big:
        per = sum(e[0] for e in big) / len(big)
        toks = sum(e[1] for e in big) / len(big)
        print(f"steady decode windows: {len(big)} x {per*1000:.1f} ms "
              f"emitting {toks:.0f} tokens each = {toks/per:.0f} tok/s")


if __name__ == "__main__":
    main()
