#!/usr/bin/env python
"""Microbench int8 weight-only matmul formulations on the chip.

Decode is weight-streaming-bound: the right formulation reads int8 from
HBM and dequantizes in VMEM.  The wrong one materializes a bf16/f32 copy
in HBM (3x traffic).  Times each candidate on the bench shapes.
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/.jax_bench_cache")


def timeit(fn, *args, n=20):
    r = fn(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / n


def main():
    B = 32
    E, F = 4096, 14336
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, E), jnp.bfloat16)
    w8 = jax.random.randint(key, (E, F), -127, 127, jnp.int8)
    wbf = w8.astype(jnp.bfloat16)
    scale = jnp.full((1, F), 0.01, jnp.float32)
    bytes_w8 = E * F
    bytes_bf = E * F * 2

    @jax.jit
    def mm_bf16(x, w):
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.bfloat16)

    @jax.jit
    def mm_dequant_f32pref(x, w, s):
        out = jax.lax.dot_general(
            x, w.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (out * s).astype(jnp.bfloat16)

    @jax.jit
    def mm_dequant_bf16pref(x, w, s):
        out = jax.lax.dot_general(
            x, w.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16,
        )
        return (out * s).astype(jnp.bfloat16)

    @jax.jit
    def mm_int8_direct(x, w, s):
        # mixed int8 rhs without explicit cast
        out = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (out * s).astype(jnp.bfloat16)

    for name, fn, args, nbytes in [
        ("bf16 w (baseline)", mm_bf16, (x, wbf), bytes_bf),
        ("int8 cast->bf16, f32 acc", mm_dequant_f32pref, (x, w8, scale),
         bytes_w8),
        ("int8 cast->bf16, bf16 acc", mm_dequant_bf16pref, (x, w8, scale),
         bytes_w8),
        ("int8 direct mixed dot", mm_int8_direct, (x, w8, scale),
         bytes_w8),
    ]:
        try:
            dt = timeit(fn, *args)
            gbs = nbytes / dt / 1e9
            print(f"{name:28s}: {dt*1e6:8.0f} us  "
                  f"({gbs:6.0f} GB/s effective weight stream)")
        except Exception as e:  # noqa: BLE001
            print(f"{name:28s}: FAILED {type(e).__name__}: {e}")

    # stacked-layer scan variant: is dynamic-slice-from-stacked the issue?
    L = 8
    w8L = jax.random.randint(key, (L, E, F), -127, 127, jnp.int8)
    sL = jnp.full((L, 1, F), 0.01, jnp.float32)

    @jax.jit
    def scan_stacked(x, wL, sL):
        def body(h, ws):
            w, s = ws
            out = jax.lax.dot_general(
                h, w.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            h2 = (out * s).astype(jnp.bfloat16)
            return h2[:, :E], None

        h, _ = jax.lax.scan(body, x, (wL, sL))
        return h

    dt = timeit(scan_stacked, x, w8L, sL, n=5)
    per = dt / L
    print(f"{'scan over stacked int8':28s}: {per*1e6:8.0f} us/layer "
          f"({bytes_w8/per/1e9:6.0f} GB/s)")


if __name__ == "__main__":
    main()
