#!/usr/bin/env python
"""Print the slowest tests from the last pytest run.

``tests/conftest.py`` records every test's setup+call+teardown seconds to
``.pytest_last_durations.json`` on each run (the tier-1 command disables
pytest's own cache with ``-p no:cacheprovider``, so this file is the only
durable record).  This script is the wall-clock-creep watchdog: when
tier-1 drifts toward its 870 s timeout, run it, then mark the offenders
``@pytest.mark.slow`` (pytest.ini registers the marker) or split them.

Usage:  python tools/slowest_tests.py [N]      (default N=10)
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".pytest_last_durations.json",
    )
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        print(
            f"no durations recorded yet ({path} missing) — run pytest "
            "first; tests/conftest.py writes it on session finish",
            file=sys.stderr,
        )
        return 1
    tests = sorted(
        data.get("tests", {}).items(), key=lambda kv: kv[1], reverse=True
    )
    total = data.get("total_seconds", sum(v for _, v in tests))
    print(f"last run: {len(tests)} tests, {total:.1f}s total")
    print(f"{'seconds':>9}  {'cum%':>5}  test")
    cum = 0.0
    for nodeid, secs in tests[:n]:
        cum += secs
        print(f"{secs:9.2f}  {100 * cum / max(total, 1e-9):4.1f}%  {nodeid}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
