{{- define "helix-tpu-node.fullname" -}}
{{- printf "%s-%s" .Release.Name "helix-tpu-node" | trunc 63 | trimSuffix "-" -}}
{{- end -}}
