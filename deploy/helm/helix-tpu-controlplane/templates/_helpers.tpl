{{- define "helix-tpu-cp.fullname" -}}
{{- printf "%s-%s" .Release.Name "helix-tpu-cp" | trunc 63 | trimSuffix "-" -}}
{{- end -}}
