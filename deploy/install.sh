#!/bin/sh
# Single-node helix-tpu install (the reference's install.sh analogue):
# control plane + one serving node on this host, run under a python venv.
#
# Usage:
#   sh deploy/install.sh [--dir /opt/helix-tpu] [--port 8080] \
#       [--node-port 8000] [--profile profiles/dev-tiny.yaml] [--tpu]
#
# --tpu installs the libtpu-enabled jax build (run on a TPU VM);
# without it the node serves on CPU (dev/smoke).

set -eu

DIR=/opt/helix-tpu
PORT=8080
NODE_PORT=8000
PROFILE=""
TPU=0

while [ $# -gt 0 ]; do
  case "$1" in
    --dir) DIR="$2"; shift 2 ;;
    --port) PORT="$2"; shift 2 ;;
    --node-port) NODE_PORT="$2"; shift 2 ;;
    --profile) PROFILE="$2"; shift 2 ;;
    --tpu) TPU=1; shift ;;
    *) echo "unknown flag $1" >&2; exit 2 ;;
  esac
done

SRC=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)

echo "==> installing helix-tpu into $DIR"
mkdir -p "$DIR"
python3 -m venv "$DIR/venv"
# shellcheck disable=SC1091
. "$DIR/venv/bin/activate"
pip install --quiet --upgrade pip
if [ "$TPU" = 1 ]; then
  pip install --quiet 'jax[tpu]' \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
else
  pip install --quiet jax
fi
pip install --quiet flax optax orbax-checkpoint chex einops numpy \
  aiohttp requests pyyaml cryptography safetensors

cp -r "$SRC/helix_tpu" "$SRC/profiles" "$DIR/"
export PYTHONPATH="$DIR"

RUNNER_TOKEN=$(python3 -c "import secrets; print(secrets.token_urlsafe(24))")
export HELIX_RUNNER_TOKEN="$RUNNER_TOKEN"
echo "$RUNNER_TOKEN" > "$DIR/runner-token"
chmod 600 "$DIR/runner-token"

echo "==> starting control plane on :$PORT"
nohup "$DIR/venv/bin/python" -m helix_tpu serve \
  --port "$PORT" --db "$DIR/helix.db" --sandbox-agents \
  > "$DIR/controlplane.log" 2>&1 &
echo $! > "$DIR/controlplane.pid"

sleep 2
echo "==> starting serving node on :$NODE_PORT"
set -- --runner-id "$(hostname)-node" \
  --control-plane "http://127.0.0.1:$PORT" --port "$NODE_PORT" \
  --advertise "http://127.0.0.1:$NODE_PORT"
[ -n "$PROFILE" ] && set -- "$@" --profile "$PROFILE"
nohup "$DIR/venv/bin/python" -m helix_tpu serve-node "$@" \
  > "$DIR/node.log" 2>&1 &
echo $! > "$DIR/node.pid"

sleep 2
echo "==> bootstrap the first admin:"
echo "    curl -s -X POST http://127.0.0.1:$PORT/api/v1/users \\"
echo "      -d '{\"email\": \"you@example.com\", \"admin\": true}'"
echo "==> UI:   http://127.0.0.1:$PORT/"
echo "==> API:  http://127.0.0.1:$PORT/v1/chat/completions"
echo "==> logs: $DIR/controlplane.log  $DIR/node.log"
