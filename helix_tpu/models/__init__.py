from helix_tpu.models.common import ModelConfig
from helix_tpu.models.llama import (
    init_params,
    forward,
    param_logical_axes,
)

__all__ = ["ModelConfig", "init_params", "forward", "param_logical_axes"]
