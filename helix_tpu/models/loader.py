"""HF safetensors checkpoint -> stacked-layer JAX parameter tree.

The reference's weight path is "vLLM downloads from HF inside the container"
(progress surfaced by ``api/pkg/composemgr/hfprogress.go``).  Here loading is
owned: safetensors are memory-mapped on the host, transposed into our
[in, out] matmul convention, stacked along a leading layer axis for the
scan-based forward, then device_put with the model's NamedShardings so each
chip only materialises its shard (no full-model HBM spike on load).

Supports Llama/Qwen2/Qwen3 per-projection layouts and Phi-3's fused
``qkv_proj``/``gate_up_proj``.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import ml_dtypes  # noqa: F401  — registers bfloat16 with numpy
import numpy as np

from helix_tpu.models.common import ModelConfig


def _open_shards(model_dir: str):
    """Yield (tensor_name -> numpy array) access across all safetensors files."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    handles = {}
    name_to_file = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        name_to_file = index["weight_map"]
        files = sorted(set(name_to_file.values()))
    else:
        files = [
            f for f in sorted(os.listdir(model_dir)) if f.endswith(".safetensors")
        ]
    for fname in files:
        handles[fname] = safe_open(
            os.path.join(model_dir, fname), framework="np"
        )
    if not name_to_file:
        for fname, h in handles.items():
            for name in h.keys():
                name_to_file[name] = fname

    class Shards:
        def __init__(self):
            self.names = set(name_to_file)

        def get(self, name: str) -> np.ndarray:
            return handles[name_to_file[name]].get_tensor(name)

        def __contains__(self, name):
            return name in self.names

    return Shards()


def load_config(model_dir: str, name: Optional[str] = None) -> ModelConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    return ModelConfig.from_hf_config(hf, name=name or os.path.basename(model_dir))


def load_params(
    model_dir: str,
    cfg: Optional[ModelConfig] = None,
    *,
    mesh=None,
    logical_axes=None,
    dtype=None,
):
    """Load checkpoint into the ``init_params`` tree layout.

    With ``mesh`` + ``logical_axes``, each stacked tensor is placed with its
    NamedSharding as it is built, so host->HBM transfer happens shard-wise.
    """
    import jax
    import jax.numpy as jnp

    from helix_tpu.models.llama import param_logical_axes
    from helix_tpu.parallel.sharding import _prune_spec_for_mesh, spec_for

    cfg = cfg or load_config(model_dir)
    dtype = np.dtype(dtype) if dtype is not None else np.dtype(cfg.dtype)
    if np.dtype(cfg.dtype) != dtype:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, dtype=dtype.name)
    shards = _open_shards(model_dir)
    L = cfg.num_layers
    H, KVH, D, E = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.hidden_size

    def get(name):
        t = shards.get(name)
        if t.dtype != dtype:
            import ml_dtypes  # noqa: F401  (registers bfloat16 for numpy)

            t = t.astype(dtype)
        return t

    def linear_t(name):
        """HF Linear stores [out, in]; our convention is [in, out]."""
        return np.ascontiguousarray(get(name).T)

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    pfx = "model.layers.{}."
    fused_qkv = f"{pfx.format(0)}self_attn.qkv_proj.weight" in shards
    fused_mlp = f"{pfx.format(0)}mlp.gate_up_proj.weight" in shards

    def qkv(i):
        p = pfx.format(i) + "self_attn."
        if fused_qkv:
            w = linear_t(p + "qkv_proj.weight")  # [E, (H+2KVH)*D]
            return (
                w[:, : H * D],
                w[:, H * D : (H + KVH) * D],
                w[:, (H + KVH) * D :],
            )
        return (
            linear_t(p + "q_proj.weight"),
            linear_t(p + "k_proj.weight"),
            linear_t(p + "v_proj.weight"),
        )

    def gate_up(i):
        p = pfx.format(i) + "mlp."
        if fused_mlp:
            w = linear_t(p + "gate_up_proj.weight")  # [E, 2F]
            return w[:, : cfg.intermediate_size], w[:, cfg.intermediate_size :]
        return linear_t(p + "gate_proj.weight"), linear_t(p + "up_proj.weight")

    layers = {
        "attn_norm": {
            "weight": stack(lambda i: get(pfx.format(i) + "input_layernorm.weight"))
        },
        "mlp_norm": {
            "weight": stack(
                lambda i: get(pfx.format(i) + "post_attention_layernorm.weight")
            )
        },
        "wq": {"weight": stack(lambda i: qkv(i)[0])},
        "wk": {"weight": stack(lambda i: qkv(i)[1])},
        "wv": {"weight": stack(lambda i: qkv(i)[2])},
        "wo": {
            "weight": stack(
                lambda i: linear_t(pfx.format(i) + "self_attn.o_proj.weight")
            )
        },
        "w_gate": {"weight": stack(lambda i: gate_up(i)[0])},
        "w_up": {"weight": stack(lambda i: gate_up(i)[1])},
        "w_down": {
            "weight": stack(lambda i: linear_t(pfx.format(i) + "mlp.down_proj.weight"))
        },
    }
    if cfg.num_experts > 0:
        # Mixtral: block_sparse_moe.gate + experts.N.w1/w3/w2
        X = cfg.num_experts
        del layers["w_gate"], layers["w_up"], layers["w_down"]
        layers["router"] = {
            "weight": stack(
                lambda i: linear_t(
                    pfx.format(i) + "block_sparse_moe.gate.weight"
                )
            )
        }

        def experts(i, w):
            return np.stack([
                linear_t(
                    pfx.format(i)
                    + f"block_sparse_moe.experts.{e}.{w}.weight"
                )
                for e in range(X)
            ])

        layers["experts"] = {
            # HF Mixtral: w1 = gate, w3 = up, w2 = down
            "w_gate": {"weight": stack(lambda i: experts(i, "w1"))},
            "w_up": {"weight": stack(lambda i: experts(i, "w3"))},
            "w_down": {"weight": stack(lambda i: experts(i, "w2"))},
        }
    if cfg.attention_bias and f"{pfx.format(0)}self_attn.q_proj.bias" in shards:
        for ours, theirs in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
            layers[ours]["bias"] = stack(
                lambda i, t=theirs: get(pfx.format(i) + f"self_attn.{t}.bias")
            )
    if cfg.qk_norm:
        layers["q_norm"] = {
            "weight": stack(lambda i: get(pfx.format(i) + "self_attn.q_norm.weight"))
        }
        layers["k_norm"] = {
            "weight": stack(lambda i: get(pfx.format(i) + "self_attn.k_norm.weight"))
        }

    params = {
        "embed": {"weight": get("model.embed_tokens.weight")},
        "layers": layers,
        "final_norm": {"weight": get("model.norm.weight")},
    }
    if not cfg.tie_word_embeddings:
        if "lm_head.weight" in shards:
            params["lm_head"] = {"weight": linear_t("lm_head.weight")}
        else:  # some checkpoints tie implicitly
            params["lm_head"] = {
                "weight": np.ascontiguousarray(params["embed"]["weight"].T)
            }

    if mesh is not None:
        from jax.sharding import NamedSharding

        axes = logical_axes or param_logical_axes(cfg)

        def place(x, ax):
            spec = _prune_spec_for_mesh(mesh, spec_for(ax))
            return jax.device_put(
                jnp.asarray(x), NamedSharding(mesh, spec)
            )

        params = jax.tree.map(place, params, axes)
    else:
        params = jax.tree.map(jnp.asarray, params)
    return cfg, params
