"""BERT-family embedding encoder (bge-base et al) as batched XLA.

BASELINE.md config 2: "bge-base embedding worker for PGVector RAG ingest".
The reference serves embeddings via vLLM pooling runners in compose profiles
(``design/sample-profiles/8xH100-vllm.yaml:15-43``, `--runner pooling`);
here it is a functional BERT encoder jitted per (batch, seq) bucket:
tokens -> embeddings -> mean/CLS pool -> L2 normalise, behind
``/v1/embeddings``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pooling: str = "cls"          # cls | mean  (bge uses CLS)
    normalize: bool = True
    dtype: str = "float32"
    name: str = "bge-base"

    @classmethod
    def from_hf_config(cls, hf: dict, name: str = "encoder") -> "EncoderConfig":
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            max_position_embeddings=hf.get("max_position_embeddings", 512),
            type_vocab_size=hf.get("type_vocab_size", 2),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
            name=name,
        )

    @classmethod
    def tiny(cls, **o) -> "EncoderConfig":
        base = dict(
            vocab_size=256, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_position_embeddings=64, name="tiny-enc",
        )
        base.update(o)
        return cls(**base)


def init_params(cfg: EncoderConfig, key) -> dict:
    L, E, F, V = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    ks = jax.random.split(key, 10)
    dt = jnp.dtype(cfg.dtype)

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    return {
        "embed": {
            "word": w(ks[0], (V, E)),
            "position": w(ks[1], (cfg.max_position_embeddings, E)),
            "token_type": w(ks[2], (cfg.type_vocab_size, E)),
            "norm": {"weight": jnp.ones((E,), dt), "bias": jnp.zeros((E,), dt)},
        },
        "layers": {
            "wq": {"weight": w(ks[3], (L, E, E)), "bias": jnp.zeros((L, E), dt)},
            "wk": {"weight": w(ks[4], (L, E, E)), "bias": jnp.zeros((L, E), dt)},
            "wv": {"weight": w(ks[5], (L, E, E)), "bias": jnp.zeros((L, E), dt)},
            "wo": {"weight": w(ks[6], (L, E, E)), "bias": jnp.zeros((L, E), dt)},
            "attn_norm": {
                "weight": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)
            },
            "w_in": {"weight": w(ks[7], (L, E, F)), "bias": jnp.zeros((L, F), dt)},
            "w_out": {"weight": w(ks[8], (L, F, E)), "bias": jnp.zeros((L, E), dt)},
            "mlp_norm": {
                "weight": jnp.ones((L, E), dt), "bias": jnp.zeros((L, E), dt)
            },
        },
    }


def _dense(x, p):
    out = jax.lax.dot_general(
        x, p["weight"], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (out + p["bias"].astype(jnp.float32)).astype(x.dtype)


def forward(params, cfg: EncoderConfig, tokens, attention_mask):
    """tokens/attention_mask: [B, S] -> pooled embeddings [B, E]."""
    B, S = tokens.shape
    H = cfg.num_heads
    E = cfg.hidden_size
    D = E // H
    dt = jnp.dtype(cfg.dtype)

    emb = params["embed"]
    h = (
        emb["word"][tokens]
        + emb["position"][jnp.arange(S)][None]
        + emb["token_type"][jnp.zeros_like(tokens)]
    ).astype(dt)
    h = layer_norm(
        h, emb["norm"]["weight"], emb["norm"]["bias"], cfg.layer_norm_eps
    )

    # bidirectional mask: [B, 1, 1, S]
    neg = jnp.asarray(-1e9, jnp.float32)
    bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    def body(h, lp):
        q = _dense(h, lp["wq"]).reshape(B, S, H, D)
        k = _dense(h, lp["wk"]).reshape(B, S, H, D)
        v = _dense(h, lp["wv"]).reshape(B, S, H, D)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(D)
        p = jax.nn.softmax(s + bias, axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        ctx = ctx.reshape(B, S, E).astype(dt)
        h = layer_norm(
            h + _dense(ctx, lp["wo"]),
            lp["attn_norm"]["weight"], lp["attn_norm"]["bias"],
            cfg.layer_norm_eps,
        )
        mid = jax.nn.gelu(_dense(h, lp["w_in"]), approximate=False)
        h = layer_norm(
            h + _dense(mid, lp["w_out"]),
            lp["mlp_norm"]["weight"], lp["mlp_norm"]["bias"],
            cfg.layer_norm_eps,
        )
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])

    if cfg.pooling == "cls":
        pooled = h[:, 0]
    else:
        m = attention_mask[..., None].astype(jnp.float32)
        pooled = (h.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
        pooled = pooled.astype(dt)
    if cfg.normalize:
        pooled = pooled / jnp.linalg.norm(
            pooled.astype(jnp.float32), axis=-1, keepdims=True
        ).astype(pooled.dtype)
    return pooled


def load_hf_encoder(model_dir: str):
    """Load a HF BERT-style checkpoint into the tree above."""
    import json
    import os

    from helix_tpu.models.loader import _open_shards

    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    cfg = EncoderConfig.from_hf_config(hf, name=os.path.basename(model_dir))
    sh = _open_shards(model_dir)
    pfx = (
        "bert."
        if any(n.startswith("bert.") for n in sh.names)
        else ""
    )

    def g(name):
        return sh.get(pfx + name)

    def lin(name):
        return np.ascontiguousarray(g(name + ".weight").T), g(name + ".bias")

    L = cfg.num_layers

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    def lw(i, n):
        return lin(f"encoder.layer.{i}.{n}")

    layers = {}
    for ours, theirs in (
        ("wq", "attention.self.query"),
        ("wk", "attention.self.key"),
        ("wv", "attention.self.value"),
        ("wo", "attention.output.dense"),
        ("w_in", "intermediate.dense"),
        ("w_out", "output.dense"),
    ):
        layers[ours] = {
            "weight": stack(lambda i, t=theirs: lw(i, t)[0]),
            "bias": stack(lambda i, t=theirs: lw(i, t)[1]),
        }
    layers["attn_norm"] = {
        "weight": stack(lambda i: g(f"encoder.layer.{i}.attention.output.LayerNorm.weight")),
        "bias": stack(lambda i: g(f"encoder.layer.{i}.attention.output.LayerNorm.bias")),
    }
    layers["mlp_norm"] = {
        "weight": stack(lambda i: g(f"encoder.layer.{i}.output.LayerNorm.weight")),
        "bias": stack(lambda i: g(f"encoder.layer.{i}.output.LayerNorm.bias")),
    }
    params = {
        "embed": {
            "word": g("embeddings.word_embeddings.weight"),
            "position": g("embeddings.position_embeddings.weight"),
            "token_type": g("embeddings.token_type_embeddings.weight"),
            "norm": {
                "weight": g("embeddings.LayerNorm.weight"),
                "bias": g("embeddings.LayerNorm.bias"),
            },
        },
        "layers": layers,
    }
    return cfg, jax.tree.map(jnp.asarray, params)


class EmbeddingRunner:
    """Batched embedding worker behind /v1/embeddings (thread-safe via GIL +
    single jit dispatch; bucketed (batch, seq) compiles)."""

    def __init__(self, cfg: EncoderConfig, params, tokenizer, max_batch=32):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self._fns: dict = {}

    @classmethod
    def build(cls, pm, tokenizer) -> "EmbeddingRunner":
        if pm.checkpoint:
            cfg, params = load_hf_encoder(pm.checkpoint)
        else:
            cfg = EncoderConfig.tiny(name=pm.name)
            params = init_params(cfg, jax.random.PRNGKey(0))
        return cls(cfg, params, tokenizer, max_batch=pm.engine.get("max_batch", 32))

    def _fn(self, B, S):
        key = (B, S)
        if key not in self._fns:
            self._fns[key] = jax.jit(
                functools.partial(forward, cfg=self.cfg)
            )
        return self._fns[key]

    def embed_tokens(self, token_lists) -> np.ndarray:
        out = []
        for i in range(0, len(token_lists), self.max_batch):
            chunk = token_lists[i : i + self.max_batch]
            maxlen = min(
                max((len(t) for t in chunk), default=1),
                self.cfg.max_position_embeddings,
            )
            S = 1
            while S < maxlen:
                S *= 2
            S = min(S, self.cfg.max_position_embeddings)
            B = len(chunk)
            toks = np.zeros((B, S), np.int32)
            mask = np.zeros((B, S), np.int32)
            for j, t in enumerate(chunk):
                t = list(t)[:S]
                toks[j, : len(t)] = t
                mask[j, : len(t)] = 1
            fn = self._fn(B, S)
            out.append(
                np.asarray(
                    fn(self.params, tokens=jnp.asarray(toks),
                       attention_mask=jnp.asarray(mask))
                )
            )
        return np.concatenate(out, axis=0) if out else np.zeros((0, self.cfg.hidden_size))

    def embed_texts(self, texts) -> np.ndarray:
        return self.embed_tokens([self.tokenizer.encode(t) for t in texts])
