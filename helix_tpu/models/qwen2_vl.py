"""Qwen2-VL: vision tower + M-RoPE decoder for the vision-RAG config.

BASELINE.md config 4 ("Qwen2-VL-7B vision-RAG agent session") — the
reference serves it as a vLLM container; here both towers are owned JAX:

- Vision tower: ViT over pre-extracted patch rows (the conv3d patch embed
  becomes one matmul), 2D rotary embeddings split across the (h, w) halves
  of each head, full bidirectional attention within each image (segment
  masking between images), spatial merger MLP projecting merge^2 patch
  groups into the LLM's hidden space.  Blocks run under ``lax.scan`` like
  the decoder.
- Text tower: the Qwen2 decoder with **M-RoPE** — rotary sections of the
  head dim driven by (temporal, height, width) position streams; text
  tokens advance all three together, image spans fan out over h/w
  (``mrope_positions`` mirrors HF's ``get_rope_index``).
- The merged sequence (text embeddings with image embeddings spliced at
  image-token placeholders) enters the SAME engine prefill/decode as pure
  text — multimodality is an embedding-level concern, invisible to the
  paged cache.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.models.common import ModelConfig
from helix_tpu.ops.norms import layer_norm
from helix_tpu.ops.quant import maybe_dequant_dense as _dense


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    depth: int = 32
    embed_dim: int = 1280
    hidden_size: int = 3584          # LLM hidden (merger output)
    num_heads: int = 16
    mlp_ratio: int = 4
    in_channels: int = 3
    patch_size: int = 14
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    @classmethod
    def from_hf(cls, d: dict) -> "VisionConfig":
        return cls(
            depth=d["depth"],
            embed_dim=d["embed_dim"],
            hidden_size=d["hidden_size"],
            num_heads=d["num_heads"],
            mlp_ratio=d["mlp_ratio"],
            in_channels=d.get("in_channels", 3),
            patch_size=d["patch_size"],
            spatial_merge_size=d["spatial_merge_size"],
            temporal_patch_size=d["temporal_patch_size"],
        )

    @classmethod
    def tiny(cls, **o) -> "VisionConfig":
        base = dict(
            depth=2, embed_dim=32, hidden_size=64, num_heads=2, mlp_ratio=2,
            patch_size=4, spatial_merge_size=2, temporal_patch_size=2,
        )
        base.update(o)
        return cls(**base)


# ---------------------------------------------------------------------------
# vision tower
# ---------------------------------------------------------------------------


def vision_rotary_pos(grid_thw: np.ndarray, merge: int) -> np.ndarray:
    """Per-patch (h, w) rotary ids in the processor's merge-block patch
    order. grid_thw: [n_images, 3] (t, h, w in patch units)."""
    out = []
    for t, h, w in np.asarray(grid_thw):
        hpos = np.arange(h)[:, None].repeat(w, axis=1)
        wpos = np.arange(w)[None, :].repeat(h, axis=0)

        def blockify(x):
            return (
                x.reshape(h // merge, merge, w // merge, merge)
                .transpose(0, 2, 1, 3)
                .reshape(-1)
            )

        hw = np.stack([blockify(hpos), blockify(wpos)], axis=-1)  # [h*w, 2]
        out.append(np.tile(hw, (int(t), 1)))
    return np.concatenate(out, axis=0)  # [N, 2]


def _vision_rope(q, k, pos_hw, head_dim):
    """Rotate q/k with 2D rope: first half of rotary dims from h, second
    from w (HF Qwen2-VL convention: freqs for h and w concatenated)."""
    half = head_dim // 2   # rotary dims (rotate_half over full head_dim)
    quarter = half // 2
    inv = 1.0 / (10000.0 ** (np.arange(0, quarter) * 2.0 / half))
    inv = jnp.asarray(inv, jnp.float32)
    h_angles = pos_hw[:, 0:1].astype(jnp.float32) * inv[None]  # [N, q]
    w_angles = pos_hw[:, 1:2].astype(jnp.float32) * inv[None]
    angles = jnp.concatenate([h_angles, w_angles], axis=-1)     # [N, half]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).astype(x.dtype)

    return rot(q), rot(k)


def init_vision_params(cfg: VisionConfig, key, dtype=jnp.float32) -> dict:
    E, F, D = cfg.embed_dim, cfg.embed_dim * cfg.mlp_ratio, cfg.head_dim
    Lv = cfg.depth
    m2 = cfg.spatial_merge_size**2
    ks = jax.random.split(key, 8)

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    return {
        "patch_embed": {"weight": w(ks[0], (cfg.patch_dim, E))},
        "blocks": {
            "norm1": {"weight": jnp.ones((Lv, E), dtype),
                      "bias": jnp.zeros((Lv, E), dtype)},
            "norm2": {"weight": jnp.ones((Lv, E), dtype),
                      "bias": jnp.zeros((Lv, E), dtype)},
            "qkv": {"weight": w(ks[1], (Lv, E, 3 * E)),
                    "bias": jnp.zeros((Lv, 3 * E), dtype)},
            "proj": {"weight": w(ks[2], (Lv, E, E)),
                     "bias": jnp.zeros((Lv, E), dtype)},
            "fc1": {"weight": w(ks[3], (Lv, E, F)),
                    "bias": jnp.zeros((Lv, F), dtype)},
            "fc2": {"weight": w(ks[4], (Lv, F, E)),
                    "bias": jnp.zeros((Lv, E), dtype)},
        },
        "merger": {
            "ln_q": {"weight": jnp.ones((E,), dtype),
                     "bias": jnp.zeros((E,), dtype)},
            "mlp0": {"weight": w(ks[5], (E * m2, E * m2)),
                     "bias": jnp.zeros((E * m2,), dtype)},
            "mlp2": {"weight": w(ks[6], (E * m2, cfg.hidden_size)),
                     "bias": jnp.zeros((cfg.hidden_size,), dtype)},
        },
    }


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def vision_forward(
    params: dict,
    cfg: VisionConfig,
    patches,        # [N, patch_dim] pre-extracted patch rows
    grid_thw,       # [n_images, 3] numpy (static — drives masks/positions)
):
    """-> [N / merge^2, hidden_size] image embeddings."""
    grid = np.asarray(grid_thw)
    N = patches.shape[0]
    E, H, D = cfg.embed_dim, cfg.num_heads, cfg.head_dim

    x = _dense(patches, params["patch_embed"])
    pos_hw = jnp.asarray(vision_rotary_pos(grid, cfg.spatial_merge_size))

    # segment id per patch (attention stays within an image)
    sizes = [int(t * h * w) for t, h, w in grid]
    seg = jnp.asarray(np.repeat(np.arange(len(sizes)), sizes))
    attn_bias = jnp.where(
        seg[:, None] == seg[None, :], 0.0, -1e9
    )[None]  # [1, N, N]

    def block(x, bp):
        y = layer_norm(x, bp["norm1"]["weight"], bp["norm1"]["bias"])
        qkv = _dense(y, bp["qkv"]).reshape(N, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        q, k = _vision_rope(q, k, pos_hw, D)
        s = jnp.einsum(
            "qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(D)
        p = jax.nn.softmax(s + attn_bias, axis=-1)
        ctx = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
        x = x + _dense(ctx.reshape(N, E).astype(x.dtype), bp["proj"])
        y = layer_norm(x, bp["norm2"]["weight"], bp["norm2"]["bias"])
        x = x + _dense(_quick_gelu(_dense(y, bp["fc1"])), bp["fc2"])
        return x, None

    x, _ = jax.lax.scan(block, x, params["blocks"])

    m = params["merger"]
    x = layer_norm(x, m["ln_q"]["weight"], m["ln_q"]["bias"])
    m2 = cfg.spatial_merge_size**2
    x = x.reshape(N // m2, E * m2)
    x = _dense(jax.nn.gelu(_dense(x, m["mlp0"]), approximate=False), m["mlp2"])
    return x


# ---------------------------------------------------------------------------
# M-RoPE for the text tower
# ---------------------------------------------------------------------------


def apply_mrope(x, positions3, inv_freq, sections: Sequence[int]):
    """Rotate q or k with multimodal rope.

    x: [B, S, H, D]; positions3: [3, B, S] (t, h, w streams);
    sections: split of the D/2 frequency dims across the 3 streams
    (e.g. [16, 24, 24] for D=128)."""
    ang = (
        positions3[..., None].astype(jnp.float32) * inv_freq
    )  # [3, B, S, D/2]
    ang = jnp.concatenate([ang, ang], axis=-1)  # [3, B, S, D]
    # HF convention (apply_multimodal_rotary_pos_emb): ``mrope_section * 2``
    # is LIST repetition — the [t, h, w] section split applies to each half
    # of the doubled dim symmetrically, so a rotation pair (j, j + D/2)
    # always takes both cos and sin from the same stream.
    idx_half = np.concatenate(
        [np.full((s,), i) for i, s in enumerate(sections)]
    )  # [D/2]
    idx = np.concatenate([idx_half, idx_half])  # [D]
    sel = jax.nn.one_hot(jnp.asarray(idx), 3, dtype=ang.dtype)  # [D, 3]
    angles = jnp.einsum("cbsf,fc->bsf", ang, sel)  # [B, S, D]
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    rot_half = jnp.concatenate([-x2, x1], axis=-1)
    return (xf * cos + rot_half * sin).astype(x.dtype)


def mrope_positions(
    input_ids: Sequence[int],
    grid_thw,
    image_token_id: int,
    merge: int = 2,
    start: Sequence[int] = (0, 0, 0),
) -> tuple:
    """(positions3 [3, S], next_delta) for one sequence — HF's
    ``get_rope_index`` reimplemented host-side.

    Text tokens advance (t, h, w) together; each image span (a run of
    ``image_token_id``) gets t constant, h/w enumerating the merged grid;
    after the span, all streams jump to max+1.  ``next_delta`` is the shared
    scalar offset for decode continuation (position - token_index)."""
    ids = list(input_ids)
    grid = np.asarray(grid_thw) if grid_thw is not None else np.zeros((0, 3))
    S = len(ids)
    pos = np.zeros((3, S), np.int64)
    cur = list(start)
    img = 0
    i = 0
    while i < S:
        if ids[i] == image_token_id and img < len(grid):
            t, h, w = (int(v) for v in grid[img])
            hh, ww = h // merge, w // merge
            n = t * hh * ww
            tpos = np.repeat(np.arange(t), hh * ww)
            hpos = np.tile(np.repeat(np.arange(hh), ww), t)
            wpos = np.tile(np.tile(np.arange(ww), hh), t)
            base = cur[0]
            pos[0, i : i + n] = base + tpos
            pos[1, i : i + n] = base + hpos
            pos[2, i : i + n] = base + wpos
            nxt = base + max(t, hh, ww)
            cur = [nxt, nxt, nxt]
            img += 1
            i += n
        else:
            pos[:, i] = cur
            cur = [c + 1 for c in cur]
            i += 1
    delta = int(cur[0]) - S
    return pos, delta


def text_forward_mrope(
    params, cfg: ModelConfig, tokens, positions3, *, attn_fn,
    layer_caches=None, carry_caches=None, input_embeds=None,
    mrope_sections=(16, 24, 24), seq_positions=None,
):
    """Qwen2-VL text tower: llama forward with M-RoPE rotation and optional
    pre-computed input embeddings (image splice).

    Cache protocols mirror ``models.llama.forward``: ``layer_caches``
    slices per layer as xs (prefill); ``carry_caches`` threads the full
    pool through the scan carry and the attn_fn returns
    ``(out, new_caches)`` (paged decode — in-kernel KV write)."""
    from helix_tpu.ops.norms import rms_norm
    from helix_tpu.ops.quant import embed_lookup
    from helix_tpu.ops.rope import rope_frequencies

    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta))
    if input_embeds is None:
        h = embed_lookup(params["embed"], tokens, jnp.dtype(cfg.dtype))
    else:
        h = input_embeds.astype(jnp.dtype(cfg.dtype))

    from helix_tpu.models.llama import _act

    B, S = h.shape[0], h.shape[1]
    if seq_positions is None:
        seq_positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))

    def block(h, layer_params, layer_cache):
        B, S, E = h.shape
        H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        p = layer_params
        x = rms_norm(h, p["attn_norm"]["weight"], cfg.rms_norm_eps)
        q = _dense(x, p["wq"]).reshape(B, S, H, D)
        k = _dense(x, p["wk"]).reshape(B, S, KVH, D)
        v = _dense(x, p["wv"]).reshape(B, S, KVH, D)
        q = apply_mrope(q, positions3, inv_freq, mrope_sections)
        k = apply_mrope(k, positions3, inv_freq, mrope_sections)
        # causal masking is by SEQUENCE index, not the mrope t-stream —
        # image-span tokens share t but still attend causally (HF parity)
        res = attn_fn(q, k, v, layer_cache, seq_positions)
        new_cache = None
        if isinstance(res, tuple):
            attn_out, new_cache = res
        else:
            attn_out = res
        h = h + _dense(attn_out.reshape(B, S, H * D), p["wo"])
        x = rms_norm(h, p["mlp_norm"]["weight"], cfg.rms_norm_eps)
        act = _act(cfg.hidden_act)
        h = h + _dense(act(_dense(x, p["w_gate"])) * _dense(x, p["w_up"]),
                       p["w_down"])
        return h, (k, v), new_cache, jnp.int32(0)

    from helix_tpu.models.llama import scan_decoder_blocks

    h, kv, _ = scan_decoder_blocks(
        h, params["layers"], cfg.num_layers, block, layer_caches,
        carry_caches,
    )
    h = rms_norm(h, params["final_norm"]["weight"], cfg.rms_norm_eps)
    w_out = (
        params["embed"]["weight"].T
        if cfg.tie_word_embeddings
        else params["lm_head"]["weight"]
    )
    if w_out.dtype == jnp.int8:
        w_out = w_out.astype(h.dtype)
    logits = jax.lax.dot_general(
        h, w_out, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if cfg.tie_word_embeddings and "embed_scale" in params["embed"]:
        logits = logits * params["embed"]["embed_scale"][:, 0][None, None, :]
    elif not cfg.tie_word_embeddings and "scale" in params.get("lm_head", {}):
        logits = logits * params["lm_head"]["scale"].reshape(-1)[None, None, :]
    return logits, kv


# ---------------------------------------------------------------------------
# loader
# ---------------------------------------------------------------------------


def load_qwen2_vl(model_dir: str, mesh=None):
    """(text_cfg, vision_cfg, params) from an HF Qwen2-VL checkpoint.
    Weight names per ``transformers`` Qwen2VLForConditionalGeneration
    (model.visual.* / model.language_model.*).

    With ``mesh``, the text tower is placed shard-wise with its
    NamedShardings as it leaves host memory (mirrors
    ``models.loader.load_params``) and the vision tower is committed whole
    to the mesh's first device — no device ever holds the full text tower.
    """
    import json
    import os

    from helix_tpu.models.loader import _open_shards

    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    tcfg = ModelConfig.from_hf_config(
        {**hf, "model_type": "qwen2"}, name=os.path.basename(model_dir)
    )
    vcfg = VisionConfig.from_hf(hf["vision_config"])
    sh = _open_shards(model_dir)

    def g(name):
        # HF has serialised Qwen2-VL both as model.<name> and
        # model.language_model.<name> across versions
        for pfx in ("model.", "", "model.language_model.", "language_model."):
            n = pfx + name
            if n in sh:
                return sh.get(n)
        raise KeyError(name)

    def lin(name):
        return np.ascontiguousarray(g(name + ".weight").T), g(name + ".bias")

    Lv = vcfg.depth

    def vstack(fn):
        return np.stack([fn(i) for i in range(Lv)])

    vb = "visual.blocks.{}."
    vision = {
        "patch_embed": {
            "weight": np.ascontiguousarray(
                g("visual.patch_embed.proj.weight")
                .reshape(vcfg.embed_dim, -1)
                .T
            )
        },
        "blocks": {
            "norm1": {
                "weight": vstack(lambda i: g(vb.format(i) + "norm1.weight")),
                "bias": vstack(lambda i: g(vb.format(i) + "norm1.bias")),
            },
            "norm2": {
                "weight": vstack(lambda i: g(vb.format(i) + "norm2.weight")),
                "bias": vstack(lambda i: g(vb.format(i) + "norm2.bias")),
            },
            "qkv": {
                "weight": vstack(lambda i: lin(vb.format(i) + "attn.qkv")[0]),
                "bias": vstack(lambda i: lin(vb.format(i) + "attn.qkv")[1]),
            },
            "proj": {
                "weight": vstack(lambda i: lin(vb.format(i) + "attn.proj")[0]),
                "bias": vstack(lambda i: lin(vb.format(i) + "attn.proj")[1]),
            },
            "fc1": {
                "weight": vstack(lambda i: lin(vb.format(i) + "mlp.fc1")[0]),
                "bias": vstack(lambda i: lin(vb.format(i) + "mlp.fc1")[1]),
            },
            "fc2": {
                "weight": vstack(lambda i: lin(vb.format(i) + "mlp.fc2")[0]),
                "bias": vstack(lambda i: lin(vb.format(i) + "mlp.fc2")[1]),
            },
        },
        "merger": {
            "ln_q": {
                "weight": g("visual.merger.ln_q.weight"),
                "bias": g("visual.merger.ln_q.bias"),
            },
            "mlp0": {
                "weight": np.ascontiguousarray(
                    g("visual.merger.mlp.0.weight").T
                ),
                "bias": g("visual.merger.mlp.0.bias"),
            },
            "mlp2": {
                "weight": np.ascontiguousarray(
                    g("visual.merger.mlp.2.weight").T
                ),
                "bias": g("visual.merger.mlp.2.bias"),
            },
        },
    }

    # text tower reuses the llama loader against the language_model prefix
    # by temporarily aliasing names
    L = tcfg.num_layers
    lm = "layers.{}."

    def tstack(fn):
        return np.stack([fn(i) for i in range(L)])

    def tlin(i, n):
        return np.ascontiguousarray(g(lm.format(i) + n + ".weight").T)

    layers = {
        "attn_norm": {
            "weight": tstack(lambda i: g(lm.format(i) + "input_layernorm.weight"))
        },
        "mlp_norm": {
            "weight": tstack(
                lambda i: g(lm.format(i) + "post_attention_layernorm.weight")
            )
        },
        "wq": {
            "weight": tstack(lambda i: tlin(i, "self_attn.q_proj")),
            "bias": tstack(lambda i: g(lm.format(i) + "self_attn.q_proj.bias")),
        },
        "wk": {
            "weight": tstack(lambda i: tlin(i, "self_attn.k_proj")),
            "bias": tstack(lambda i: g(lm.format(i) + "self_attn.k_proj.bias")),
        },
        "wv": {
            "weight": tstack(lambda i: tlin(i, "self_attn.v_proj")),
            "bias": tstack(lambda i: g(lm.format(i) + "self_attn.v_proj.bias")),
        },
        "wo": {"weight": tstack(lambda i: tlin(i, "self_attn.o_proj"))},
        "w_gate": {"weight": tstack(lambda i: tlin(i, "mlp.gate_proj"))},
        "w_up": {"weight": tstack(lambda i: tlin(i, "mlp.up_proj"))},
        "w_down": {"weight": tstack(lambda i: tlin(i, "mlp.down_proj"))},
    }
    text = {
        "embed": {"weight": g("embed_tokens.weight")},
        "layers": layers,
        "final_norm": {"weight": g("norm.weight")},
    }
    if not tcfg.tie_word_embeddings:
        try:
            text["lm_head"] = {
                "weight": np.ascontiguousarray(g("lm_head.weight").T)
            }
        except KeyError:
            text["lm_head"] = {
                "weight": np.ascontiguousarray(text["embed"]["weight"].T)
            }
    import dataclasses as _dc

    hf_dtype = hf.get("torch_dtype") or hf.get("dtype") or "float32"
    tcfg = _dc.replace(tcfg, attention_bias=True, dtype=str(hf_dtype))

    if mesh is not None:
        from helix_tpu.models.llama import param_logical_axes
        from helix_tpu.parallel.sharding import shard_params

        text = shard_params(text, mesh, param_logical_axes(tcfg))
        dev0 = mesh.devices.flat[0]
        text["visual"] = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), dev0), vision
        )
    else:
        text = jax.tree.map(jnp.asarray, text)
        text["visual"] = jax.tree.map(jnp.asarray, vision)
    return tcfg, vcfg, text
