"""Llama-family decoder as functional JAX over a parameter pytree.

Covers Llama-3, Phi-3 (MHA, fused-free), Qwen-2 (attention bias), Qwen-3
(qk-norm) via ``ModelConfig`` switches — the decoder families the reference
serves through vLLM containers (``design/sample-profiles/``), here as owned
TPU-first code:

- Layers are **stacked** (every weight has a leading ``num_layers`` dim) and
  the forward pass is a single ``lax.scan`` — one layer gets traced/compiled
  once regardless of depth, keeping XLA compile times flat.
- Attention is injected (``attn_fn``) so the same forward serves training
  (flash attention), prefill (flash + segment masks) and decode (paged
  attention over the engine's KV cache) without re-tracing model code.
- All matmuls run in bf16 on the MXU with fp32 accumulation
  (``preferred_element_type``); norms/softmax in fp32.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from helix_tpu.models.common import ModelConfig
from helix_tpu.ops.norms import rms_norm
from helix_tpu.ops.rope import apply_rope, rope_frequencies

Params = dict
# attn_fn(q, k, v, layer_cache, positions) -> attention output
AttnFn = Callable[..., jax.Array]


def _dense(x, p, adapter_ids=None):
    """x @ p["weight"] with fp32 MXU accumulation; handles int8-quantized
    weights ({weight, scale}), optional bias, and batched multi-LoRA
    pool slots (``adapter_ids`` names each token's slot) transparently."""
    from helix_tpu.ops.quant import maybe_dequant_dense

    return maybe_dequant_dense(x, p, adapter_ids=adapter_ids)


def _act(name: str):
    if name == "silu":
        return jax.nn.silu
    if name in ("gelu", "gelu_new", "gelu_pytorch_tanh", "gelu_tanh"):
        return functools.partial(jax.nn.gelu, approximate=True)
    raise ValueError(f"unknown activation {name}")


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=None
) -> Params:
    """Random-init a stacked-layer parameter tree (tests, training-from-init).

    Real checkpoints come from ``helix_tpu.models.loader`` which produces the
    same tree from HF safetensors.
    """
    dtype = dtype or jnp.dtype(cfg.dtype)
    L, E, H, KVH, D, F, V = (
        cfg.num_layers,
        cfg.hidden_size,
        cfg.num_heads,
        cfg.num_kv_heads,
        cfg.head_dim,
        cfg.intermediate_size,
        cfg.vocab_size,
    )
    ks = jax.random.split(key, 8)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params = {
        "embed": {"weight": norm(ks[0], (V, E))},
        "layers": {
            "attn_norm": {"weight": jnp.ones((L, E), dtype)},
            "mlp_norm": {"weight": jnp.ones((L, E), dtype)},
            "wq": {"weight": norm(ks[1], (L, E, H * D))},
            "wk": {"weight": norm(ks[2], (L, E, KVH * D))},
            "wv": {"weight": norm(ks[3], (L, E, KVH * D))},
            "wo": {"weight": norm(ks[4], (L, H * D, E))},
            "w_gate": {"weight": norm(ks[5], (L, E, F))},
            "w_up": {"weight": norm(ks[6], (L, E, F))},
            "w_down": {"weight": norm(ks[7], (L, F, E))},
        },
        "final_norm": {"weight": jnp.ones((E,), dtype)},
    }
    if cfg.num_experts > 0:
        # Mixtral-family: router + expert-stacked SwiGLU replaces the
        # dense FFN (models/moe.py)
        X = cfg.num_experts
        kk = jax.random.split(jax.random.fold_in(key, 7), 4)
        layers = params["layers"]
        del layers["w_gate"], layers["w_up"], layers["w_down"]
        layers["router"] = {"weight": norm(kk[0], (L, E, X))}
        layers["experts"] = {
            "w_gate": {"weight": norm(kk[1], (L, X, E, F))},
            "w_up": {"weight": norm(kk[2], (L, X, E, F))},
            "w_down": {"weight": norm(kk[3], (L, X, F, E))},
        }
    if cfg.attention_bias:
        for nm, width in (("wq", H * D), ("wk", KVH * D), ("wv", KVH * D)):
            params["layers"][nm]["bias"] = jnp.zeros((L, width), dtype)
    if cfg.qk_norm:
        params["layers"]["q_norm"] = {"weight": jnp.ones((L, D), dtype)}
        params["layers"]["k_norm"] = {"weight": jnp.ones((L, D), dtype)}
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"weight": norm(jax.random.fold_in(key, 99), (E, V))}
    return params


def param_logical_axes(cfg: ModelConfig) -> Any:
    """Tree of logical-axis tuples matching ``init_params``.

    The leading stacked-layer axis carries the "layers" logical name on
    EVERY weight: it prunes to replicated on meshes without a pp axis,
    and shards layer blocks across pipeline groups on ``mesh: {pp: N}``
    — a new stacked weight must use "layers" too or it silently
    replicates across the pipeline."""
    lax_ = {
        "attn_norm": {"weight": ("layers", None)},
        "mlp_norm": {"weight": ("layers", None)},
        "wq": {"weight": ("layers", "embed", "heads")},
        "wk": {"weight": ("layers", "embed", "kv_heads")},
        "wv": {"weight": ("layers", "embed", "kv_heads")},
        "wo": {"weight": ("layers", "heads", "embed")},
        "w_gate": {"weight": ("layers", "embed", "mlp")},
        "w_up": {"weight": ("layers", "embed", "mlp")},
        "w_down": {"weight": ("layers", "mlp", "embed")},
    }
    if cfg.num_experts > 0:
        del lax_["w_gate"], lax_["w_up"], lax_["w_down"]
        lax_["router"] = {"weight": ("layers", "embed", None)}
        lax_["experts"] = {
            "w_gate": {"weight": ("layers", "expert", "embed", "mlp")},
            "w_up": {"weight": ("layers", "expert", "embed", "mlp")},
            "w_down": {"weight": ("layers", "expert", "mlp", "embed")},
        }
    if cfg.attention_bias:
        lax_["wq"]["bias"] = ("layers", "heads")
        lax_["wk"]["bias"] = ("layers", "kv_heads")
        lax_["wv"]["bias"] = ("layers", "kv_heads")
    if cfg.qk_norm:
        lax_["q_norm"] = {"weight": ("layers", None)}
        lax_["k_norm"] = {"weight": ("layers", None)}
    axes = {
        "embed": {"weight": ("vocab", "embed")},
        "layers": lax_,
        "final_norm": {"weight": (None,)},
    }
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = {"weight": ("embed", "vocab")}
    return axes


def _layer(
    h,
    layer_params: Params,
    layer_cache,
    cfg: ModelConfig,
    positions,
    inv_freq,
    attn_fn: AttnFn,
    moe_token_mask=None,
    adapter_ids=None,
):
    """One decoder block. h: [B, S, E].

    When ``attn_fn`` returns ``(out, new_cache)`` (the carry-cache decode
    protocol — the paged pool threads through the layer scan and the
    kernel updates it in place), the new cache is returned as the third
    element; plain attn_fns (prefill) return the output alone.

    The fourth return is the layer's MoE capacity-overflow drop count
    (int32 scalar, 0 for dense layers) — threaded out of the scan so the
    engine can surface silently-dropped routing work in its stats.
    """
    B, S, E = h.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    p = layer_params

    # --- attention ---
    x = rms_norm(h, p["attn_norm"]["weight"], cfg.rms_norm_eps, cfg.norm_offset)
    q = _dense(x, p["wq"], adapter_ids).reshape(B, S, H, D)
    k = _dense(x, p["wk"], adapter_ids).reshape(B, S, KVH, D)
    v = _dense(x, p["wv"], adapter_ids).reshape(B, S, KVH, D)
    if cfg.qk_norm:
        q = rms_norm(q, p["q_norm"]["weight"], cfg.rms_norm_eps)
        k = rms_norm(k, p["k_norm"]["weight"], cfg.rms_norm_eps)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    res = attn_fn(q, k, v, layer_cache, positions)
    new_cache = None
    if isinstance(res, tuple):
        attn_out, new_cache = res
    else:
        attn_out = res
    h = h + _dense(attn_out.reshape(B, S, H * D), p["wo"], adapter_ids)

    # --- mlp ---
    x = rms_norm(h, p["mlp_norm"]["weight"], cfg.rms_norm_eps, cfg.norm_offset)
    act = _act(cfg.hidden_act)
    moe_dropped = jnp.int32(0)
    if cfg.num_experts > 0:
        from helix_tpu.models.moe import moe_ffn

        router_w = p["router"]["weight"]
        if router_w.dtype == jnp.int8:
            # dequantise in fp32: the router's softmax runs in fp32, and
            # rounding through bf16 here could flip near-tied top-k picks
            router_w = router_w.astype(jnp.float32) * p["router"][
                "scale"
            ].astype(jnp.float32)
        moe_out, moe_dropped = moe_ffn(
            x, router_w, p["experts"], cfg, act,
            token_mask=moe_token_mask,
            return_dropped=True,
        )
        h = h + moe_out
    else:
        gate = _dense(x, p["w_gate"], adapter_ids)
        up = _dense(x, p["w_up"], adapter_ids)
        h = h + _dense(act(gate) * up, p["w_down"], adapter_ids)
    return h, (k, v), new_cache, moe_dropped


def scan_decoder_blocks(
    h, layers_params, num_layers: int, block, layer_caches, carry_caches
):
    """Shared cache-protocol dispatch for decoder towers (llama families +
    the Qwen2-VL mrope tower share this so the two protocols cannot
    diverge).

    ``block(h, layer_params, layer_cache) -> (h, (k, v), new_cache,
    moe_dropped)``.

    - xs mode (``layer_caches`` or no cache): the scan slices a per-layer
      cache view; returns (h, kv, moe_dropped) with kv stacked [L, ...]
      for the caller's scatter.
    - carry mode (``carry_caches``): the full cache pytree threads through
      the scan carry and block's attn_fn receives ``(caches, layer_idx)``;
      returns (h, final_caches, moe_dropped).

    ``moe_dropped`` is the int32 total of MoE capacity-overflow drops
    summed over all layers (0 for dense towers).
    """
    if carry_caches is not None:
        def carry_body(carry, xs):
            h, caches, drops = carry
            layer_params, lyr = xs
            h, _, caches, d = block(h, layer_params, (caches, lyr))
            return (h, caches, drops + d), None

        xs = (layers_params, jnp.arange(num_layers, dtype=jnp.int32))
        (h, kv, dropped), _ = jax.lax.scan(
            carry_body, (h, carry_caches, jnp.int32(0)), xs
        )
    else:
        def scan_body(h, xs):
            layer_params, layer_cache = xs
            h, kv, _, d = block(h, layer_params, layer_cache)
            return h, (kv, d)

        if layer_caches is None:
            # lax.scan needs every xs leaf to have a leading L dim; "no
            # history" is a zero-length dummy the attn_fn never touches.
            layer_caches = jnp.zeros((num_layers, 0), jnp.int32)
        h, (kv, drops) = jax.lax.scan(
            scan_body, h, (layers_params, layer_caches)
        )
        dropped = jnp.sum(drops)
    return h, kv, dropped


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens,               # [B, S] int32
    positions,            # [B, S] int32 (absolute, ragged-aware)
    *,
    attn_fn: AttnFn,
    layer_caches=None,    # pytree whose leaves have leading num_layers dim
    carry_caches=None,    # pytree threaded through the scan as carry
    return_hidden: bool = False,
    moe_token_mask=None,  # [B, S] bool: MoE routing validity (padding /
                          # inactive decode slots never consume capacity)
    return_moe_stats: bool = False,  # also return {"dropped": int32} —
                          # MoE capacity-overflow drops summed over layers
    adapter_ids=None,     # [B, S] i32: per-token multi-LoRA pool slot
                          # (0 = identity); None = no batched adapters
):
    """Run the decoder.

    Two cache protocols:

    - ``layer_caches`` (prefill): the scan slices a per-layer view as xs;
      ``attn_fn(q, k, v, layer_cache, pos)`` returns the attention output;
      returns (logits, kv) with kv = fresh K/V stacked [L, B, S, KVH, D]
      for the caller's one-shot scatter into the paged pool.
    - ``carry_caches`` (decode): the FULL cache pytree threads through the
      scan carry; ``attn_fn(q, k, v, (caches, layer_idx), pos)`` returns
      ``(out, new_caches)`` and updates the pool itself (the Pallas kernel
      writes the token's K/V in place) — no stacked kv, no scatter, no
      pool-sized layout copies in the loop.  Returns (logits, caches).
    """
    from helix_tpu.ops.quant import embed_lookup

    inv_freq = jnp.asarray(
        rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    )
    h = embed_lookup(params["embed"], tokens, jnp.dtype(cfg.dtype))

    def block(h, layer_params, layer_cache):
        return _layer(
            h, layer_params, layer_cache, cfg, positions, inv_freq,
            attn_fn, moe_token_mask=moe_token_mask,
            adapter_ids=adapter_ids,
        )

    h, kv, moe_dropped = scan_decoder_blocks(
        h, params["layers"], cfg.num_layers, block, layer_caches,
        carry_caches,
    )
    h = rms_norm(h, params["final_norm"]["weight"], cfg.rms_norm_eps, cfg.norm_offset)
    if return_hidden:
        return h, kv
    if cfg.tie_word_embeddings:
        w_out = params["embed"]["weight"].T
        out_scale = params["embed"].get("embed_scale")  # [V, 1] if quantized
        out_scale = None if out_scale is None else out_scale[:, 0]
    else:
        w_out = params["lm_head"]["weight"]
        out_scale = params["lm_head"].get("scale")
        out_scale = None if out_scale is None else out_scale.reshape(-1)
    if w_out.dtype == jnp.int8:
        w_out = w_out.astype(h.dtype)
    logits = jax.lax.dot_general(
        h, w_out, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if out_scale is not None:
        logits = logits * out_scale[None, None, :]
    if cfg.logits_soft_cap:
        logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
    if return_moe_stats:
        return logits, kv, {"dropped": moe_dropped}
    return logits, kv


def prefill_attn_fn(q, k, v, layer_cache, positions, *, segment_ids=None,
                    backend=None, soft_cap=None):
    """Self-attention over the freshly computed K/V (no history)."""
    from helix_tpu.ops.attention import attention

    return attention(
        q, k, v,
        causal=True,
        q_positions=positions,
        kv_positions=positions,
        q_segment_ids=segment_ids,
        kv_segment_ids=segment_ids,
        logits_soft_cap=soft_cap,
        backend=backend,
    )
