"""Model configuration shared across families.

One config dataclass covers the decoder families the reference serves via
vLLM compose profiles (Llama-3, Phi-3, Qwen-2/3 — see
``design/sample-profiles/`` and BASELINE.md configs); family-specific
behaviour is expressed as data (activation, norm offsets, qk-norm, soft
caps), not subclasses, so one compiled forward function serves them all.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 500000.0
    # stored as a sorted tuple of (key, value) pairs so the config stays
    # hashable (it keys compiled-function caches); None = no scaling
    rope_scaling: Optional[tuple] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    hidden_act: str = "silu"            # silu | gelu | gelu_tanh
    attention_bias: bool = False        # qkv bias (Qwen2)
    mlp_bias: bool = False
    qk_norm: bool = False               # per-head RMSNorm on q/k (Qwen3)
    logits_soft_cap: Optional[float] = None
    attn_logits_soft_cap: Optional[float] = None
    norm_offset: float = 0.0            # 1.0 for Gemma-style (1+w) RMSNorm
    max_position_embeddings: int = 8192
    dtype: str = "bfloat16"
    # multimodal rope sections (t, h, w) — set => Qwen2-VL-family text tower
    mrope_sections: Optional[tuple] = None
    # --- mixture of experts (Mixtral family); 0 = dense FFN ---
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # per-expert token capacity = factor * tokens * k / num_experts
    # (GShard-style dispatch; overflow tokens fall back to the residual)
    expert_capacity_factor: float = 1.5
    # --- non-architectural serving metadata ---
    name: str = "unnamed"

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @classmethod
    def from_hf_config(cls, hf: dict, name: str = "unnamed") -> "ModelConfig":
        """Build from a HuggingFace ``config.json`` dict (Llama/Qwen/Phi/
        Mistral-style decoder configs)."""
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        model_type = hf.get("model_type", "llama")
        rs = hf.get("rope_scaling") or {}
        mrope = (
            tuple(rs["mrope_section"]) if "mrope_section" in rs else None
        )
        # mrope is not a frequency scaling; store real scalings as a sorted
        # tuple so the config stays hashable
        rope_scaling = None
        if rs and mrope is None:
            rope_scaling = tuple(sorted(rs.items()))
        return cls(
            mrope_sections=mrope,
            vocab_size=hf["vocab_size"],
            hidden_size=hidden,
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=hf.get("num_key_value_heads", heads),
            head_dim=hf.get("head_dim") or hidden // heads,
            intermediate_size=hf["intermediate_size"],
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            hidden_act=hf.get("hidden_act", "silu"),
            attention_bias=hf.get("attention_bias", False)
            or model_type == "qwen2",
            mlp_bias=hf.get("mlp_bias", False),
            qk_norm=model_type == "qwen3",
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            num_experts=hf.get("num_local_experts", 0),
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
            name=name,
        )

    @classmethod
    def tiny(cls, **overrides) -> "ModelConfig":
        """A toy config for tests (fast to init/compile on one CPU core)."""
        base = dict(
            vocab_size=256,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            rope_theta=10000.0,
            max_position_embeddings=512,
            name="tiny",
        )
        base.update(overrides)
        return cls(**base)


# Canonical catalogue entries for the BASELINE.md configs — architecture
# hyperparameters only (weights come from HF checkpoints via
# ``models/loader.py``).
LLAMA3_8B = ModelConfig(
    vocab_size=128256,
    hidden_size=4096,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    rope_theta=500000.0,
    rms_norm_eps=1e-5,
    max_position_embeddings=8192,
    name="meta-llama/Meta-Llama-3-8B-Instruct",
)

PHI3_MINI = ModelConfig(
    vocab_size=32064,
    hidden_size=3072,
    num_layers=32,
    num_heads=32,
    num_kv_heads=32,
    head_dim=96,
    intermediate_size=8192,
    rope_theta=10000.0,
    max_position_embeddings=4096,
    name="microsoft/Phi-3-mini-4k-instruct",
)

QWEN2_7B = ModelConfig(
    vocab_size=152064,
    hidden_size=3584,
    num_layers=28,
    num_heads=28,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=18944,
    rope_theta=1000000.0,
    attention_bias=True,
    max_position_embeddings=32768,
    name="Qwen/Qwen2-7B-Instruct",
)

MIXTRAL_8X7B = ModelConfig(
    vocab_size=32000,
    hidden_size=4096,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    rope_theta=1000000.0,
    max_position_embeddings=32768,
    num_experts=8,
    num_experts_per_tok=2,
    name="mistralai/Mixtral-8x7B-Instruct-v0.1",
)

CATALOG = {
    m.name: m
    for m in (LLAMA3_8B, PHI3_MINI, QWEN2_7B, MIXTRAL_8X7B)
}
