"""Mixture-of-experts FFN: GShard-style top-k dispatch on the MXU.

The reference serves Mixtral through vLLM's fused CUDA MoE kernels
(SURVEY.md §2.2 model families); the TPU-native formulation is the
GShard/Switch dispatch algebra — everything is dense einsums over a
``[experts, capacity]`` buffer, so XLA tiles it onto the MXU and, when
the mesh carries an ``ep`` axis, shards the expert dimension and inserts
the all-to-alls (the layout jax-ml's scaling guidance prescribes for
MoE):

- router: per-token logits over experts, softmax, top-k;
- capacity: each expert processes at most ``C = factor * T * k / X``
  tokens per call — a STATIC shape, which is the whole point: ragged
  per-expert batches don't exist under jit. Tokens that overflow an
  expert's capacity are dropped from that expert (their combine weight
  is zero) and ride the residual stream, the standard GShard fallback;
- dispatch/combine: one-hot ``[T, X, C]`` masks move tokens into and out
  of the expert buffers with two einsums; the expert FFNs themselves are
  a single batched SwiGLU over stacked ``[X, E, F]`` weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _expert_dense(h_in, wp, spec):
    """Batched per-expert matmul over stacked weights, feeding int8
    weight-only storage DIRECTLY into the einsum (mixed-precision dot:
    XLA converts the int8 operand in VMEM after the halved HBM fetch —
    ops/quant.py maybe_dequant_dense's convention) and rescaling the
    output per channel."""
    w = wp["weight"]
    out = jnp.einsum(spec, h_in, w, preferred_element_type=jnp.float32)
    scale = wp.get("scale")
    if scale is not None:
        # scale: [X, 1, out] — broadcasts over the capacity dim
        out = out * scale.astype(jnp.float32)
    return out


def moe_ffn(x, router_p, experts_p, cfg, act, token_mask=None,
            return_dropped=False):
    """x: [B, S, E] -> [B, S, E] (or ``(out, dropped)`` with
    ``return_dropped``: the int32 count of (token, choice) routing
    assignments this call dropped to capacity overflow — the tokens that
    silently ride the residual stream instead of their expert.  Decode is
    dropless (C = T), so only prefill shapes ever report > 0).

    router_p: [E, X] (dequantised); experts_p: {"w_gate"/"w_up":
    {"weight": [X, E, F][, "scale"]}, "w_down": {...}} — int8
    weight-only trees pass through unchanged.

    token_mask [B, S] (optional): False tokens (padding, inactive decode
    slots) are EXCLUDED from routing entirely — they consume no expert
    capacity, so a request's outputs never depend on garbage riding the
    same batch.

    Capacity: C = factor * T * k / X for prefill shapes; decode (S == 1)
    runs DROPLESS (C = T) — the buffers are tiny at decode batch sizes
    and per-token determinism matters more than the dispatch saving."""
    B, S, E = x.shape
    X = cfg.num_experts
    k = cfg.num_experts_per_tok
    T = B * S
    xf = x.reshape(T, E)
    valid = (
        jnp.ones((T,), jnp.bool_)
        if token_mask is None
        else token_mask.reshape(T)
    )

    # --- router (fp32 for a stable softmax over few logits) ---
    logits = jnp.dot(
        xf.astype(jnp.float32), router_p.astype(jnp.float32)
    )                                               # [T, X]
    top_vals, top_idx = jax.lax.top_k(logits, k)    # [T, k]
    top_w = jax.nn.softmax(top_vals, axis=-1)       # renormalised over k

    # --- capacity + position of each (token, choice) in its expert ---
    if S == 1:
        C = T                                        # dropless decode
    else:
        C = max(int(cfg.expert_capacity_factor * T * k / X), 1)
    # choice-major flattening ranks first choices ahead of second
    # choices across the batch, so capacity overflow drops the weaker
    # assignments first; invalid tokens are routed to a sentinel so they
    # never occupy a capacity slot
    flat_idx = jnp.where(
        jnp.tile(valid, k), top_idx.T.reshape(-1), X
    )                                               # [k*T] expert ids
    onehot = jax.nn.one_hot(flat_idx, X, dtype=jnp.int32)   # [kT, X]
    pos_in_expert = (
        jnp.cumsum(onehot, axis=0) - onehot
    )                                               # [kT, X]
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [kT]
    keep = (pos < C) & (flat_idx < X)
    # capacity-overflow accounting: a valid assignment (real token, real
    # expert) whose position overflowed C — exactly the work that falls
    # back to the residual stream
    dropped = jnp.sum(((~keep) & (flat_idx < X)).astype(jnp.int32))
    # back to [T, k]
    pos = pos.reshape(k, T).T
    keep = keep.reshape(k, T).T

    # --- dispatch/combine tensors ---
    # dispatch[t, x, c] = 1 where token t's choice lands at slot c of
    # expert x; combine carries the softmax weight on the same support
    dispatch = jnp.zeros((T, X, C), jnp.float32)
    combine = jnp.zeros((T, X, C), jnp.float32)
    for j in range(k):      # k is 2: an unrolled static loop
        sel = (
            jax.nn.one_hot(top_idx[:, j], X, dtype=jnp.float32)[:, :, None]
            * jax.nn.one_hot(pos[:, j], C, dtype=jnp.float32)[:, None, :]
            * keep[:, j, None, None].astype(jnp.float32)
        )
        dispatch = dispatch + sel
        combine = combine + sel * top_w[:, j, None, None]

    # --- expert buffers + batched SwiGLU over stacked weights ---
    expert_in = jnp.einsum(
        "txc,te->xce", dispatch.astype(x.dtype), xf
    )                                                       # [X, C, E]
    gate = _expert_dense(expert_in, experts_p["w_gate"], "xce,xef->xcf")
    up = _expert_dense(expert_in, experts_p["w_up"], "xce,xef->xcf")
    h = _expert_dense(
        (act(gate) * up).astype(x.dtype), experts_p["w_down"],
        "xcf,xfe->xce",
    )                                                       # [X, C, E]
    out = jnp.einsum("txc,xce->te", combine, h)
    out = out.reshape(B, S, E).astype(x.dtype)
    if return_dropped:
        return out, dropped
    return out
