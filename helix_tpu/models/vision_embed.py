"""Vision embedding worker: images + text into one vector space.

Reference: the vision-RAG path serves a *vision embedding* model as a
vLLM pooling runner (SURVEY.md §2.5 "Vision RAG": Qwen3-VL-Embedding in
``design/sample-profiles/8xH100-vllm.yaml:15-43``) so image documents
and text queries meet in one index.  Round-2 shipped VL *chat* only
(VERDICT §2.5 #60: "no vision embedding worker").

TPU-first design: the Qwen2-VL vision tower already projects patches
into the text model's hidden space (``models/qwen2_vl.vision_forward``),
so a shared text/image space comes from the model itself:

- image  -> vision tower -> mean-pool over patch embeddings -> L2 norm
- text   -> token-embedding lookup -> mean-pool -> L2 norm

Pooling runs in one jit per shape bucket; the tower batch is the
concatenated patch sequence (dense MXU work, no per-image dispatch).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class VisionEmbeddingRunner:
    """Batched pooling worker behind /v1/embeddings for image+text input."""

    def __init__(self, model_cfg, vcfg, params, vparams, tokenizer,
                 max_pixels: int = 14 * 14 * 4 * 1280):
        self.model_cfg = model_cfg
        self.vcfg = vcfg
        self.params = params          # text params (embed table used)
        self.vparams = vparams
        self.tokenizer = tokenizer
        self.max_pixels = max_pixels

    @classmethod
    def build(cls, pm, tokenizer) -> "VisionEmbeddingRunner":
        import dataclasses

        from helix_tpu.models.common import ModelConfig
        from helix_tpu.models.llama import init_params
        from helix_tpu.models.qwen2_vl import (
            VisionConfig,
            init_vision_params,
            load_qwen2_vl,
        )

        if pm.checkpoint:
            model_cfg, vcfg, params = load_qwen2_vl(pm.checkpoint)
            model_cfg = dataclasses.replace(model_cfg, name=pm.name)
            vparams = params.pop("visual")
        else:
            model_cfg = ModelConfig.tiny(
                name=pm.name,
                vocab_size=max(getattr(tokenizer, "vocab_size", 512), 512),
            )
            params = init_params(model_cfg, jax.random.PRNGKey(0))
            vcfg = VisionConfig.tiny(hidden_size=model_cfg.hidden_size)
            vparams = init_vision_params(vcfg, jax.random.PRNGKey(1))
        return cls(model_cfg, vcfg, params, vparams, tokenizer)

    # -- pooling jits --------------------------------------------------------
    @functools.cached_property
    def _pool_text(self):
        from helix_tpu.ops.quant import embed_lookup

        @jax.jit
        def pool(embed_params, tokens, mask):
            # embed_lookup handles both plain and row-quantized (int8 +
            # embed_scale) tables — hand-rolled dequant here previously
            # risked pooling raw int8 rows into garbage vectors
            emb = embed_lookup(embed_params, tokens, jnp.float32)
            m = mask[..., None].astype(emb.dtype)
            summed = (emb * m).sum(axis=1)
            count = jnp.maximum(m.sum(axis=1), 1.0)
            v = summed / count
            return v / jnp.maximum(
                jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-9
            )

        return pool

    # -- public API ----------------------------------------------------------
    def embed_texts(self, texts) -> np.ndarray:
        """Mean-pooled, L2-normalised token embeddings (shared space with
        the vision tower's projection)."""
        if not texts:
            return np.zeros((0, self.model_cfg.hidden_size), np.float32)
        token_lists = [
            self.tokenizer.encode(t)[
                : self.model_cfg.max_position_embeddings
            ]
            or [0]
            for t in texts
        ]
        S = 1
        maxlen = max(len(t) for t in token_lists)
        while S < maxlen:
            S *= 2
        B = len(token_lists)
        toks = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), np.int32)
        for i, t in enumerate(token_lists):
            toks[i, : len(t)] = t
            mask[i, : len(t)] = 1
        out = self._pool_text(
            self.params["embed"], jnp.asarray(toks), jnp.asarray(mask)
        )
        return np.asarray(out, np.float32)

    def embed_images(self, sources) -> np.ndarray:
        """-> [N, E] pooled vision-tower embeddings; ``sources`` are data
        URLs / base64 / raw arrays (``serving.vision.decode_image``)."""
        from helix_tpu.models.qwen2_vl import vision_forward
        from helix_tpu.serving.vision import decode_image, patchify

        if not sources:
            return np.zeros((0, self.model_cfg.hidden_size), np.float32)
        out = []
        for src in sources:
            img = decode_image(src)
            patches, grid = patchify(
                img,
                patch_size=self.vcfg.patch_size,
                temporal_patch_size=self.vcfg.temporal_patch_size,
                merge_size=self.vcfg.spatial_merge_size,
                max_pixels=self.max_pixels,
            )
            emb = vision_forward(
                self.vparams, self.vcfg, jnp.asarray(patches),
                [grid],
            )                                            # [T, E]
            v = np.asarray(emb, np.float32).mean(axis=0)
            v = v / max(float(np.linalg.norm(v)), 1e-9)
            out.append(v)
        return np.stack(out)

    def embed_mixed(self, inputs) -> np.ndarray:
        """OpenAI /v1/embeddings input list where each entry is a string
        OR {"image": <url/b64>} — order preserved."""
        out: list = [None] * len(inputs)
        texts, t_idx, images, i_idx = [], [], [], []
        for i, item in enumerate(inputs):
            if isinstance(item, dict) and "image" in item:
                images.append(item["image"])
                i_idx.append(i)
            else:
                texts.append(str(item))
                t_idx.append(i)
        for i, v in zip(t_idx, self.embed_texts(texts)):
            out[i] = v
        for i, v in zip(i_idx, self.embed_images(images)):
            out[i] = v
        return np.stack(out) if out else np.zeros(
            (0, self.model_cfg.hidden_size), np.float32
        )
