"""Small shared utilities for the helix-tpu runtime."""

from __future__ import annotations

import os
import secrets as pysecrets


def load_or_create_keyfile(path: str, nbytes: int = 32) -> bytes:
    """Read a secret key file, creating it atomically if absent.

    Concurrency-safe across processes sharing ``path``: creation uses a
    0600 temp file hard-linked into place (``os.link`` fails if the file
    already exists, so a loser of the race re-reads the winner's key —
    nobody ever deletes or clobbers a live key). A truncated file (crash
    mid-write of an older implementation) is atomically replaced via
    ``os.rename``. All processes converge on whatever is on disk.
    """
    for _ in range(20):
        truncated = False
        try:
            with open(path, "rb") as f:
                key = f.read()
            if len(key) >= nbytes:
                return key
            truncated = True
        except FileNotFoundError:
            pass
        key = pysecrets.token_bytes(nbytes)
        tmp = f"{path}.tmp.{os.getpid()}.{pysecrets.token_hex(4)}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key)
        if truncated:
            os.rename(tmp, path)  # atomic replace of the garbage file
        else:
            try:
                os.link(tmp, path)  # create-if-absent, never clobber
            except FileExistsError:
                pass  # lost the race — loop re-reads the winner's key
            os.unlink(tmp)
        # fall through to re-read so every caller returns the on-disk key
    raise RuntimeError(f"could not create or read key file {path}")


def like_escape(q: str) -> str:
    """Escape SQL LIKE metacharacters so a user query matches literally
    (pair with ``LIKE ? ESCAPE '\\'``).  Backslash must be escaped FIRST
    or it would double-escape the %/_ replacements."""
    return (
        q.replace("\\", "\\\\").replace("%", r"\%").replace("_", r"\_")
    )
