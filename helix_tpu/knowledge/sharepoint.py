"""SharePoint knowledge source: Microsoft Graph drive walking + download.

Reference: ``api/pkg/sharepoint/client.go`` (site lookup by id/URL,
default drive, recursive folder listing with extension filters, download
via ``@microsoft.graph.downloadUrl``) driven from the knowledge
reconciler (``knowledge_extract.go:423 extractDataFromSharePoint``) with
the owner's Microsoft OAuth connection supplying the bearer token.

The HTTP layer is injectable (``http_fn``) so tests run against a fake
Graph server and so the knowledge manager can plug in its own fetcher.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import urllib.parse
import urllib.request
from typing import Callable, Optional

log = logging.getLogger("helix.sharepoint")

GRAPH_BASE = "https://graph.microsoft.com/v1.0"


@dataclasses.dataclass
class SharePointSource:
    """Source config (reference: ``types.KnowledgeSourceSharePoint``)."""

    site_id: str = ""
    site_url: str = ""                  # alternative to site_id
    drive_id: str = ""                  # empty = site default drive
    folder_path: str = ""               # empty = drive root
    recursive: bool = True
    extensions: tuple = ()              # (".docx", ".pdf"); empty = all
    oauth_provider: str = "microsoft"   # token source

    @classmethod
    def from_doc(cls, doc: dict) -> "SharePointSource":
        return cls(
            site_id=doc.get("site_id", ""),
            site_url=doc.get("site_url", ""),
            drive_id=doc.get("drive_id", ""),
            folder_path=doc.get("folder_path", ""),
            recursive=bool(doc.get("recursive", True)),
            extensions=tuple(
                e.lower() if e.startswith(".") else f".{e.lower()}"
                for e in doc.get("extensions", [])
            ),
            oauth_provider=doc.get("oauth_provider", "microsoft"),
        )


class SharePointClient:
    def __init__(
        self,
        token: str,
        base_url: str = GRAPH_BASE,
        http_fn: Optional[Callable] = None,
    ):
        self.token = token
        self.base_url = base_url.rstrip("/")
        self._http = http_fn or self._default_http

    def _default_http(self, url: str, headers: dict) -> bytes:
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.read()

    def _get(self, path: str) -> dict:
        url = (
            path if path.startswith("http") else f"{self.base_url}{path}"
        )
        raw = self._http(
            url, {"Authorization": f"Bearer {self.token}"}
        )
        return json.loads(raw)

    # -- sites / drives -----------------------------------------------------
    def site_by_url(self, site_url: str) -> dict:
        """https://contoso.sharepoint.com/sites/Team ->
        GET /sites/contoso.sharepoint.com:/sites/Team
        (reference client.go:136 GetSiteByURL)."""
        p = urllib.parse.urlparse(site_url)
        return self._get(f"/sites/{p.netloc}:{p.path}")

    def default_drive(self, site_id: str) -> dict:
        return self._get(f"/sites/{site_id}/drive")

    def resolve(self, src: SharePointSource) -> tuple:
        """-> (site_id, drive_id)"""
        site_id = src.site_id
        if not site_id and src.site_url:
            site_id = self.site_by_url(src.site_url)["id"]
        if not site_id:
            raise ValueError("sharepoint source needs site_id or site_url")
        drive_id = src.drive_id or self.default_drive(site_id)["id"]
        return site_id, drive_id

    # -- files --------------------------------------------------------------
    def list_files(
        self, src: SharePointSource, drive_id: str = ""
    ) -> list:
        """-> [DriveItem dicts] honoring folder_path / recursive /
        extension filter (reference client.go:188-281). Pass an already-
        resolved ``drive_id`` to skip the site/drive lookup round-trips."""
        if not drive_id:
            _, drive_id = self.resolve(src)
        if src.folder_path:
            quoted = urllib.parse.quote(src.folder_path.strip("/"))
            root = f"/drives/{drive_id}/root:/{quoted}:/children"
        else:
            root = f"/drives/{drive_id}/root/children"
        out: list = []
        self._walk(drive_id, root, src, out)
        return out

    def _walk(self, drive_id: str, path: str, src, out: list) -> None:
        page: Optional[str] = path
        while page:
            doc = self._get(page)
            for item in doc.get("value", []):
                if "folder" in item:
                    if src.recursive:
                        self._walk(
                            drive_id,
                            f"/drives/{drive_id}/items/{item['id']}"
                            "/children",
                            src, out,
                        )
                    continue
                if "file" not in item:
                    continue
                if src.extensions:
                    name = item.get("name", "").lower()
                    if not any(name.endswith(e) for e in src.extensions):
                        continue
                out.append(item)
            page = doc.get("@odata.nextLink")

    def download(self, drive_id: str, item: dict) -> bytes:
        """Prefer the pre-authenticated downloadUrl; fall back to the
        /content endpoint (reference client.go:283-356)."""
        url = item.get("@microsoft.graph.downloadUrl")
        if url:
            return self._http(url, {})
        return self._http(
            f"{self.base_url}/drives/{drive_id}/items/{item['id']}/content",
            {"Authorization": f"Bearer {self.token}"},
        )


def gather_sharepoint(
    src_doc: dict, token: str, base_url: str = GRAPH_BASE,
    http_fn: Optional[Callable] = None,
    progress: Optional[Callable[[int, int, str], None]] = None,
) -> list:
    """-> [(text, meta)] documents for the knowledge indexer."""
    from helix_tpu.knowledge.extract_binary import extract_any

    src = SharePointSource.from_doc(src_doc)
    client = SharePointClient(token, base_url=base_url, http_fn=http_fn)
    _, drive_id = client.resolve(src)
    files = client.list_files(src, drive_id=drive_id)
    docs: list = []
    for i, item in enumerate(files):
        name = item.get("name", "")
        if progress:
            progress(i, len(files), name)
        try:
            data = client.download(drive_id, item)
        except Exception as e:  # noqa: BLE001 — skip bad file, keep going
            log.warning("sharepoint download failed for %s: %s", name, e)
            continue
        text = extract_any(data, name)
        if text.strip():
            docs.append(
                (
                    text,
                    {
                        "source": item.get("webUrl", name),
                        "title": name,
                        "sharepoint_id": item.get("id", ""),
                    },
                )
            )
    return docs
