"""Text extraction + chunking for knowledge ingestion.

The reference pipeline is crawler -> extractor service -> splitter ->
indexer (``api/pkg/controller/knowledge/``); extraction here is in-process
(markdown/HTML/plain), splitting is paragraph-aware with overlap.
"""

from __future__ import annotations

import re
from html.parser import HTMLParser


class _HTMLText(HTMLParser):
    SKIP = {"script", "style", "noscript", "head"}

    def __init__(self):
        super().__init__()
        self.parts: list = []
        self._skip_depth = 0

    def handle_starttag(self, tag, attrs):
        if tag in self.SKIP:
            self._skip_depth += 1

    def handle_endtag(self, tag):
        if tag in self.SKIP and self._skip_depth:
            self._skip_depth -= 1

    def handle_data(self, data):
        if not self._skip_depth and data.strip():
            self.parts.append(data.strip())


def extract_text(content: str, content_type: str = "text/plain") -> str:
    """HTML/markdown/plain -> clean text (the extractor-service stand-in,
    reference ``api/pkg/extract/extract.go:22-29`` calls out over HTTP)."""
    if "html" in content_type:
        p = _HTMLText()
        p.feed(content)
        return "\n".join(p.parts)
    # markdown: strip the common syntax, keep prose
    text = re.sub(r"```.*?```", "", content, flags=re.S)
    text = re.sub(r"`([^`]*)`", r"\1", text)
    text = re.sub(r"!\[[^\]]*\]\([^)]*\)", "", text)
    text = re.sub(r"\[([^\]]*)\]\([^)]*\)", r"\1", text)
    text = re.sub(r"^#+\s*", "", text, flags=re.M)
    text = re.sub(r"[*_]{1,3}([^*_]+)[*_]{1,3}", r"\1", text)
    return text.strip()


def split_text(
    text: str,
    chunk_size: int = 1000,
    overlap: int = 100,
) -> list:
    """Paragraph-aware sliding chunks of ~chunk_size chars with overlap."""
    paragraphs = [p.strip() for p in re.split(r"\n\s*\n", text) if p.strip()]
    chunks: list = []
    cur = ""
    for p in paragraphs:
        if len(cur) + len(p) + 1 <= chunk_size:
            cur = f"{cur}\n{p}".strip()
            continue
        if cur:
            chunks.append(cur)
            tail = cur[-overlap:] if overlap else ""
            cur = (tail + "\n" + p).strip()
        else:
            cur = p
        # hard-split any paragraph that alone exceeds the chunk size
        while len(cur) > chunk_size:
            chunks.append(cur[:chunk_size])
            cur = cur[chunk_size - overlap :] if overlap else cur[chunk_size:]
    if cur:
        chunks.append(cur)
    return chunks
