"""Browser pool for crawling and agent browsing — the reference bundles a
Chrome container driven through a rod/CDP pool for its crawler and browser
skill (``api/cmd/helix/serve.go:356-372``, knowledge crawler "Chrome/rod
browser pool + readability", SURVEY.md §2.5).  A TPU node image has no
Chrome, so the pool manages *fetcher* instances behind one seam:

- :class:`HttpBrowser` — requests-based page fetch + a readability-style
  main-content extractor (text-density scoring over block elements, link
  text discounted), title + outbound links.  No JS execution; this is the
  zero-dependency default.
- :class:`CdpBrowser` — drives a real Chromium over the DevTools protocol
  when ``HELIX_CHROME_BIN`` points at one (launch headless, navigate, pull
  rendered HTML).  The class is the seam the reference's rod pool fills;
  constructing it without a binary raises a clear error.

Pool semantics mirror the reference's: a bounded set of instances, leases
with a wait deadline, recycle-after-N-pages (rod restarts Chrome to bound
leaks), and crash replacement.
"""

from __future__ import annotations

import html
import html.parser
import os
import queue
import re
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass
class Page:
    url: str
    title: str
    text: str          # readability-extracted main content
    html: str
    links: List[str] = field(default_factory=list)


_BLOCK_TAGS = {
    "p", "div", "article", "section", "main", "td", "li", "pre",
    "blockquote", "h1", "h2", "h3", "h4",
}
_SKIP_TAGS = {"script", "style", "noscript", "svg", "head", "template"}
_BOILERPLATE_TAGS = {"nav", "footer", "aside", "header", "form"}


class _ReadabilityParser(html.parser.HTMLParser):
    """Single-pass text-density extractor.

    Scores each block element by its direct text mass, discounting text
    inside <a> (menus/footers are link-dense) and anything under
    boilerplate containers; the page text is the concatenation of blocks
    whose score clears a fraction of the best block's.  The same
    density-vs-link-ratio heuristic readability/trafilatura use, sized for
    a stdlib parser."""

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.title = ""
        self._in_title = False
        self._skip_depth = 0
        self._boiler_depth = 0
        self._link_depth = 0
        self._stack: list = []           # (tag, [text parts], link_chars)
        self.blocks: list = []           # (score, text)
        self.links: list = []

    def handle_starttag(self, tag, attrs):
        if tag == "title":
            self._in_title = True
        if tag in _SKIP_TAGS:
            self._skip_depth += 1
        if tag in _BOILERPLATE_TAGS:
            self._boiler_depth += 1
        if tag == "a":
            self._link_depth += 1
            href = dict(attrs).get("href")
            if href:
                self.links.append(href)
        if tag in _BLOCK_TAGS:
            self._stack.append([tag, [], 0])

    def handle_endtag(self, tag):
        if tag == "title":
            self._in_title = False
        if tag in _SKIP_TAGS and self._skip_depth:
            self._skip_depth -= 1
        if tag in _BOILERPLATE_TAGS and self._boiler_depth:
            self._boiler_depth -= 1
        if tag == "a" and self._link_depth:
            self._link_depth -= 1
        if tag in _BLOCK_TAGS and self._stack:
            # close the innermost matching block
            for i in range(len(self._stack) - 1, -1, -1):
                if self._stack[i][0] == tag:
                    _t, parts, link_chars = self._stack.pop(i)
                    text = re.sub(r"\s+", " ", "".join(parts)).strip()
                    if text:
                        # link-dense rows (menus) score near zero
                        density = 1.0 - min(link_chars / max(len(text), 1), 1.0)
                        score = len(text) * (0.1 + 0.9 * density)
                        if self._boiler_depth:
                            score *= 0.05
                        self.blocks.append((score, text))
                    break

    def handle_data(self, data):
        if self._in_title:
            self.title += data
            return
        if self._skip_depth or not self._stack:
            return
        self._stack[-1][1].append(data)
        if self._link_depth:
            self._stack[-1][2] += len(data)


def extract_readable(html_src: str) -> tuple:
    """-> (title, main_text, links)."""
    p = _ReadabilityParser()
    try:
        p.feed(html_src)
    except Exception:  # noqa: BLE001 — malformed markup: keep what parsed
        pass
    if not p.blocks:
        return p.title.strip(), "", p.links
    best = max(s for s, _ in p.blocks)
    keep = [t for s, t in p.blocks if s >= max(best * 0.05, 20)]
    return p.title.strip(), "\n".join(keep), p.links


class HttpBrowser:
    """JS-less fetcher + readability. One 'browser instance' of the pool."""

    def __init__(self, fetch: Optional[Callable] = None):
        from helix_tpu.knowledge.crawler import default_fetch

        self._fetch = fetch or default_fetch
        self.pages_served = 0
        self.alive = True

    def fetch(self, url: str, timeout: float = 15.0) -> Page:
        content, ctype = self._fetch(url, timeout=timeout)
        self.pages_served += 1
        if "html" not in (ctype or "html"):
            return Page(url=url, title="", text=content, html="", links=[])
        title, text, links = extract_readable(content)
        links = [
            urllib.parse.urljoin(url, h)
            for h in links
            if not h.startswith(("javascript:", "mailto:", "#"))
        ]
        return Page(url=url, title=title, text=text, html=content,
                    links=links)

    def close(self):
        self.alive = False


class CdpBrowser:
    """Chromium over the DevTools protocol — the seam the reference's rod
    pool fills.  Requires HELIX_CHROME_BIN; kept import-light so the
    framework runs where no browser exists."""

    def __init__(self, fetch: Optional[Callable] = None):
        self.bin = os.environ.get("HELIX_CHROME_BIN", "")
        if not self.bin or not os.path.exists(self.bin):
            raise RuntimeError(
                "CdpBrowser needs HELIX_CHROME_BIN pointing at a Chromium "
                "binary; use HttpBrowser on browserless nodes"
            )
        self.pages_served = 0
        self.alive = True
        self._proc = None

    def fetch(self, url: str, timeout: float = 30.0) -> Page:
        raise NotImplementedError(
            "CDP drive-path lands with a Chromium-bearing image"
        )

    def close(self):
        self.alive = False
        if self._proc:
            self._proc.terminate()


class BrowserPool:
    """Bounded lease pool with recycle-after-N-pages and crash replacement."""

    def __init__(self, size: int = 2, max_pages: int = 100,
                 factory: Optional[Callable] = None):
        self.size = size
        self.max_pages = max_pages
        self.factory = factory or HttpBrowser
        self._idle: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._created = 0
        self._recycled = 0
        for _ in range(size):
            self._idle.put(self._new())

    def _new(self):
        with self._lock:
            self._created += 1
        return self.factory()

    def lease(self, timeout: float = 30.0):
        """Context manager: ``with pool.lease() as browser: ...``"""
        pool = self

        class _Lease:
            def __enter__(self):
                try:
                    self.browser = pool._idle.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError(
                        f"no browser free within {timeout}s"
                    ) from None
                return self.browser

            def __exit__(self, exc_type, exc, tb):
                b = self.browser
                if (
                    exc_type is not None
                    or not b.alive
                    or b.pages_served >= pool.max_pages
                ):
                    # crashed or aged out: replace (rod restarts Chrome)
                    try:
                        b.close()
                    except Exception:  # noqa: BLE001
                        pass
                    with pool._lock:
                        pool._recycled += 1
                    b = pool._new()
                pool._idle.put(b)
                return False

        return _Lease()

    def fetch(self, url: str, timeout: float = 15.0) -> Page:
        with self.lease() as b:
            return b.fetch(url, timeout=timeout)

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "size": self.size, "created": self._created,
                "recycled": self._recycled, "idle": self._idle.qsize(),
            }

    def close(self):
        while True:
            try:
                self._idle.get_nowait().close()
            except queue.Empty:
                break
