"""Embedding clients for knowledge ingestion.

- ``RemoteEmbedder``: sync client for any /v1/embeddings surface (a TPU
  runner's bge worker — BASELINE config 2 — or an external provider).
- ``HashEmbedder``: deterministic character-n-gram feature hashing. Zero
  dependencies, zero models; makes knowledge/RAG functional out of the box
  and in tests, with the same interface the learned embedder fills later.
"""

from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np


class HashEmbedder:
    def __init__(self, dim: int = 256, ngram: int = 3):
        self.dim = dim
        self.ngram = ngram

    def __call__(self, texts: list) -> np.ndarray:
        out = np.zeros((len(texts), self.dim), np.float32)
        for i, t in enumerate(texts):
            t = f"  {t.lower()}  "
            for j in range(len(t) - self.ngram + 1):
                g = t[j : j + self.ngram].encode()
                h = int.from_bytes(
                    hashlib.blake2b(g, digest_size=8).digest(), "little"
                )
                idx = h % self.dim
                sign = 1.0 if (h >> 63) & 1 else -1.0
                out[i, idx] += sign
            n = np.linalg.norm(out[i])
            if n > 0:
                out[i] /= n
        return out


class RemoteEmbedder:
    """Sync /v1/embeddings client; ``pick_address`` resolves lazily so a
    router-backed deployment keeps working across runner churn."""

    def __init__(self, model: str, base_url=None, pick_address=None,
                 api_key: str = "", timeout: float = 120.0):
        self.model = model
        self.base_url = base_url
        self.pick_address = pick_address
        self.api_key = api_key
        self.timeout = timeout

    def __call__(self, texts: list) -> np.ndarray:
        import requests

        base = self.base_url or (self.pick_address and self.pick_address())
        if not base:
            raise RuntimeError("no embeddings endpoint available")
        headers = {}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        r = requests.post(
            f"{base}/v1/embeddings",
            json={"model": self.model, "input": list(texts)},
            headers=headers,
            timeout=self.timeout,
        )
        r.raise_for_status()
        data = r.json()["data"]
        return np.asarray([d["embedding"] for d in data], np.float32)
