from helix_tpu.knowledge.vector_store import VectorStore
from helix_tpu.knowledge.splitter import split_text
from helix_tpu.knowledge.ingest import KnowledgeManager, KnowledgeSpec

__all__ = ["VectorStore", "split_text", "KnowledgeManager", "KnowledgeSpec"]
