"""Bundled metasearch service — the SearXNG the reference ships beside its
control plane (``api/cmd/helix/serve.go:375-382``, ``api/pkg/searxng/``,
prod compose runs a ``searxng`` container).  Instead of depending on an
external metasearch container, the aggregator is part of the framework:

- engine adapters (searx-compatible JSON, MediaWiki, DuckDuckGo-lite HTML,
  generic JSON templates) normalise per-engine results;
- a query fans out to all configured engines in parallel with a per-engine
  deadline; stragglers are dropped, not awaited;
- results dedup by canonical URL and merge with reciprocal-rank fusion
  (the rank aggregation SearXNG uses across engines);
- the HTTP surface (``/search?format=json`` on the control plane) speaks
  the searx wire shape, so the agent ``web_search`` skill — and any tool
  written against SearXNG — can point at our own server.

Engines come from ``HELIX_SEARCH_ENGINES`` (JSON list of adapter specs);
in a zero-egress deployment the list is empty and the endpoint degrades to
an explicit "no engines configured" error rather than hanging.
"""

from __future__ import annotations

import concurrent.futures
import html
import html.parser
import json
import os
import re
import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass
class SearchResult:
    title: str
    url: str
    content: str = ""
    engine: str = ""
    score: float = 0.0

    def to_dict(self) -> dict:
        return {
            "title": self.title, "url": self.url, "content": self.content,
            "engine": self.engine, "score": round(self.score, 4),
        }


def _canonical(url: str) -> str:
    """Dedup key: scheme/host lowered, default port + fragment + trailing
    slash + utm_* tracking params stripped."""
    p = urllib.parse.urlsplit(url.strip())
    host = (p.hostname or "").lower()
    if p.port and not (
        (p.scheme == "http" and p.port == 80)
        or (p.scheme == "https" and p.port == 443)
    ):
        host = f"{host}:{p.port}"
    q = [
        (k, v)
        for k, v in urllib.parse.parse_qsl(p.query, keep_blank_values=True)
        if not k.lower().startswith("utm_")
    ]
    return urllib.parse.urlunsplit(
        (p.scheme.lower(), host, p.path.rstrip("/") or "/",
         urllib.parse.urlencode(q), "")
    )


def default_fetch(url: str, timeout: float = 10.0) -> str:
    """Engine HTTP GET with the crawler's SSRF posture (private targets
    refused unless explicitly allowed)."""
    from helix_tpu.knowledge.crawler import default_fetch as crawl_fetch

    content, _ctype = crawl_fetch(url, timeout=timeout)
    return content


class Engine:
    """One upstream search engine."""

    name = "engine"
    weight = 1.0

    def search(self, query: str, fetch: Callable[[str], str]) -> List[SearchResult]:
        raise NotImplementedError


class SearxJsonEngine(Engine):
    """searx/SearXNG-compatible JSON endpoint (also: another helix node)."""

    def __init__(self, name: str, base_url: str, weight: float = 1.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.weight = weight

    def search(self, query, fetch):
        url = (
            f"{self.base_url}/search?"
            + urllib.parse.urlencode({"q": query, "format": "json"})
        )
        data = json.loads(fetch(url))
        out = []
        for r in data.get("results", []):
            if r.get("url"):
                out.append(SearchResult(
                    title=r.get("title", r["url"]),
                    url=r["url"],
                    content=r.get("content", ""),
                    engine=self.name,
                ))
        return out


class MediaWikiEngine(Engine):
    """MediaWiki opensearch API (wikipedia etc.)."""

    def __init__(self, name: str = "wikipedia",
                 base_url: str = "https://en.wikipedia.org",
                 weight: float = 1.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.weight = weight

    def search(self, query, fetch):
        url = (
            f"{self.base_url}/w/api.php?"
            + urllib.parse.urlencode({
                "action": "opensearch", "search": query, "limit": "10",
                "format": "json",
            })
        )
        data = json.loads(fetch(url))
        # opensearch: [query, [titles], [descriptions], [urls]]
        titles, descs, urls = (
            data[1], data[2] if len(data) > 2 else [],
            data[3] if len(data) > 3 else [],
        )
        out = []
        for i, t in enumerate(titles):
            if i < len(urls):
                out.append(SearchResult(
                    title=t, url=urls[i],
                    content=descs[i] if i < len(descs) else "",
                    engine=self.name,
                ))
        return out


class _DdgLiteParser(html.parser.HTMLParser):
    """Extracts (title, href, snippet) triples from the DDG lite table."""

    def __init__(self):
        super().__init__()
        self.results: list = []
        self._in_link = False
        self._cur: Optional[dict] = None
        self._in_snippet = False

    def handle_starttag(self, tag, attrs):
        a = dict(attrs)
        if tag == "a" and "result-link" in (a.get("class") or ""):
            self._in_link = True
            self._cur = {"url": a.get("href", ""), "title": "", "content": ""}
        elif tag == "td" and "result-snippet" in (a.get("class") or ""):
            self._in_snippet = True

    def handle_endtag(self, tag):
        if tag == "a" and self._in_link:
            self._in_link = False
        elif tag == "td" and self._in_snippet:
            self._in_snippet = False
            if self._cur and self._cur["url"]:
                self.results.append(self._cur)
            self._cur = None

    def handle_data(self, data):
        if self._in_link and self._cur is not None:
            self._cur["title"] += data
        elif self._in_snippet and self._cur is not None:
            self._cur["content"] += data


class DdgLiteEngine(Engine):
    """DuckDuckGo lite HTML (no API key, server-rendered table)."""

    def __init__(self, name: str = "duckduckgo",
                 base_url: str = "https://lite.duckduckgo.com",
                 weight: float = 1.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.weight = weight

    def search(self, query, fetch):
        url = (
            f"{self.base_url}/lite/?"
            + urllib.parse.urlencode({"q": query})
        )
        p = _DdgLiteParser()
        p.feed(fetch(url))
        return [
            SearchResult(
                title=r["title"].strip(), url=r["url"],
                content=r["content"].strip(), engine=self.name,
            )
            for r in p.results
        ]


def engine_from_spec(spec: dict) -> Engine:
    kind = spec.get("kind", "searx")
    if kind == "searx":
        return SearxJsonEngine(
            spec.get("name", "searx"), spec["url"],
            float(spec.get("weight", 1.0)),
        )
    if kind == "mediawiki":
        return MediaWikiEngine(
            spec.get("name", "wikipedia"),
            spec.get("url", "https://en.wikipedia.org"),
            float(spec.get("weight", 1.0)),
        )
    if kind == "ddg":
        return DdgLiteEngine(
            spec.get("name", "duckduckgo"),
            spec.get("url", "https://lite.duckduckgo.com"),
            float(spec.get("weight", 1.0)),
        )
    raise ValueError(f"unknown engine kind {kind!r}")


class MetaSearch:
    """Parallel fan-out + reciprocal-rank-fusion merge over engines."""

    def __init__(self, engines: Optional[List[Engine]] = None,
                 fetch: Optional[Callable[[str], str]] = None,
                 engine_timeout: float = 6.0):
        if engines is None:
            engines = [
                engine_from_spec(s)
                for s in json.loads(
                    os.environ.get("HELIX_SEARCH_ENGINES", "[]")
                )
            ]
        self.engines = engines
        self.fetch = fetch or default_fetch
        self.engine_timeout = engine_timeout
        self._stats: dict = {"queries": 0, "engine_errors": {}}
        self._lock = threading.Lock()
        # ONE shared pool: per-query pools would leak a live (non-daemon)
        # worker for every engine that outlives its deadline — executor
        # threads are joined at interpreter exit since py3.9, so a
        # drip-feeding engine could block shutdown.  A shared bounded pool
        # caps stragglers at max_workers; the real stop is the fetch
        # timeout inside each engine call.
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2 * len(self.engines), 2),
            thread_name_prefix="metasearch",
        ) if self.engines else None

    def search(self, query: str, max_results: int = 20) -> dict:
        """-> searx-wire dict {"query", "results": [...], "engines": {...}}."""
        if not self.engines:
            raise RuntimeError(
                "no search engines configured (set HELIX_SEARCH_ENGINES)"
            )
        with self._lock:
            self._stats["queries"] += 1
        per_engine: dict[str, list] = {}
        futs = {
            self._pool.submit(e.search, query, self.fetch): e
            for e in self.engines
        }
        done, not_done = concurrent.futures.wait(
            futs, timeout=self.engine_timeout
        )
        # stragglers are dropped from THIS query (cancel if still queued);
        # a running one keeps its shared-pool worker until its own fetch
        # timeout fires — bounded by max_workers, never per-query threads
        for f in not_done:
            f.cancel()
            e = futs[f]
            with self._lock:
                self._stats["engine_errors"][e.name] = "timeout"
        for f in done:
            e = futs[f]
            try:
                per_engine[e.name] = f.result()
            except Exception as exc:  # noqa: BLE001 — engine down
                with self._lock:
                    self._stats["engine_errors"][e.name] = str(exc)[:200]
        # reciprocal-rank fusion with per-engine weights
        K = 60.0
        merged: dict[str, SearchResult] = {}
        for e in self.engines:
            for rank, r in enumerate(per_engine.get(e.name, [])):
                key = _canonical(r.url)
                add = e.weight / (K + rank + 1)
                if key in merged:
                    merged[key].score += add
                    if len(r.content) > len(merged[key].content):
                        merged[key].content = r.content
                else:
                    r.score = add
                    merged[key] = r
        ranked = sorted(
            merged.values(), key=lambda r: r.score, reverse=True
        )[:max_results]
        return {
            "query": query,
            "number_of_results": len(ranked),
            "results": [r.to_dict() for r in ranked],
            "engines": {
                e.name: len(per_engine.get(e.name, []))
                for e in self.engines
            },
        }

    @property
    def stats(self) -> dict:
        with self._lock:
            return json.loads(json.dumps(self._stats))
