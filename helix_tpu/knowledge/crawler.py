"""Web crawler knowledge source: BFS fetch + readable-text extraction.

The counterpart of the reference's browser-pool crawler
(``api/pkg/controller/knowledge/`` — Chrome/rod + readability, wired at
``api/cmd/helix/serve.go:375-382``), rebuilt without a browser: static
fetch, HTML link extraction, same-domain BFS with page/depth budgets, and
robots.txt respect.  The fetch function is injected (requests-based
default) so zero-egress deployments and tests run against local servers.
"""

from __future__ import annotations

import dataclasses
import html.parser
import urllib.parse
import urllib.robotparser
from typing import Callable, Optional

from helix_tpu.knowledge.splitter import extract_text


@dataclasses.dataclass
class CrawlSpec:
    seeds: tuple
    max_pages: int = 50
    max_depth: int = 2
    same_domain: bool = True
    respect_robots: bool = True


class _LinkParser(html.parser.HTMLParser):
    def __init__(self):
        super().__init__()
        self.links: list[str] = []
        self.title = ""
        self._in_title = False

    def handle_starttag(self, tag, attrs):
        if tag == "a":
            for k, v in attrs:
                if k == "href" and v:
                    self.links.append(v)
        elif tag == "title":
            self._in_title = True

    def handle_endtag(self, tag):
        if tag == "title":
            self._in_title = False

    def handle_data(self, data):
        if self._in_title:
            self.title += data


def _host_is_private(host: str) -> bool:
    import ipaddress
    import socket

    try:
        infos = socket.getaddrinfo(host, None)
    except socket.gaierror:
        return True   # unresolvable: treat as forbidden
    for info in infos:
        addr = ipaddress.ip_address(info[4][0])
        if (
            addr.is_private
            or addr.is_loopback
            or addr.is_link_local
            or addr.is_reserved
            or addr.is_multicast
        ):
            return True
    return False


def default_fetch(url: str, timeout: float = 15.0) -> tuple:
    """-> (content, content_type).  Used when the deployment has egress.

    SSRF guard: refuses private/link-local/loopback targets (user-supplied
    URLs must not read cloud metadata or internal services), following
    redirects hop-by-hop so a public URL can't bounce inside.  Set
    HELIX_CRAWLER_ALLOW_PRIVATE=1 to crawl intranet docs deliberately.
    """
    import os

    import requests

    allow_private = os.environ.get("HELIX_CRAWLER_ALLOW_PRIVATE") == "1"
    for _ in range(5):   # bounded redirect chain
        parts = urllib.parse.urlsplit(url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme {parts.scheme!r}")
        if not allow_private and _host_is_private(parts.hostname or ""):
            raise PermissionError(f"refusing private address {url}")
        r = requests.get(
            url, timeout=timeout, allow_redirects=False,
            headers={"User-Agent": "helix-tpu-crawler/1.0"},
        )
        if r.status_code in (301, 302, 303, 307, 308):
            url = urllib.parse.urljoin(url, r.headers.get("Location", ""))
            continue
        r.raise_for_status()
        return r.text, r.headers.get("Content-Type", "text/html")
    raise ValueError("too many redirects")


class Crawler:
    def __init__(self, fetch: Optional[Callable] = None):
        self.fetch = fetch or default_fetch
        self._robots: dict[str, urllib.robotparser.RobotFileParser] = {}

    # ------------------------------------------------------------------
    def _allowed(self, url: str, spec: CrawlSpec) -> bool:
        if not spec.respect_robots:
            return True
        parts = urllib.parse.urlsplit(url)
        origin = f"{parts.scheme}://{parts.netloc}"
        rp = self._robots.get(origin)
        if rp is None:
            rp = urllib.robotparser.RobotFileParser()
            try:
                content, _ = self.fetch(f"{origin}/robots.txt")
                rp.parse(content.splitlines())
            except Exception:  # noqa: BLE001 — no robots file: allow all
                rp.parse([])
            self._robots[origin] = rp
        return rp.can_fetch("helix-tpu-crawler", url)

    @staticmethod
    def _normalise(base: str, href: str) -> Optional[str]:
        href = href.split("#", 1)[0].strip()
        if not href or href.startswith(("mailto:", "javascript:", "tel:")):
            return None
        absu = urllib.parse.urljoin(base, href)
        if not absu.startswith(("http://", "https://")):
            return None
        return absu

    # ------------------------------------------------------------------
    def crawl(self, spec: CrawlSpec) -> list:
        """BFS from the seeds.  Returns [(url, title, text)]."""
        seed_domains = {
            urllib.parse.urlsplit(s).netloc for s in spec.seeds
        }
        queue: list[tuple[str, int]] = [(s, 0) for s in spec.seeds]
        seen: set[str] = set(spec.seeds)
        out = []
        while queue and len(out) < spec.max_pages:
            url, depth = queue.pop(0)
            if not self._allowed(url, spec):
                continue
            try:
                content, ctype = self.fetch(url)
            except Exception:  # noqa: BLE001 — dead link: skip
                continue
            is_html = "html" in (ctype or "").lower()
            title, links = "", []
            if is_html:
                parser = _LinkParser()
                try:
                    parser.feed(content)
                except Exception:  # noqa: BLE001 — malformed markup
                    pass
                title = parser.title.strip()
                links = parser.links
            text = extract_text(content, ctype or "text/html")
            if text.strip():
                out.append((url, title, text))
            if depth >= spec.max_depth:
                continue
            for href in links:
                nxt = self._normalise(url, href)
                if nxt is None or nxt in seen:
                    continue
                if (
                    spec.same_domain
                    and urllib.parse.urlsplit(nxt).netloc not in seed_domains
                ):
                    continue
                seen.add(nxt)
                queue.append((nxt, depth + 1))
        return out
