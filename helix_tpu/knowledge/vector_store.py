"""Embedded vector store: SQLite rows + matmul / native-HNSW search.

The reference delegates vector search to a VectorChord/pgvector container
via the embedded kodit library (``SURVEY.md`` §2.5); this build keeps the
control plane dependency-free: chunk text/metadata persist in SQLite,
embeddings sit in a normalised fp32 matrix per collection.  Small
collections search with one exact [N, D] @ [D] matmul; collections past
``ANN_THRESHOLD`` build a native HNSW graph (``native/hnsw`` via
``knowledge/ann.py`` — the VectorChord-ANN analogue) and search that,
with the SQLite rows remaining the durable source of truth.  The
interface (upsert/delete/query by collection) is pgvector-shaped so an
external backend can slot in later.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import uuid
from typing import Optional, Sequence

import numpy as np

_SCHEMA = """
CREATE TABLE IF NOT EXISTS chunks (
    id TEXT PRIMARY KEY,
    collection TEXT NOT NULL,
    version INTEGER NOT NULL DEFAULT 1,
    text TEXT NOT NULL,
    meta TEXT NOT NULL DEFAULT '{}',
    embedding BLOB NOT NULL,
    dim INTEGER NOT NULL,
    created_at REAL DEFAULT (unixepoch('subsec'))
);
CREATE INDEX IF NOT EXISTS idx_chunks_collection ON chunks(collection, version);
"""


import os

# collections at/above this many chunks search via the native HNSW graph
ANN_THRESHOLD = int(os.environ.get("HELIX_ANN_THRESHOLD", "5000"))


class VectorStore:
    def __init__(self, path: str = ":memory:",
                 ann_threshold: int = ANN_THRESHOLD):
        from helix_tpu.control.db import Database

        self._db = Database.resolve(path)
        self._conn = self._db.conn
        self._lock = self._db.lock
        self._db.migrate("vectors", [(1, "initial", _SCHEMA)])
        # collection -> (ids, normalised matrix) cache
        self._cache: dict[str, tuple] = {}
        # collection -> HNSWIndex over the cached matrix's row positions
        self._ann: dict[str, object] = {}
        self._build_locks: dict[str, threading.Lock] = {}
        self.ann_threshold = ann_threshold

    # ------------------------------------------------------------------
    def upsert(
        self,
        collection: str,
        texts: Sequence[str],
        embeddings: np.ndarray,          # [N, D]
        metas: Optional[Sequence[dict]] = None,
        version: int = 1,
    ) -> list:
        embeddings = np.asarray(embeddings, np.float32)
        metas = metas or [{}] * len(texts)
        ids = []
        with self._lock:
            for text, emb, meta in zip(texts, embeddings, metas):
                cid = f"chk_{uuid.uuid4().hex[:16]}"
                ids.append(cid)
                self._conn.execute(
                    "INSERT INTO chunks(id, collection, version, text, meta, "
                    "embedding, dim) VALUES(?,?,?,?,?,?,?)",
                    (
                        cid, collection, version, text, json.dumps(meta),
                        emb.astype(np.float32).tobytes(), emb.shape[-1],
                    ),
                )
            self._db.commit()
            self._cache.pop(collection, None)
            self._ann.pop(collection, None)
        return ids

    def versions(self, collection: str) -> list:
        """[{version, chunks}] newest first (the /knowledge/{}/versions
        shape; the reconciler keeps only the live version after a
        successful re-index, older rows exist mid-index)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT version, COUNT(*) FROM chunks WHERE collection=?"
                " GROUP BY version ORDER BY version DESC",
                (collection,),
            ).fetchall()
        return [{"version": r[0], "chunks": r[1]} for r in rows]

    def dump(self, collection: str, version: Optional[int] = None) -> list:
        """Chunk texts + metadata for export (embeddings omitted)."""
        q = ("SELECT id, version, text, meta FROM chunks"
             " WHERE collection=?")
        args: list = [collection]
        if version is not None:
            q += " AND version=?"
            args.append(version)
        q += " ORDER BY created_at"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [
            {
                "id": r[0], "version": r[1], "text": r[2],
                "meta": json.loads(r[3]),
            }
            for r in rows
        ]

    def delete_collection(self, collection: str) -> int:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM chunks WHERE collection=?", (collection,)
            )
            self._db.commit()
            self._cache.pop(collection, None)
            self._ann.pop(collection, None)
            return cur.rowcount

    def delete_versions_below(self, collection: str, version: int) -> int:
        """Version-swap ingestion: new version lands, old is pruned
        (mirrors the reference's knowledge versioning)."""
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM chunks WHERE collection=? AND version<?",
                (collection, version),
            )
            self._db.commit()
            self._cache.pop(collection, None)
            self._ann.pop(collection, None)
            return cur.rowcount

    def count(self, collection: str) -> int:
        with self._lock:
            row = self._conn.execute(
                "SELECT COUNT(*) FROM chunks WHERE collection=?",
                (collection,),
            ).fetchone()
        return row[0]

    def collections(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT collection FROM chunks"
            ).fetchall()
        return sorted(r[0] for r in rows)

    # ------------------------------------------------------------------
    def _matrix(self, collection: str):
        with self._lock:
            cached = self._cache.get(collection)
            if cached is not None:
                return cached
            return self._load_matrix_locked(collection)

    def _load_matrix_locked(self, collection: str):
        """Caller holds self._lock."""
        rows = self._conn.execute(
            "SELECT id, embedding, dim FROM chunks WHERE collection=?",
            (collection,),
        ).fetchall()
        if not rows:
            self._cache[collection] = ([], None)
            return [], None
        ids = [r[0] for r in rows]
        mat = np.stack(
            [np.frombuffer(r[1], np.float32, count=r[2]) for r in rows]
        )
        norms = np.linalg.norm(mat, axis=1, keepdims=True)
        mat = mat / np.maximum(norms, 1e-9)
        self._cache[collection] = (ids, mat)
        return ids, mat

    def _snapshot(self, collection: str):
        """One consistent (ids, mat, ann_index_or_None) snapshot.

        Consistency: the returned graph is always built over the returned
        matrix (pairing an old graph with new ids would return wrong
        chunks).  The build itself — thousands of ctypes inserts, and
        possibly a first-use ``make`` — runs OUTSIDE the store lock so it
        cannot freeze every other collection's queries; the finished
        graph is only installed in the shared cache if the matrix it
        indexes is still the current one."""
        from helix_tpu.knowledge import ann as _ann

        def _index_for(mat_obj):
            # an index is only ever used with the exact matrix object it
            # was built over (stored as a (matrix, graph) pair) — a graph
            # built over a newer matrix must not be paired with an older
            # snapshot's ids
            stored = self._ann.get(collection)
            if stored is not None and stored[0] is mat_obj:
                return stored[1]
            return None

        with self._lock:
            cached = self._cache.get(collection)
            if cached is None:
                cached = self._load_matrix_locked(collection)
            ids, mat = cached
            index = _index_for(mat)
            need_build = (
                index is None
                and mat is not None
                and len(ids) >= self.ann_threshold
            )
        if need_build and _ann.native_available():
            # per-collection build lock: N concurrent first-queries must
            # not each rebuild an identical graph (the build is the
            # expensive part the store lock no longer covers)
            build_lock = self._build_locks.setdefault(
                collection, threading.Lock()
            )
            with build_lock:
                with self._lock:
                    index = _index_for(mat)
                if index is None:
                    index = _ann.HNSWIndex(mat.shape[1])
                    index.add_batch(mat)     # row position == ANN id
                    with self._lock:
                        cur = self._cache.get(collection)
                        if cur is not None and cur[1] is mat:
                            self._ann[collection] = (mat, index)
                        # else: changed mid-build — the graph still
                        # matches OUR (ids, mat) snapshot; this query
                        # uses it, the next one rebuilds fresh
        return ids, mat, index

    def query(
        self,
        collection: str,
        embedding: np.ndarray,
        top_k: int = 5,
        min_score: float = 0.0,
    ) -> list:
        """-> [{id, text, meta, score}] by cosine similarity — exact
        matmul for small collections, native HNSW past ann_threshold
        (exact always when the native library is unavailable: the numpy
        fallback inside HNSWIndex would be strictly slower than the
        cached-matrix matmul)."""
        ids, mat, index = self._snapshot(collection)
        if mat is None:
            return []
        q = np.asarray(embedding, np.float32).reshape(-1)
        q = q / max(np.linalg.norm(q), 1e-9)
        k = min(top_k, len(ids))
        if index is not None:
            rows, scores_arr = index.search(q, k)
            ranked = list(zip(rows.tolist(), scores_arr.tolist()))
        else:
            scores = mat @ q
            top = np.argsort(-scores)[:k]
            ranked = [(int(i), float(scores[i])) for i in top]
        out = []
        with self._lock:
            for i, score in ranked:
                if score < min_score:
                    continue
                row = self._conn.execute(
                    "SELECT text, meta FROM chunks WHERE id=?", (ids[i],)
                ).fetchone()
                if row is None:   # deleted between snapshot and fetch
                    continue
                out.append(
                    {
                        "id": ids[i],
                        "text": row[0],
                        "meta": json.loads(row[1]),
                        "score": score,
                    }
                )
        return out
