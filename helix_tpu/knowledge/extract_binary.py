"""Binary-document text extraction: PDF, docx, pptx, xlsx.

The reference calls an unstructured.io-style extractor *service* over HTTP
(``api/pkg/extract/extract.go:22-29``) and feeds it crawler output and
uploaded/SharePoint files.  This build extracts in-process with the
stdlib: Office OpenXML formats are zip archives of XML (pull the text
nodes), and PDFs embed text in content streams as ``Tj``/``TJ`` operators
(inflate FlateDecode streams, then parse the operators).  The PDF path
covers the overwhelmingly common case (Flate-compressed, standard-encoded
text); exotic encodings degrade to empty text rather than errors.
"""

from __future__ import annotations

import io
import re
import zipfile
import zlib

__all__ = ["extract_any", "extract_pdf", "extract_docx", "extract_pptx",
           "extract_xlsx", "sniff_kind"]


def sniff_kind(data: bytes, filename: str = "") -> str:
    """-> pdf | docx | pptx | xlsx | zip | text"""
    if data[:5] == b"%PDF-":
        return "pdf"
    if data[:2] == b"PK":
        name = filename.lower()
        if name.endswith(".docx"):
            return "docx"
        if name.endswith(".pptx"):
            return "pptx"
        if name.endswith(".xlsx"):
            return "xlsx"
        # sniff by archive members
        try:
            with zipfile.ZipFile(io.BytesIO(data)) as z:
                names = z.namelist()
            if any(n.startswith("word/") for n in names):
                return "docx"
            if any(n.startswith("ppt/") for n in names):
                return "pptx"
            if any(n.startswith("xl/") for n in names):
                return "xlsx"
        except zipfile.BadZipFile:
            pass
        return "zip"
    return "text"


def extract_any(data: bytes, filename: str = "") -> str:
    """Dispatch on sniffed kind; text-ish bytes decode with replacement."""
    kind = sniff_kind(data, filename)
    if kind == "pdf":
        return extract_pdf(data)
    if kind == "docx":
        return extract_docx(data)
    if kind == "pptx":
        return extract_pptx(data)
    if kind == "xlsx":
        return extract_xlsx(data)
    if kind == "zip":
        return ""
    return data.decode("utf-8", errors="replace")


# -- Office OpenXML ----------------------------------------------------------

_XML_TAG = re.compile(rb"<[^>]+>")


def _xml_text(xml: bytes, para_tag: bytes, text_tag: bytes) -> str:
    """Pull the character data of <text_tag> runs, joining runs within a
    <para_tag> and separating paragraphs with newlines."""
    out: list = []
    for para in re.split(b"</" + para_tag + b">", xml):
        runs = re.findall(
            b"<" + text_tag + b"(?:\\s[^>]*)?>(.*?)</" + text_tag + b">",
            para, re.S,
        )
        if runs:
            text = b"".join(runs)
            out.append(_unescape(_XML_TAG.sub(b"", text).decode(
                "utf-8", errors="replace"
            )))
    return "\n".join(t for t in out if t.strip())


def _unescape(s: str) -> str:
    import html

    return html.unescape(s)


def extract_docx(data: bytes) -> str:
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        parts = []
        for name in sorted(z.namelist()):
            if name == "word/document.xml" or re.match(
                r"word/(header|footer)\d*\.xml", name
            ):
                parts.append(_xml_text(z.read(name), b"w:p", b"w:t"))
    return "\n".join(p for p in parts if p)


def extract_pptx(data: bytes) -> str:
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        slides = sorted(
            n for n in z.namelist()
            if re.match(r"ppt/slides/slide\d+\.xml$", n)
        )
        return "\n\n".join(
            t
            for n in slides
            if (t := _xml_text(z.read(n), b"a:p", b"a:t"))
        )


def extract_xlsx(data: bytes) -> str:
    """Shared strings + inline strings; numbers are left out (RAG wants
    prose, not a number soup)."""
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        names = z.namelist()
        parts = []
        if "xl/sharedStrings.xml" in names:
            parts.append(
                _xml_text(z.read("xl/sharedStrings.xml"), b"si", b"t")
            )
        for n in sorted(names):
            if re.match(r"xl/worksheets/sheet\d+\.xml$", n):
                inline = _xml_text(z.read(n), b"is", b"t")
                if inline:
                    parts.append(inline)
    return "\n".join(p for p in parts if p)


# -- PDF ---------------------------------------------------------------------

_STREAM_RE = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.S)
# text-showing operators inside content streams
_TJ_RE = re.compile(rb"\((?:\\.|[^\\()])*\)\s*Tj")
_TJ_ARRAY_RE = re.compile(rb"\[((?:[^\[\]\\]|\\.)*?)\]\s*TJ", re.S)
_STR_RE = re.compile(rb"\(((?:\\.|[^\\()])*)\)")
_BT_ET_RE = re.compile(rb"BT(.*?)ET", re.S)
_TSTAR = re.compile(rb"T\*|\bTd\b|\bTD\b")


def _pdf_unescape(raw: bytes) -> str:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == 0x5C and i + 1 < len(raw):  # backslash
            n = raw[i + 1]
            mapped = {
                ord("n"): 10, ord("r"): 13, ord("t"): 9, ord("b"): 8,
                ord("f"): 12, ord("("): 40, ord(")"): 41, ord("\\"): 92,
            }.get(n)
            if mapped is not None:
                out.append(mapped)
                i += 2
                continue
            if 0x30 <= n <= 0x37:  # octal escape, up to 3 digits
                j = i + 1
                oct_digits = b""
                while j < len(raw) and len(oct_digits) < 3 and (
                    0x30 <= raw[j] <= 0x37
                ):
                    oct_digits += bytes([raw[j]])
                    j += 1
                out.append(int(oct_digits, 8) & 0xFF)
                i = j
                continue
            i += 1  # unknown escape: drop the backslash
            continue
        out.append(c)
        i += 1
    # PDFs may use UTF-16BE strings (BOM-prefixed)
    if out[:2] == b"\xfe\xff":
        return bytes(out[2:]).decode("utf-16-be", errors="replace")
    return bytes(out).decode("latin-1", errors="replace")


def _stream_text(stream: bytes) -> str:
    lines: list = []
    for block in _BT_ET_RE.findall(stream):
        parts: list = []
        pos = 0
        # walk the block in order, collecting show-text ops and breaks
        tokens = sorted(
            [(m.start(), "tj", m) for m in _TJ_RE.finditer(block)]
            + [(m.start(), "TJ", m) for m in _TJ_ARRAY_RE.finditer(block)]
            + [(m.start(), "nl", m) for m in _TSTAR.finditer(block)]
        )
        del pos
        for _, kind, m in tokens:
            if kind == "nl":
                parts.append("\n")
            elif kind == "tj":
                s = _STR_RE.search(m.group(0))
                if s:
                    parts.append(_pdf_unescape(s.group(1)))
            else:
                for s in _STR_RE.finditer(m.group(1)):
                    parts.append(_pdf_unescape(s.group(1)))
        text = "".join(parts)
        if text.strip():
            lines.append(text)
    return "\n".join(lines)


def extract_pdf(data: bytes) -> str:
    """Inflate every Flate stream and parse BT..ET text blocks; raw
    (uncompressed) streams are parsed as-is."""
    texts: list = []
    for m in _STREAM_RE.finditer(data):
        raw = m.group(1)
        inflated = None
        try:
            inflated = zlib.decompress(raw)
        except zlib.error:
            # try skipping leading whitespace junk, then give up -> raw
            try:
                inflated = zlib.decompress(raw.lstrip(b"\r\n"))
            except zlib.error:
                inflated = raw
        t = _stream_text(inflated)
        if t:
            texts.append(t)
    out = "\n".join(texts)
    # collapse intra-word kerning artifacts: TJ arrays emit fragments
    out = re.sub(r"[ \t]+", " ", out)
    return out.strip()
