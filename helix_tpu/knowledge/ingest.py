"""Knowledge manager: sources -> extract -> split -> embed -> index.

The in-process counterpart of the reference's knowledge reconciler
(``api/pkg/controller/knowledge/knowledge.go:35-103``): specs declare
sources (files/dir/inline text), a background reconcile pass drives each
knowledge through pending -> indexing -> ready (error on failure) with
per-knowledge progress, and re-indexing bumps a version whose chunks
atomically replace the old ones.  Embeddings come from any callable
(the local TPU EmbeddingRunner or a provider's /v1/embeddings).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import traceback
from typing import Callable, Optional

import numpy as np

from helix_tpu.knowledge.splitter import extract_text, split_text
from helix_tpu.knowledge.vector_store import VectorStore


@dataclasses.dataclass
class KnowledgeSpec:
    id: str
    name: str = ""
    # sources
    text: Optional[str] = None          # inline content
    path: Optional[str] = None          # file or directory
    urls: tuple = ()                    # single pages, or crawl seeds
    crawl_depth: int = 0                # >0: BFS-crawl from urls
    max_pages: int = 50                 # crawl page budget
    # SharePoint drive source (reference: KnowledgeSourceSharePoint,
    # knowledge_extract.go:423): {site_id|site_url, drive_id?,
    # folder_path?, recursive?, extensions?, oauth_provider?}
    sharepoint: Optional[dict] = None
    owner: str = ""                     # OAuth connection owner
    # chunking
    chunk_size: int = 1000
    chunk_overlap: int = 100
    # state (managed)
    state: str = "pending"              # pending|indexing|ready|error
    version: int = 0
    progress: dict = dataclasses.field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_TEXT_EXTS = {".txt", ".md", ".markdown", ".rst", ".py", ".go", ".js", ".ts",
              ".json", ".yaml", ".yml", ".toml", ".html", ".htm", ".css"}
_BINARY_EXTS = {".pdf", ".docx", ".pptx", ".xlsx"}


class KnowledgeManager:
    def __init__(
        self,
        store: VectorStore,
        embed_fn: Callable[[list], np.ndarray],
        fetch_fn: Optional[Callable[[str], tuple]] = None,  # url -> (text, ctype)
        reconcile_interval: float = 10.0,
        sharepoint_token: Optional[Callable[[str, str], str]] = None,
        sharepoint_http: Optional[Callable] = None,
    ):
        self.store = store
        self.embed = embed_fn
        self.fetch = fetch_fn
        # (owner, provider) -> bearer token; wired to the OAuth manager by
        # the control plane (reference: knowledge reconciler + oauthManager)
        self.sharepoint_token = sharepoint_token
        self.sharepoint_http = sharepoint_http   # injectable Graph HTTP
        self.reconcile_interval = reconcile_interval
        self._specs: dict[str, KnowledgeSpec] = {}
        self._dirty: set = set()
        self._lock = threading.Lock()
        # per-knowledge mutation locks: index() and complete() hold the
        # kid's lock for their WHOLE read-version/gather/upsert/reap
        # span, so an in-flight background index can never interleave
        # with an external push and delete its chunks
        self._kid_locks: dict = {}
        # push epochs: bumped by complete(); the reconcile loop snapshots
        # them at dequeue and skips any kid whose epoch moved before its
        # index started (a dequeued-but-not-started re-index must not
        # clobber a push that landed in between)
        self._push_epoch: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def add(self, spec: KnowledgeSpec) -> KnowledgeSpec:
        with self._lock:
            self._specs[spec.id] = spec
            self._dirty.add(spec.id)
        return spec

    def get(self, kid: str) -> Optional[KnowledgeSpec]:
        return self._specs.get(kid)

    def list(self) -> list:
        return [self._specs[k] for k in sorted(self._specs)]

    def remove(self, kid: str) -> None:
        with self._lock:
            self._specs.pop(kid, None)
            self._dirty.discard(kid)
        self.store.delete_collection(kid)

    def refresh(self, kid: str) -> None:
        with self._lock:
            if kid in self._specs:
                self._dirty.add(kid)

    # ------------------------------------------------------------------
    def _gather(self, spec: KnowledgeSpec) -> list:
        """-> [(text, meta)] raw documents."""
        docs = []
        if spec.text:
            docs.append((spec.text, {"source": "inline"}))
        if spec.path:
            if os.path.isfile(spec.path):
                paths = [spec.path]
            else:
                paths = [
                    os.path.join(r, f)
                    for r, _, fs in os.walk(spec.path)
                    for f in fs
                    if os.path.splitext(f)[1].lower()
                    in (_TEXT_EXTS | _BINARY_EXTS)
                ]
            for p in sorted(paths):
                ext = os.path.splitext(p)[1].lower()
                try:
                    if ext in _BINARY_EXTS:
                        # pdf/docx/pptx/xlsx: in-process binary extractor
                        # (the reference calls an extractor service here).
                        # One corrupt file must not fail the whole index —
                        # degrade per-file like the text path does.
                        from helix_tpu.knowledge.extract_binary import (
                            extract_any,
                        )

                        with open(p, "rb") as f:
                            text = extract_any(f.read(), p)
                        docs.append((text, {"source": p}))
                        continue
                    with open(p, errors="replace") as f:
                        content = f.read()
                except OSError:
                    continue
                except Exception:  # noqa: BLE001 — corrupt binary file
                    continue
                ctype = (
                    "text/html"
                    if p.lower().endswith((".html", ".htm"))
                    else "text/plain"
                )
                docs.append(
                    (extract_text(content, ctype), {"source": p})
                )
        if spec.urls and spec.crawl_depth > 0:
            # web-crawl source (reference: the knowledge crawler's
            # browser-pool + readability path)
            if self.fetch is None:
                raise RuntimeError(
                    "url sources need a fetcher (no egress in this node?)"
                )
            from helix_tpu.knowledge.crawler import Crawler, CrawlSpec

            crawler = Crawler(fetch=self.fetch)
            pages = crawler.crawl(
                CrawlSpec(
                    seeds=tuple(spec.urls),
                    max_pages=spec.max_pages,
                    max_depth=spec.crawl_depth,
                )
            )
            for url, title, text in pages:
                docs.append((text, {"source": url, "title": title}))
        elif spec.urls:
            if self.fetch is None:
                raise RuntimeError(
                    "url sources need a fetcher (no egress in this node?)"
                )
            for url in spec.urls:
                content, ctype = self.fetch(url)
                docs.append((extract_text(content, ctype), {"source": url}))
        if spec.sharepoint:
            if self.sharepoint_token is None:
                raise RuntimeError(
                    "sharepoint sources need an OAuth manager "
                    "(sharepoint_token hook unset)"
                )
            from helix_tpu.knowledge.sharepoint import gather_sharepoint

            provider = spec.sharepoint.get("oauth_provider", "microsoft")
            token = self.sharepoint_token(spec.owner, provider)

            def _progress(i, total, name):
                spec.progress = {
                    "step": "downloading",
                    "progress": int(i / max(total, 1) * 100),
                    "message": f"Downloading {name} ({i + 1}/{total})",
                }

            docs.extend(
                gather_sharepoint(
                    spec.sharepoint, token,
                    http_fn=self.sharepoint_http,
                    progress=_progress,
                )
            )
        return docs

    def _kid_lock(self, kid: str) -> threading.Lock:
        with self._lock:
            return self._kid_locks.setdefault(kid, threading.Lock())

    def index(self, kid: str) -> KnowledgeSpec:
        """Synchronous (re-)index of one knowledge."""
        with self._kid_lock(kid):
            return self._index_locked(kid)

    def _index_locked(self, kid: str) -> KnowledgeSpec:
        spec = self._specs[kid]
        spec.state = "indexing"
        spec.error = ""
        try:
            docs = self._gather(spec)
            new_version = spec.version + 1
            total_chunks = 0
            for di, (text, meta) in enumerate(docs):
                chunks = split_text(text, spec.chunk_size, spec.chunk_overlap)
                if not chunks:
                    continue
                embeddings = self.embed(chunks)
                self.store.upsert(
                    kid, chunks, embeddings,
                    metas=[{**meta, "doc": di}] * len(chunks),
                    version=new_version,
                )
                total_chunks += len(chunks)
                spec.progress = {
                    "docs_done": di + 1,
                    "docs_total": len(docs),
                    "chunks": total_chunks,
                }
            self.store.delete_versions_below(kid, new_version)
            spec.version = new_version
            spec.state = "ready"
        except Exception as e:  # noqa: BLE001 — surfaced in spec state
            spec.state = "error"
            spec.error = f"{e}\n{traceback.format_exc(limit=3)}"
        return spec

    # ------------------------------------------------------------------
    def complete(self, kid: str, chunks: list) -> KnowledgeSpec:
        """External-extractor ingestion (reference: the extractor service
        POSTs /knowledge/{id}/complete with pre-extracted content): embed
        + index caller-supplied chunks as a new version and mark ready.

        chunks: [{"text": ..., "meta": {...}?}, ...]"""
        spec = self._specs[kid]
        if not all(isinstance(c, dict) for c in chunks):
            raise ValueError("chunks must be objects with a 'text' field")
        texts = [str(c.get("text", "")) for c in chunks if c.get("text")]
        if not texts:
            raise ValueError("complete needs at least one chunk with text")
        metas = [
            dict(c.get("meta") or {})
            for c in chunks if c.get("text")
        ]
        embeddings = self.embed(texts)
        # clear any pending reconcile (a scheduled re-gather of the
        # original source must not supersede the push), bump the push
        # epoch (a DEQUEUED-but-not-started re-index checks it and
        # skips), then commit under the per-kid lock — an ALREADY-RUNNING
        # index() holds that lock, so the push lands strictly after it at
        # a higher version
        with self._lock:
            self._dirty.discard(kid)
            self._push_epoch[kid] = self._push_epoch.get(kid, 0) + 1
        with self._kid_lock(kid):
            new_version = spec.version + 1
            self.store.upsert(
                kid, texts, embeddings, metas=metas, version=new_version
            )
            self.store.delete_versions_below(kid, new_version)
            spec.version = new_version
            spec.state = "ready"
            spec.error = ""
            spec.progress = {"chunks": len(texts), "source": "external"}
        return spec

    def query(self, kids, text: str, top_k: int = 5) -> list:
        """Search one or many knowledges; merged by score."""
        if isinstance(kids, str):
            kids = [kids]
        q = self.embed([text])[0]
        results = []
        for kid in kids:
            for r in self.store.query(kid, q, top_k=top_k):
                results.append({**r, "knowledge_id": kid})
        results.sort(key=lambda r: -r["score"])
        return results[:top_k]

    # ------------------------------------------------------------------
    def start(self):
        """Background reconcile loop (gocron analogue)."""

        def run():
            while not self._stop.is_set():
                with self._lock:
                    dirty = list(self._dirty)
                    self._dirty.clear()
                    epochs = {
                        k: self._push_epoch.get(k, 0) for k in dirty
                    }
                for kid in dirty:
                    with self._lock:
                        moved = (
                            self._push_epoch.get(kid, 0) != epochs[kid]
                        )
                    if moved:
                        continue   # an external push superseded this pass
                    if kid in self._specs:
                        self.index(kid)
                self._stop.wait(self.reconcile_interval)

        self._thread = threading.Thread(
            target=run, name="helix-knowledge", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
