"""ANN index: ctypes binding for the native HNSW (native/hnsw).

The reference's vector search runs in a VectorChord (pgvector-compatible)
container with ANN indexes (SURVEY.md §2.5); here the durable store is
SQLite (``vector_store.py``) and this module supplies the ANN
acceleration natively.  Falls back to exact numpy search when the native
library cannot build, so nothing above this layer has a hard native
dependency.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("helix.ann")

_NATIVE_DIR = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native", "hnsw",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhxhnsw.so")
_lock = threading.Lock()
_lib = None
_lib_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR], check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
        except Exception as e:  # noqa: BLE001 — fall back to numpy
            log.warning("native HNSW unavailable (%s); using exact numpy", e)
            _lib_failed = True
            return None
        lib.hx_hnsw_create.restype = ctypes.c_void_p
        lib.hx_hnsw_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.hx_hnsw_destroy.argtypes = [ctypes.c_void_p]
        lib.hx_hnsw_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.hx_hnsw_size.restype = ctypes.c_int
        lib.hx_hnsw_size.argtypes = [ctypes.c_void_p]
        lib.hx_hnsw_search.restype = ctypes.c_int
        lib.hx_hnsw_search.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class HNSWIndex:
    """Cosine ANN over pre-normalised float32 vectors.

    ids are caller-assigned int64 (the vector store uses row positions).
    """

    def __init__(self, dim: int, M: int = 16, ef_construction: int = 100):
        self.dim = dim
        self._lib = _load()
        self._handle = None
        self._fallback_vecs: list = []
        self._fallback_ids: list = []
        if self._lib is not None:
            self._handle = self._lib.hx_hnsw_create(dim, M, ef_construction)
        self._mu = threading.Lock()

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and h:
            lib.hx_hnsw_destroy(h)

    def __len__(self) -> int:
        if self._handle:
            return self._lib.hx_hnsw_size(self._handle)
        return len(self._fallback_ids)

    def add(self, idx: int, vec: np.ndarray) -> None:
        v = np.ascontiguousarray(vec, np.float32)
        n = float(np.linalg.norm(v))
        if n > 0:
            v = v / n
        with self._mu:
            if self._handle:
                self._lib.hx_hnsw_add(
                    self._handle, idx,
                    v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                )
            else:
                self._fallback_ids.append(idx)
                self._fallback_vecs.append(v)

    def add_batch(self, vecs: np.ndarray, start_id: int = 0) -> None:
        for i, v in enumerate(vecs):
            self.add(start_id + i, v)

    def search(
        self, query: np.ndarray, k: int, ef: int = 64
    ) -> tuple:
        """-> (ids[int64], scores[float32]) sorted by descending cosine."""
        q = np.ascontiguousarray(query, np.float32).reshape(-1)
        n = float(np.linalg.norm(q))
        if n > 0:
            q = q / n
        if self._handle:
            out_ids = np.zeros((k,), np.int64)
            out_scores = np.zeros((k,), np.float32)
            got = self._lib.hx_hnsw_search(
                self._handle,
                q.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                k, max(ef, k),
                out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                out_scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            )
            return out_ids[:got], out_scores[:got]
        if not self._fallback_ids:
            return np.zeros((0,), np.int64), np.zeros((0,), np.float32)
        mat = np.stack(self._fallback_vecs)
        scores = mat @ q
        top = np.argsort(-scores)[:k]
        return (
            np.asarray(self._fallback_ids, np.int64)[top],
            scores[top].astype(np.float32),
        )
