"""Kubernetes operator: AIApp custom resources reconciled into apps.

Reference: ``operator/`` — a kubebuilder controller for the ``AIApp``
CRD (group ``app.aispec.org/v1alpha1``) that converts each CR into a
Helix app via the API, namespacing the app id as ``k8s.<ns>.<name>``,
managing a finalizer for deletes, and writing status back
(``operator/internal/controller/aiapp_controller.go:56``,
``operator/api/v1alpha1/aiapp_types.go``).

This build keeps the same reconcile semantics with a self-contained
controller process: a list+watch loop against the K8s API (plain HTTP —
injectable for tests), idempotent upserts into the control plane's app
store, finalizer add/strip, and a status patch per reconcile.  CRD and
deployment manifests live in ``deploy/k8s/``.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from typing import Callable, Optional

log = logging.getLogger("helix.operator")

GROUP = "app.aispec.org"
VERSION = "v1alpha1"
PLURAL = "aiapps"
FINALIZER = "app.aispec.org/finalizer"
K8S_PREFIX = "k8s"


def app_id_for(namespace: str, name: str) -> str:
    """Namespaced, clash-free app id for k8s-managed apps (reference
    uses dots for URL safety: ``k8s.<ns>.<name>``)."""
    return f"{K8S_PREFIX}.{namespace}.{name}"


def crd_to_app_doc(aiapp: dict) -> dict:
    """AIApp CR -> helix.yaml-shaped app document."""
    meta = aiapp.get("metadata", {})
    spec = aiapp.get("spec", {}) or {}
    assistants = []
    for a in spec.get("assistants", []) or []:
        assistant = {
            k: v
            for k, v in a.items()
            if k in (
                "id", "name", "description", "provider", "model",
                "system_prompt", "temperature", "max_tokens", "knowledge",
                "apis", "tools",
            )
        }
        assistants.append(assistant)
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "AIApp",
        "metadata": {
            "name": app_id_for(
                meta.get("namespace", "default"), meta.get("name", "")
            ),
        },
        "spec": {
            "description": spec.get("description", ""),
            "assistants": assistants,
            "triggers": spec.get("triggers", []),
        },
    }


class K8sClient:
    """Minimal typed client for one CRD; HTTP layer injectable."""

    def __init__(
        self,
        base_url: str,
        token: str = "",
        http_fn: Optional[Callable] = None,
        namespace: Optional[str] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.namespace = namespace
        self._http = http_fn or self._default_http

    @classmethod
    def in_cluster(cls) -> "K8sClient":
        """Standard in-cluster config: service-account token + env."""
        import os

        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token = ""
        try:
            with open(
                "/var/run/secrets/kubernetes.io/serviceaccount/token"
            ) as f:
                token = f.read().strip()
        except OSError:
            pass
        return cls(f"https://{host}:{port}", token)

    def _default_http(self, method, url, body=None, headers=None):
        req = urllib.request.Request(
            url, data=body, method=method, headers=headers or {}
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read()

    def _req(self, method: str, path: str, body: Optional[dict] = None,
             content_type: str = "application/json"):
        headers = {"Accept": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        data = None
        if body is not None:
            headers["Content-Type"] = content_type
            data = json.dumps(body).encode()
        status, raw = self._http(
            method, f"{self.base_url}{path}", data, headers
        )
        if status >= 400:
            raise RuntimeError(f"k8s API {method} {path}: HTTP {status}")
        return json.loads(raw) if raw else {}

    def _crd_path(self, namespace: Optional[str] = None) -> str:
        ns = namespace or self.namespace
        if ns:
            return f"/apis/{GROUP}/{VERSION}/namespaces/{ns}/{PLURAL}"
        return f"/apis/{GROUP}/{VERSION}/{PLURAL}"

    def list_aiapps(self) -> dict:
        return self._req("GET", self._crd_path())

    def update_aiapp(self, aiapp: dict) -> dict:
        meta = aiapp["metadata"]
        return self._req(
            "PUT",
            f"/apis/{GROUP}/{VERSION}/namespaces/{meta['namespace']}/"
            f"{PLURAL}/{meta['name']}",
            aiapp,
        )

    def patch_status(self, namespace: str, name: str, status: dict) -> None:
        self._req(
            "PATCH",
            f"/apis/{GROUP}/{VERSION}/namespaces/{namespace}/{PLURAL}/"
            f"{name}/status",
            {"status": status},
            content_type="application/merge-patch+json",
        )


class AIAppReconciler:
    """Reconcile every AIApp CR into the control plane's app store."""

    def __init__(
        self,
        k8s: K8sClient,
        helix_url: str = "",
        helix_token: str = "",
        apply_fn: Optional[Callable[[str, dict], None]] = None,
        delete_fn: Optional[Callable[[str], None]] = None,
        resync_interval: float = 30.0,
    ):
        """``apply_fn(app_id, doc)`` / ``delete_fn(app_id)`` default to
        the control-plane HTTP API at ``helix_url``; injectable so the
        operator can run in-process with a ControlPlane store."""
        self.k8s = k8s
        self.helix_url = helix_url.rstrip("/")
        self.helix_token = helix_token
        self.apply_fn = apply_fn or self._apply_http
        self.delete_fn = delete_fn or self._delete_http
        self.resync_interval = resync_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # app_id -> last applied doc (skip no-op PUTs)
        self._applied: dict[str, str] = {}

    # -- helix API default sinks -------------------------------------------
    def _helix_req(self, method, path, body=None):
        headers = {"Content-Type": "application/json"}
        if self.helix_token:
            headers["Authorization"] = f"Bearer {self.helix_token}"
        req = urllib.request.Request(
            f"{self.helix_url}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method, headers=headers,
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.read()

    def _apply_http(self, app_id: str, doc: dict) -> None:
        self._helix_req("POST", "/api/v1/apps", doc)

    def _delete_http(self, app_id: str) -> None:
        import urllib.error

        try:
            self._helix_req(
                "DELETE", f"/api/v1/apps/{urllib.parse.quote(app_id)}"
            )
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    # -- reconcile ----------------------------------------------------------
    def reconcile_one(self, aiapp: dict) -> str:
        """-> outcome: applied | deleted | finalizer-added | unchanged"""
        meta = aiapp.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        app_id = app_id_for(ns, name)
        finalizers = meta.get("finalizers", []) or []
        if meta.get("deletionTimestamp"):
            self.delete_fn(app_id)
            self._applied.pop(app_id, None)
            if FINALIZER in finalizers:
                meta["finalizers"] = [
                    f for f in finalizers if f != FINALIZER
                ]
                self.k8s.update_aiapp(aiapp)
            return "deleted"
        if FINALIZER not in finalizers:
            meta["finalizers"] = finalizers + [FINALIZER]
            self.k8s.update_aiapp(aiapp)
            return "finalizer-added"
        doc = crd_to_app_doc(aiapp)
        fingerprint = json.dumps(doc, sort_keys=True)
        if self._applied.get(app_id) == fingerprint:
            return "unchanged"
        try:
            self.apply_fn(app_id, doc)
            self._applied[app_id] = fingerprint
            self._status(ns, name, "Ready", app_id, "")
            return "applied"
        except Exception as e:  # noqa: BLE001 — surface on the CR status
            log.warning("reconcile %s failed: %s", app_id, e)
            self._status(ns, name, "Error", app_id, str(e))
            return "error"

    def _status(self, ns, name, phase, app_id, message) -> None:
        try:
            self.k8s.patch_status(
                ns, name,
                {"phase": phase, "appId": app_id, "message": message},
            )
        except Exception:  # noqa: BLE001 — status is best effort
            log.debug("status patch failed", exc_info=True)

    def resync(self) -> dict:
        """One full list+reconcile pass; returns outcome counts."""
        out: dict = {}
        doc = self.k8s.list_aiapps()
        seen = set()
        for item in doc.get("items", []):
            meta = item.get("metadata", {})
            seen.add(
                app_id_for(meta.get("namespace", "default"),
                           meta.get("name", ""))
            )
            try:
                res = self.reconcile_one(item)
            except Exception as e:  # noqa: BLE001 — one CR (409 conflict
                # on the finalizer PUT, transient API error) must not
                # starve the CRs sorted after it; the next tick retries
                log.warning(
                    "reconcile %s/%s failed: %s",
                    meta.get("namespace"), meta.get("name"), e,
                )
                res = "error"
            out[res] = out.get(res, 0) + 1
        # apps we applied whose CR vanished without a deletion event
        # (finalizer normally prevents this; belt-and-braces GC)
        for app_id in list(self._applied):
            if app_id not in seen:
                self.delete_fn(app_id)
                self._applied.pop(app_id, None)
                out["gc"] = out.get("gc", 0) + 1
        return out

    def start(self) -> "AIAppReconciler":
        def run():
            while not self._stop.is_set():
                try:
                    self.resync()
                except Exception as e:  # noqa: BLE001 — keep the loop up
                    log.warning("resync failed: %s", e)
                self._stop.wait(self.resync_interval)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
