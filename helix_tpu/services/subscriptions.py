"""Agent-subscription credentials: Claude / Codex OAuth tokens per user.

The reference stores per-user Claude and Codex subscription credentials
and mints session-scoped copies for sandboxes
(``/api/v1/claude-subscriptions``, ``/codex-subscriptions``,
``/sessions/{}/claude-credentials`` in ``api/pkg/server/server.go``) —
agents inside sandboxes then call the vendor API on the USER's
subscription rather than a platform key.

Credentials are envelope-encrypted at rest (the service-connection
posture).  ``session_credentials`` mints a short-lived, session-bound
HMAC-wrapped credential handle: the sandbox gets a reference it can
exchange in-process, never the raw token on the wire; the gateway
(``control/anthropic_gateway.py`` DirectTransport oauth_token) consumes
the resolved token.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time
import uuid
from typing import List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS agent_subscriptions (
  id TEXT PRIMARY KEY,
  owner TEXT NOT NULL,
  vendor TEXT NOT NULL,             -- claude | codex
  name TEXT NOT NULL DEFAULT '',
  tier TEXT NOT NULL DEFAULT '',
  token_ciphertext BLOB NOT NULL,
  created_at REAL NOT NULL,
  last_used REAL
);
"""

VENDORS = ("claude", "codex")


class SubscriptionStore:
    def __init__(self, auth):
        self.auth = auth
        self._db = auth._db
        self._conn = auth._conn
        self._lock = auth._lock
        self._db.migrate("agent_subscriptions", [(1, "initial", _SCHEMA)])
        # deterministic across restarts (derived from the master key):
        # minted session credentials stay resolvable after a reboot
        self._hmac_key = auth.derive_key("session-credential")

    # -- CRUD ----------------------------------------------------------------
    def create(self, owner: str, vendor: str, token: str,
               name: str = "", tier: str = "") -> dict:
        if vendor not in VENDORS:
            raise ValueError(f"vendor must be one of {VENDORS}")
        if not token:
            raise ValueError("token is required")
        sid = f"sub_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO agent_subscriptions(id, owner, vendor, name,"
                " tier, token_ciphertext, created_at)"
                " VALUES(?,?,?,?,?,?,?)",
                (sid, owner, vendor, name or vendor, tier,
                 self.auth.encrypt(token.encode()), time.time()),
            )
            self._db.commit()
        return self.get(sid)

    def get(self, sid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, owner, vendor, name, tier, created_at,"
                " last_used FROM agent_subscriptions WHERE id=?",
                (sid,),
            ).fetchone()
        if row is None:
            return None
        return {
            "id": row[0], "owner": row[1], "vendor": row[2],
            "name": row[3], "tier": row[4], "created_at": row[5],
            "last_used": row[6],
        }

    def list(self, owner: str, vendor: Optional[str] = None) -> List[dict]:
        q = ("SELECT id FROM agent_subscriptions WHERE owner=?")
        args: list = [owner]
        if vendor:
            q += " AND vendor=?"
            args.append(vendor)
        q += " ORDER BY created_at"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [self.get(r[0]) for r in rows]

    def delete(self, sid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM agent_subscriptions WHERE id=?", (sid,)
            )
            self._db.commit()
        return cur.rowcount > 0

    # -- in-process consumers ------------------------------------------------
    def token(self, sid: str) -> Optional[str]:
        with self._lock:
            row = self._conn.execute(
                "SELECT token_ciphertext FROM agent_subscriptions"
                " WHERE id=?",
                (sid,),
            ).fetchone()
            if row is None:
                return None
            self._conn.execute(
                "UPDATE agent_subscriptions SET last_used=? WHERE id=?",
                (time.time(), sid),
            )
            self._db.commit()
        return self.auth.decrypt(row[0]).decode()

    # -- session-scoped credentials ------------------------------------------
    def mint_session_credential(self, sid: str, session_id: str,
                                ttl: float = 3600.0) -> dict:
        """A signed, expiring handle binding subscription -> session.
        The sandbox presents the handle; the control plane exchanges it
        in-process via resolve_session_credential — the raw OAuth token
        never rides the session wire."""
        if self.get(sid) is None:
            raise KeyError(sid)
        expires = int(time.time() + ttl)
        msg = f"{sid}:{session_id}:{expires}".encode()
        sig = hmac.new(self._hmac_key, msg, hashlib.sha256).hexdigest()
        return {
            "subscription_id": sid,
            "session_id": session_id,
            "expires": expires,
            "credential": f"hxc_{sid}.{session_id}.{expires}.{sig}",
        }

    def resolve_session_credential(self, credential: str) -> Optional[str]:
        """credential handle -> raw token (None: invalid/expired)."""
        if not credential.startswith("hxc_"):
            return None
        try:
            sid, session_id, expires_s, sig = (
                credential[len("hxc_"):].split(".")
            )
            expires = int(expires_s)
        except ValueError:
            return None
        if time.time() > expires:
            return None
        msg = f"{sid}:{session_id}:{expires}".encode()
        want = hmac.new(self._hmac_key, msg, hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            return None
        return self.token(sid)
