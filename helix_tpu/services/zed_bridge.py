"""Zed editor integration: instance/thread bridge over durable streams.

The reference bridges Zed editor instances to helix work sessions with a
versioned protocol over NATS JetStream queues
(``api/pkg/pubsub/zed_protocol.go``: zed_instance_management /
zed_thread_management / zed_events streams, v1.0 envelope with
message_id + correlation metadata) — spec-task threads open as Zed agent
threads, activity/heartbeat flows back into the kanban.

This is the same bridge over our durable JetStream analogue
(:mod:`helix_tpu.control.jetstream` via the EventBus): an envelope-
compatible protocol module + a :class:`ZedBridge` service that

- consumes ``instance_create`` / ``thread_create`` requests and answers
  ``instance_created`` / ``thread_created`` on the event stream (queue
  semantics: one bridge instance wins each request);
- tracks instances and threads, with heartbeat-timeout eviction
  (a dead editor must not hold a work session);
- routes ``activity_update`` / ``progress_update`` into the spec-task
  service so the kanban card reflects editor-thread progress;
- exposes the registry to the HTTP surface (``/api/v1/zed/instances``).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

PROTOCOL_VERSION = "v1.0"

STREAM_INSTANCES = "zed_instance_management"
STREAM_THREADS = "zed_thread_management"
STREAM_EVENTS = "zed_events"

T_INSTANCE_CREATE = "instance_create"
T_INSTANCE_CREATED = "instance_created"
T_INSTANCE_STOP = "instance_stop"
T_INSTANCE_STOPPED = "instance_stopped"
T_THREAD_CREATE = "thread_create"
T_THREAD_CREATED = "thread_created"
T_HEARTBEAT = "heartbeat"
T_ACTIVITY = "activity_update"
T_PROGRESS = "progress_update"


def make_message(msg_type: str, data: dict, metadata: Optional[dict] = None
                 ) -> dict:
    """v1.0 envelope (zed_protocol.go NewZedProtocolMessage)."""
    return {
        "version": PROTOCOL_VERSION,
        "message_id": f"zmsg_{uuid.uuid4().hex[:16]}",
        "type": msg_type,
        "data": data,
        "metadata": metadata or {},
        "timestamp": time.time(),
    }


def validate_message(msg: dict) -> None:
    for f in ("version", "message_id", "type", "data"):
        if f not in msg:
            raise ValueError(f"zed message missing {f!r}")
    if msg["version"] != PROTOCOL_VERSION:
        raise ValueError(f"unsupported zed protocol {msg['version']!r}")


def stream_for(msg_type: str) -> str:
    if msg_type.startswith("instance_"):
        return STREAM_INSTANCES
    if msg_type.startswith("thread_"):
        return STREAM_THREADS
    return STREAM_EVENTS


@dataclass
class ZedThread:
    id: str
    instance_id: str
    work_session_id: str = ""
    name: str = ""
    status: str = "starting"
    last_activity: float = field(default_factory=time.time)


@dataclass
class ZedInstance:
    id: str
    spec_task_id: str = ""
    user_id: str = ""
    project_path: str = ""
    status: str = "starting"
    auth_token: str = ""
    created: float = field(default_factory=time.time)
    last_heartbeat: float = field(default_factory=time.time)
    threads: Dict[str, ZedThread] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "id": self.id, "spec_task_id": self.spec_task_id,
            "user_id": self.user_id, "project_path": self.project_path,
            "status": self.status, "created": self.created,
            "last_heartbeat": self.last_heartbeat,
            "threads": [
                {
                    "id": t.id, "work_session_id": t.work_session_id,
                    "name": t.name, "status": t.status,
                    "last_activity": t.last_activity,
                }
                for t in self.threads.values()
            ],
        }


class ZedBridge:
    """Bridge service: consumes instance/thread requests, keeps the
    registry, routes events into spec tasks."""

    def __init__(self, bus, task_note=None,
                 heartbeat_timeout: float = 90.0):
        """task_note(task_id, kind, note): sink for thread activity on the
        kanban card (the server wires it to the spec-task service)."""
        self.bus = bus
        self.task_note = task_note
        self.heartbeat_timeout = heartbeat_timeout
        self.instances: Dict[str, ZedInstance] = {}
        self._lock = threading.Lock()
        self._subs: list = []
        self._stop = threading.Event()
        self._evictor: Optional[threading.Thread] = None

    def start(self, auto_evict: bool = True) -> "ZedBridge":
        # queue groups: of N bridge replicas, one consumes each request
        self._subs = [
            self.bus.subscribe(
                STREAM_INSTANCES, self._on_instance_msg, group="zed-bridge"
            ),
            self.bus.subscribe(
                STREAM_THREADS, self._on_thread_msg, group="zed-bridge"
            ),
            self.bus.subscribe(
                STREAM_EVENTS, self._on_event, group="zed-bridge"
            ),
        ]
        if auto_evict:
            # periodic heartbeat-timeout eviction (router.evict_stale
            # posture): a crashed editor must not hold sessions forever
            def run():
                while not self._stop.wait(
                    min(self.heartbeat_timeout / 3, 30.0)
                ):
                    self.evict_stale()

            self._evictor = threading.Thread(
                target=run, name="zed-bridge-evict", daemon=True
            )
            self._evictor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()

    # -- message handlers --------------------------------------------------
    def _on_instance_msg(self, topic: str, msg: dict) -> None:
        try:
            validate_message(msg)
        except ValueError:
            return
        data = msg["data"]
        if msg["type"] == T_INSTANCE_CREATE:
            inst = ZedInstance(
                id=data.get("instance_id") or f"zed_{uuid.uuid4().hex[:12]}",
                spec_task_id=data.get("spec_task_id", ""),
                user_id=data.get("user_id", ""),
                project_path=data.get("project_path", ""),
                status="running",
                auth_token=uuid.uuid4().hex,
            )
            for tcfg in data.get("initial_threads", []):
                t = self._thread_from_config(inst.id, tcfg)
                inst.threads[t.id] = t
            with self._lock:
                self.instances[inst.id] = inst
            self.bus.publish(STREAM_EVENTS, make_message(
                T_INSTANCE_CREATED,
                {
                    "instance_id": inst.id, "status": inst.status,
                    "auth_token": inst.auth_token,
                    "websocket_url": f"/api/v1/zed/{inst.id}/ws",
                    "created_at": inst.created,
                },
                {"correlation_id": msg["message_id"],
                 "spec_task_id": inst.spec_task_id},
            ))
        elif msg["type"] == T_INSTANCE_STOP:
            iid = data.get("instance_id", "")
            with self._lock:
                inst = self.instances.pop(iid, None)
            if inst is not None:
                self.bus.publish(STREAM_EVENTS, make_message(
                    T_INSTANCE_STOPPED, {"instance_id": iid},
                    {"correlation_id": msg["message_id"]},
                ))

    def _thread_from_config(self, instance_id: str, tcfg: dict) -> ZedThread:
        return ZedThread(
            id=tcfg.get("thread_id") or f"zth_{uuid.uuid4().hex[:12]}",
            instance_id=instance_id,
            work_session_id=tcfg.get("work_session_id", ""),
            name=tcfg.get("name", ""),
            status="running",
        )

    def _on_thread_msg(self, topic: str, msg: dict) -> None:
        try:
            validate_message(msg)
        except ValueError:
            return
        if msg["type"] != T_THREAD_CREATE:
            return
        data = msg["data"]
        iid = data.get("instance_id", "")
        with self._lock:
            inst = self.instances.get(iid)
            if inst is None:
                return
            t = self._thread_from_config(iid, data.get("thread", {}))
            inst.threads[t.id] = t
        self.bus.publish(STREAM_EVENTS, make_message(
            T_THREAD_CREATED,
            {"instance_id": iid, "thread_id": t.id, "status": t.status},
            {"correlation_id": msg["message_id"],
             "work_session_id": t.work_session_id},
        ))

    def _on_event(self, topic: str, msg: dict) -> None:
        try:
            validate_message(msg)
        except ValueError:
            return
        data = msg["data"]
        if msg["type"] == T_HEARTBEAT:
            with self._lock:
                inst = self.instances.get(data.get("instance_id", ""))
                if inst is not None:
                    inst.last_heartbeat = time.time()
                    inst.status = data.get("status", inst.status)
        elif msg["type"] in (T_ACTIVITY, T_PROGRESS):
            iid = data.get("instance_id", "")
            tid = data.get("thread_id", "")
            with self._lock:
                inst = self.instances.get(iid)
                thread = inst.threads.get(tid) if inst else None
                if thread is not None:
                    thread.last_activity = time.time()
                    thread.status = data.get("status", thread.status)
            # kanban routing: editor-thread progress lands on the task
            if self.task_note is not None and inst is not None \
                    and inst.spec_task_id:
                note = data.get("description") or data.get("activity", "")
                try:
                    self.task_note(
                        inst.spec_task_id, f"zed:{msg['type']}", note[:500]
                    )
                except Exception:  # noqa: BLE001 — unknown task id
                    pass

    # -- registry ----------------------------------------------------------
    def evict_stale(self) -> List[str]:
        """Instances whose editor stopped heartbeating are evicted (the
        connman-grace posture: a dead editor frees its work sessions)."""
        now = time.time()
        gone = []
        with self._lock:
            for iid, inst in list(self.instances.items()):
                if now - inst.last_heartbeat > self.heartbeat_timeout:
                    del self.instances[iid]
                    gone.append(iid)
        for iid in gone:
            self.bus.publish(STREAM_EVENTS, make_message(
                T_INSTANCE_STOPPED,
                {"instance_id": iid, "reason": "heartbeat timeout"},
            ))
        return gone

    def list(self) -> List[dict]:
        with self._lock:
            return [i.to_dict() for i in self.instances.values()]

    def get(self, iid: str) -> Optional[ZedInstance]:
        with self._lock:
            return self.instances.get(iid)
