"""Sandbox child process: runs one agent turn inside an isolated workspace.

The in-sandbox half of the isolated executor (reference: the agent binary
running inside a hydra dev container, ``api/pkg/external-agent/
hydra_executor.go:130-569``).  The parent (``SandboxExecutor``) launches
this module with resource limits applied, a scrubbed environment, and the
workspace as cwd; the only egress is the control plane's OpenAI endpoint
(HELIX_API_BASE) — exactly how the reference's containerised agents talk
back to Helix.

Protocol (stdout, line-oriented, mirrored into the watchable desktop
stream by the parent):

    STEP {json StepInfo}        one per agent step
    RESULT {"answer": ...}      terminal line on success
    ERROR {"error": ...}        terminal line on failure

The job spec arrives as one JSON document on stdin.  This module imports
only the jax-free agent core — a sandbox child never touches the
accelerator.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys


class HTTPLLM:
    """Minimal OpenAI-compatible chat client (the sandbox's only egress)."""

    def __init__(self, base_url: str, api_key: str = ""):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key

    async def chat(self, body: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            f"{self.base_url}/v1/chat/completions",
            data=json.dumps(body).encode(),
            headers={
                "Content-Type": "application/json",
                **(
                    {"Authorization": f"Bearer {self.api_key}"}
                    if self.api_key
                    else {}
                ),
            },
        )

        def call():
            with urllib.request.urlopen(req, timeout=600) as resp:
                return json.loads(resp.read())

        return await asyncio.get_running_loop().run_in_executor(None, call)


def shell_skill(root: str, timeout: float = 120.0):
    """Run shell commands inside the workspace.  Only offered in the
    sandbox child — the process is already resource-limited and isolated,
    which is the reference's model (agents get a full shell *inside* the
    container, never in the control plane)."""
    import subprocess

    from helix_tpu.agent.skill import Skill

    def run(command: str) -> str:
        p = subprocess.run(
            command, shell=True, cwd=root, capture_output=True, text=True,
            timeout=timeout,
        )
        out = (p.stdout or "") + (p.stderr or "")
        return f"exit={p.returncode}\n{out[:8000]}"

    return Skill(
        name="shell",
        description="Run a shell command in the workspace; returns exit "
                    "code and output.",
        parameters={
            "type": "object",
            "properties": {"command": {"type": "string"}},
            "required": ["command"],
        },
        handler=run,
        dangerous=True,
    )


def _apply_limits(limits: dict) -> None:
    """Apply resource limits first thing, before any agent code runs —
    this module is the trusted launcher inside the sandbox."""
    import resource

    cpu = int(limits.get("cpu_s", 0))
    if cpu > 0:
        resource.setrlimit(resource.RLIMIT_CPU, (cpu, cpu))
    nofile = int(limits.get("nofile", 0))
    if nofile > 0:
        resource.setrlimit(resource.RLIMIT_NOFILE, (nofile, nofile))
    mem = int(limits.get("memory_bytes", 0))
    if mem > 0:
        try:
            resource.setrlimit(resource.RLIMIT_AS, (mem, mem))
        except (ValueError, OSError):  # pragma: no cover - platform
            pass


def main() -> int:
    job = json.loads(sys.stdin.read())
    _apply_limits(job.get("limits") or {})

    from helix_tpu.agent.agent import Agent, AgentConfig
    from helix_tpu.agent.skill import SkillRegistry
    from helix_tpu.agent.skills import filesystem_skill

    workspace = os.getcwd()
    skills = [filesystem_skill(workspace)]
    if job.get("shell", True):
        skills.append(shell_skill(workspace))

    def emit(step):
        print("STEP " + json.dumps(step.to_dict()), flush=True)

    agent = Agent(
        AgentConfig(
            prompt=job["prompt"],
            model=job.get("model", ""),
            max_iterations=int(job.get("max_iterations", 12)),
        ),
        SkillRegistry(skills),
        HTTPLLM(
            os.environ.get("HELIX_API_BASE", job.get("api_base", "")),
            os.environ.get("HELIX_API_KEY", job.get("api_key", "")),
        ),
        emitter=emit,
    )
    try:
        answer, _steps = asyncio.run(agent.run(job["message"]))
    except Exception as e:  # noqa: BLE001 — reported over the protocol
        print("ERROR " + json.dumps({"error": f"{type(e).__name__}: {e}"}),
              flush=True)
        return 1
    print("RESULT " + json.dumps({"answer": answer}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
