"""Agent evaluation suites: scripted questions + assertions against an app.

Reference surface: ``api/pkg/types/evaluation.go`` (EvaluationSuite /
EvaluationRun / assertion types contains | not_contains | regex |
llm_judge | skill_used), persisted entities at
``api/pkg/store/postgres.go:245-246``, routes at
``api/pkg/server/server.go:1058-1067`` (suite CRUD under an app, run
start/list/get/delete + an SSE progress stream), and the ``evals`` CLI
verb (``api/cmd/helix/evals.go``).

Design: a run executes every suite question through the session
controller (the same ``ChatCompletion`` path users hit, so agent-mode
apps exercise their real skill loop), applies the question's assertions
to the response, and persists per-question results + an aggregate
summary.  Progress events stream over the in-process event bus so the
HTTP layer can serve them as SSE.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import re
import time
from typing import Optional

log = logging.getLogger("helix.evals")

ASSERTION_TYPES = (
    "contains", "not_contains", "regex", "llm_judge", "skill_used",
)

_JUDGE_PROMPT = (
    "You are grading an AI assistant's answer.\n"
    "Question: {question}\n"
    "Answer: {answer}\n"
    "Grading instruction: {instruction}\n"
    "Reply with exactly PASS or FAIL on the first line, then one short "
    "sentence of reasoning."
)


@dataclasses.dataclass
class Assertion:
    type: str
    value: str = ""
    llm_judge_prompt: str = ""

    @classmethod
    def from_doc(cls, doc: dict) -> "Assertion":
        t = doc.get("type", "contains")
        if t not in ASSERTION_TYPES:
            raise ValueError(f"unknown assertion type {t!r}")
        return cls(
            type=t,
            value=doc.get("value", ""),
            llm_judge_prompt=doc.get("llm_judge_prompt", ""),
        )


def validate_suite_doc(doc: dict) -> dict:
    """Normalise + validate a suite document; raises ValueError."""
    questions = doc.get("questions") or []
    if not isinstance(questions, list):
        raise ValueError("questions must be a list")
    out_q = []
    for i, q in enumerate(questions):
        if not q.get("question"):
            raise ValueError(f"question #{i} has no text")
        asserts = [
            dataclasses.asdict(Assertion.from_doc(a))
            for a in (q.get("assertions") or [])
        ]
        out_q.append(
            {
                "id": q.get("id") or f"q{i + 1}",
                "question": q["question"],
                "assertions": asserts,
            }
        )
    return {
        "name": doc.get("name", ""),
        "description": doc.get("description", ""),
        # judge model/provider for llm_judge assertions; empty = first
        # available model (self-hosted deployments have no external judge)
        "judge_model": doc.get("judge_model", ""),
        "judge_provider": doc.get("judge_provider", ""),
        "questions": out_q,
    }


class EvalService:
    """Runs evaluation suites through the controller; persists results."""

    def __init__(self, store, controller, events=None):
        self.store = store
        self.controller = controller
        self.events = events          # EventBus (optional)
        self._tasks: dict[str, asyncio.Task] = {}
        # crash recovery: run tasks are in-memory only, so rows left in a
        # non-terminal state by a previous process can never finish —
        # fail them at boot (reference boot-time reset of running
        # executions, serve.go:270-278)
        for run in self.store.list_eval_runs():
            if run.get("status") in ("pending", "running"):
                doc = {
                    "summary": run.get("summary", {}),
                    "results": run.get("results", []),
                    "error": "interrupted by control-plane restart",
                }
                self.store.update_eval_run(run["id"], "failed", doc)

    # -- suite CRUD (thin wrappers; validation lives here) -----------------
    def create_suite(self, app_id: str, owner: str, doc: dict) -> dict:
        sid = self.store.create_eval_suite(
            app_id, owner, validate_suite_doc(doc)
        )
        return self.store.get_eval_suite(sid)

    def update_suite(self, sid: str, doc: dict) -> Optional[dict]:
        if not self.store.update_eval_suite(sid, validate_suite_doc(doc)):
            return None
        return self.store.get_eval_suite(sid)

    # -- runs --------------------------------------------------------------
    def start_run(self, suite_id: str, owner: str) -> Optional[dict]:
        """Create a pending run and launch it on the current event loop."""
        suite = self.store.get_eval_suite(suite_id)
        if suite is None:
            return None
        rid = self.store.create_eval_run(
            suite_id, suite.get("app_id", ""), owner,
            {"summary": {}, "results": []},
        )
        self._tasks[rid] = asyncio.get_event_loop().create_task(
            self._run(rid, suite, owner)
        )
        return self.store.get_eval_run(rid)

    def cancel_run(self, rid: str) -> bool:
        task = self._tasks.get(rid)
        if task is None or task.done():
            return False
        task.cancel()
        return True

    async def _run(self, rid: str, suite: dict, owner: str) -> None:
        results = []
        summary = {
            "total_questions": len(suite["questions"]),
            "passed": 0, "failed": 0, "total_duration_ms": 0,
            "total_tokens": 0, "skills_used": [],
        }
        doc = {"summary": summary, "results": results}
        self.store.update_eval_run(rid, "running", doc)
        self._progress(rid, "running", 0, summary)
        try:
            for i, q in enumerate(suite["questions"]):
                result = await self._run_question(suite, q, owner)
                results.append(result)
                summary["passed" if result["passed"] else "failed"] += 1
                summary["total_duration_ms"] += result["duration_ms"]
                summary["total_tokens"] += result.get("tokens_used", 0)
                for s in result.get("skills_used", []):
                    if s not in summary["skills_used"]:
                        summary["skills_used"].append(s)
                self.store.update_eval_run(rid, "running", doc)
                self._progress(rid, "running", i + 1, summary, result)
            self.store.update_eval_run(rid, "completed", doc)
            self._progress(
                rid, "completed", len(suite["questions"]), summary
            )
        except asyncio.CancelledError:
            doc["error"] = "cancelled"
            self.store.update_eval_run(rid, "cancelled", doc)
            self._progress(rid, "cancelled", len(results), summary)
        except Exception as e:  # noqa: BLE001 — run must land in a state
            log.exception("eval run %s failed", rid)
            doc["error"] = str(e)
            self.store.update_eval_run(rid, "failed", doc)
            self._progress(rid, "failed", len(results), summary)
        finally:
            self._tasks.pop(rid, None)

    async def _run_question(self, suite: dict, q: dict, owner: str) -> dict:
        t0 = time.monotonic()
        result = {
            "question_id": q["id"],
            "question": q["question"],
            "response": "",
            "duration_ms": 0,
            "tokens_used": 0,
            "skills_used": [],
            "assertion_results": [],
            "passed": False,
            "error": "",
        }
        try:
            resp = await self.controller.chat(
                [{"role": "user", "content": q["question"]}],
                user=owner,
                app_id=suite.get("app_id") or None,
            )
            answer = (
                resp.get("choices", [{}])[0]
                .get("message", {})
                .get("content", "")
            )
            result["response"] = answer
            usage = resp.get("usage") or {}
            result["tokens_used"] = int(usage.get("total_tokens", 0))
            result["skills_used"] = sorted(
                {
                    s.get("name", "")
                    for s in resp.get("steps", [])
                    if s.get("kind") == "tool" and s.get("name")
                }
            )
            checks = [
                await self._check(suite, a, q["question"], answer, result)
                for a in (
                    Assertion.from_doc(d) for d in q["assertions"]
                )
            ]
            result["assertion_results"] = checks
            result["passed"] = all(c["passed"] for c in checks)
        except Exception as e:  # noqa: BLE001 — one bad question != run
            result["error"] = str(e)
        result["duration_ms"] = int((time.monotonic() - t0) * 1000)
        return result

    async def _check(
        self, suite: dict, a: Assertion, question: str, answer: str,
        result: dict,
    ) -> dict:
        out = {
            "assertion_type": a.type,
            "assertion_value": a.value,
            "passed": False,
            "details": "",
        }
        if a.type == "contains":
            out["passed"] = a.value.lower() in answer.lower()
        elif a.type == "not_contains":
            out["passed"] = a.value.lower() not in answer.lower()
        elif a.type == "regex":
            try:
                out["passed"] = re.search(a.value, answer) is not None
            except re.error as e:
                out["details"] = f"bad regex: {e}"
        elif a.type == "skill_used":
            out["passed"] = a.value in result["skills_used"]
        elif a.type == "llm_judge":
            out.update(await self._judge(suite, a, question, answer))
        return out

    async def _judge(
        self, suite: dict, a: Assertion, question: str, answer: str
    ) -> dict:
        """LLM-judge assertion: ask a model to grade PASS/FAIL.

        The judge model comes from the suite (``judge_model`` /
        ``judge_provider``); unset, it falls back to the first model the
        router actually serves — a bare resolve("") in a helix-only
        deployment would 404 on the empty model name."""
        prompt = (a.llm_judge_prompt or _JUDGE_PROMPT).format(
            question=question, answer=answer,
            instruction=a.value or "Is the answer correct and helpful?",
        )
        model = suite.get("judge_model", "")
        provider = suite.get("judge_provider") or None
        if not model and not provider:
            router = getattr(self.controller.providers, "_router", None)
            served = router.available_models() if router else []
            if served:
                model = served[0]
        client, model = self.controller.providers.resolve(model, provider)
        resp = await client.chat(
            {
                "model": model,
                "messages": [{"role": "user", "content": prompt}],
                "temperature": 0.0,
            }
        )
        verdict = (
            resp.get("choices", [{}])[0]
            .get("message", {})
            .get("content", "")
        )
        first = verdict.strip().splitlines()[0].strip().upper() if verdict else ""
        return {"passed": first.startswith("PASS"), "details": verdict[:500]}

    def _progress(
        self, rid: str, status: str, current: int, summary: dict,
        latest: Optional[dict] = None,
    ) -> None:
        if self.events is None:
            return
        evt = {
            "run_id": rid,
            "status": status,
            "current_question": current,
            "total_questions": summary.get("total_questions", 0),
            "summary": summary,
        }
        if latest is not None:
            evt["latest_result"] = latest
        try:
            self.events.publish(f"evals.{rid}", evt)
        except Exception:  # noqa: BLE001 — progress is best-effort
            log.debug("eval progress publish failed", exc_info=True)
