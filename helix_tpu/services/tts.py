"""TTS sidecar: /v1/audio/speech serving WAV.

Reference: ``tts-server/`` — an optional sidecar wrapping a neural TTS
engine behind a small HTTP surface.  This build keeps the same shape:
an OpenAI-compatible ``/v1/audio/speech`` route with a pluggable
``synthesize(text, voice, speed) -> (pcm16, sample_rate)`` backend.  The
built-in backend is a dependency-free formant synthesizer (diphone-ish
vowel formants + noise bursts for consonants) — intelligibility is not
the point; the API surface, WAV plumbing, and backend seam are, and a
neural acoustic model drops into the same seam.
"""

from __future__ import annotations

import io
import struct
import wave

import numpy as np

SAMPLE_RATE = 16_000

# coarse letter -> (f1, f2) vowel formants / noise flags
_VOWELS = {
    "a": (730, 1090), "e": (530, 1840), "i": (270, 2290),
    "o": (570, 840), "u": (300, 870), "y": (270, 2100),
}
_VOICED = set("bdgjlmnrvwz")


def _segment(ch: str, dur_s: float, f0: float, sr: int) -> np.ndarray:
    n = max(int(dur_s * sr), 1)
    t = np.arange(n) / sr
    env = np.hanning(n)
    if ch in _VOWELS:
        f1, f2 = _VOWELS[ch]
        carrier = (
            0.6 * np.sign(np.sin(2 * np.pi * f0 * t))  # glottal-ish buzz
        )
        formant = (
            0.5 * np.sin(2 * np.pi * f1 * t)
            + 0.35 * np.sin(2 * np.pi * f2 * t)
        )
        return env * carrier * (0.5 + 0.5 * formant)
    if ch.isalpha():
        rng = np.random.default_rng(ord(ch))
        noise = rng.standard_normal(n) * 0.3
        if ch in _VOICED:
            noise += 0.4 * np.sin(2 * np.pi * f0 * t)
        return env * noise
    return np.zeros(n)   # space / punctuation = silence


def formant_synthesize(
    text: str, voice: str = "default", speed: float = 1.0,
    sample_rate: int = SAMPLE_RATE,
) -> tuple:
    """-> (int16 pcm array, sample_rate)."""
    f0 = {"default": 120.0, "alto": 180.0, "bass": 90.0}.get(voice, 120.0)
    speed = min(max(speed, 0.25), 4.0)
    base = 0.09 / speed
    parts = [
        _segment(ch, base * (1.4 if ch in _VOWELS else 0.8), f0,
                 sample_rate)
        for ch in text.lower()[:2000]
    ] or [np.zeros(sample_rate // 10)]
    pcm = np.concatenate(parts)
    peak = np.max(np.abs(pcm)) or 1.0
    return (pcm / peak * 0.8 * 32767).astype(np.int16), sample_rate


def to_wav_bytes(pcm: np.ndarray, sample_rate: int) -> bytes:
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()


def klatt_synthesize(
    text: str, voice: str = "default", speed: float = 1.0,
    sample_rate: int = 16000,
) -> tuple:
    """Default backend: the rule-based Klatt-style pipeline
    (text normalisation -> letter-to-sound -> prosody -> cascade formant
    synthesis, :mod:`helix_tpu.services.tts_klatt`)."""
    from helix_tpu.services.tts_klatt import SR, synthesize

    f0 = {"default": 120.0, "alto": 180.0, "bass": 90.0}.get(voice, 120.0)
    speed = min(max(speed, 0.25), 4.0)
    pcm = synthesize(text[:2000], f0_base=f0, speed=speed)
    return (pcm * 32767).astype(np.int16), SR


class TTSService:
    def __init__(self, synthesize=None):
        self.synthesize = synthesize or klatt_synthesize

    def speech(self, text: str, voice: str = "default",
               speed: float = 1.0) -> bytes:
        pcm, sr = self.synthesize(text, voice=voice, speed=speed)
        return to_wav_bytes(np.asarray(pcm, np.int16), sr)

    async def handle_speech(self, request):
        """Shared /v1/audio/speech handler (mounted by the sidecar app
        AND the control plane — one copy of validation + dispatch)."""
        import asyncio as _asyncio

        from aiohttp import web

        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        text = body.get("input", "")
        if not text:
            return web.json_response(
                {"error": {"message": "missing input"}}, status=400
            )
        try:
            speed = float(body.get("speed", 1.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "speed must be a number"}},
                status=400,
            )
        if not (0.1 <= speed <= 10.0):   # also rejects NaN
            return web.json_response(
                {"error": {"message": "speed out of range (0.1-10)"}},
                status=400,
            )
        wav = await _asyncio.get_running_loop().run_in_executor(
            None,
            lambda: self.speech(
                text, voice=body.get("voice", "default"), speed=speed
            ),
        )
        return web.Response(body=wav, content_type="audio/wav")

    def build_app(self):
        from aiohttp import web

        async def healthz(request):
            return web.json_response({"status": "ok"})

        app = web.Application()
        app.router.add_post("/v1/audio/speech", self.handle_speech)
        app.router.add_get("/healthz", healthz)
        return app
