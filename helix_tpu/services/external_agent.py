"""External coding agents on the kanban: an ACP client over stdio.

The reference's headline orchestration runs third-party coding agents
(Claude Code, Zed, Qwen Code) against spec-task workspaces inside hydra
desktop containers, speaking the Agent Client Protocol over stdio
(``api/pkg/external-agent/hydra_executor.go:130-569``, executor seam
``external-agent/executor.go:13-37``).  This module is the TPU build's
equivalent: ``ExternalAgentExecutor`` fills the orchestrator's
``Executor`` seam by launching ANY ACP-speaking agent CLI as a
resource-limited subprocess whose cwd is the task's git workspace,
driving ``initialize -> session/new -> session/prompt`` and mirroring
``session/update`` notifications into the watchable desktop stream.

Protocol subset (JSON-RPC 2.0, one message per line over stdio):

    -> {"jsonrpc":"2.0","id":1,"method":"initialize",
        "params":{"protocolVersion":1}}
    <- {"jsonrpc":"2.0","id":1,"result":{"protocolVersion":1}}
    -> {"id":2,"method":"session/new","params":{"cwd": <workspace>}}
    <- {"id":2,"result":{"sessionId":"sess-1"}}
    -> {"id":3,"method":"session/prompt","params":{"sessionId":"sess-1",
        "prompt":[{"type":"text","text": <prompt>}]}}
    <- {"method":"session/update","params":{"update":{
        "sessionUpdate":"agent_message_chunk",
        "content":{"type":"text","text":"..."}}}}        (0..n)
    <- {"id":3,"result":{"stopReason":"end_turn"}}

The agent edits files directly in its cwd (the git workspace); the
orchestrator commits and opens the PR exactly as for in-process agents.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import subprocess
import sys
import threading
from typing import Callable, Optional

from helix_tpu.services.sandbox_executor import _StepView
from helix_tpu.services.spec_tasks import (
    Executor,
    SpecTask,
    build_agent_message,
    build_agent_prompt,
)


class ACPError(RuntimeError):
    pass


class ACPClient:
    """Line-JSON-RPC client half of ACP over a child's stdio."""

    def __init__(self, proc: subprocess.Popen,
                 on_update: Optional[Callable[[dict], None]] = None):
        self._proc = proc
        self._ids = itertools.count(1)
        self._pending: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._eof = False
        self.on_update = on_update or (lambda u: None)
        self._reader = threading.Thread(
            target=self._read_loop, name="acp-reader", daemon=True
        )
        self._reader.start()

    def _read_loop(self):
        for line in self._proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                # agents log to stdout too; surface as an update
                self.on_update({"sessionUpdate": "stdout", "text": line})
                continue
            if "id" in msg and ("result" in msg or "error" in msg):
                with self._cond:
                    self._pending[msg["id"]] = msg
                    self._cond.notify_all()
            elif msg.get("method") == "session/update":
                self.on_update(
                    (msg.get("params") or {}).get("update") or {}
                )
            elif "id" in msg and "method" in msg:
                # agent-initiated request: answer it or the agent blocks
                # forever waiting (claude-code-acp asks permission before
                # edits; the workspace sandbox IS the permission boundary
                # here, same as the reference's container policy)
                self._answer_agent_request(msg)
        with self._cond:
            self._eof = True
            self._cond.notify_all()

    def _answer_agent_request(self, msg: dict):
        method, mid = msg["method"], msg["id"]
        if method == "session/request_permission":
            opts = (msg.get("params") or {}).get("options") or []
            pick = next(
                (o for o in opts
                 if str(o.get("kind", "")).startswith("allow")),
                opts[0] if opts else {"optionId": "allow"},
            )
            reply = {"jsonrpc": "2.0", "id": mid, "result": {
                "outcome": {"outcome": "selected",
                            "optionId": pick.get("optionId", "allow")},
            }}
        else:
            reply = {"jsonrpc": "2.0", "id": mid, "error": {
                "code": -32601, "message": f"method not supported: {method}",
            }}
        try:
            self._proc.stdin.write(json.dumps(reply) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass

    def request(self, method: str, params: dict, timeout: float) -> dict:
        mid = next(self._ids)
        doc = {"jsonrpc": "2.0", "id": mid, "method": method,
               "params": params}
        try:
            self._proc.stdin.write(json.dumps(doc) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise ACPError(f"agent closed stdin mid-{method}: {e}") from e
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        import time as _time

        t_end = _time.monotonic() + deadline
        with self._cond:
            while mid not in self._pending:
                if self._eof:
                    raise ACPError(
                        f"agent exited before replying to {method}"
                    )
                left = t_end - _time.monotonic()
                if left <= 0:
                    raise ACPError(f"{method} timed out after {timeout}s")
                self._cond.wait(timeout=min(left, 0.5))
            msg = self._pending.pop(mid)
        if "error" in msg:
            e = msg["error"]
            raise ACPError(
                f"{method} failed: {e.get('message', e)} "
                f"(code {e.get('code')})"
            )
        return msg.get("result") or {}


class ExternalAgentExecutor(Executor):
    """Run an external ACP agent CLI per task turn, sandboxed.

    ``argv`` is the agent command (e.g. ``["claude-code-acp"]`` or
    ``["zed", "--acp"]``); it runs in its own session with rlimits applied
    by the trusted ``exec_launcher``, a scrubbed environment (plus
    ``extra_env`` for the agent's own credentials), and cwd = workspace.
    """

    def __init__(
        self,
        argv: list,
        make_emitter=None,
        time_limit: float = 900.0,
        rpc_timeout: float = 60.0,
        extra_env: Optional[dict] = None,
        cpu_limit_s: int = 600,
        memory_limit_bytes: int = 2 << 30,
    ):
        self.argv = list(argv)
        self.make_emitter = make_emitter
        self.time_limit = time_limit
        self.rpc_timeout = rpc_timeout
        self.extra_env = dict(extra_env or {})
        self.cpu_limit_s = cpu_limit_s
        self.memory_limit_bytes = memory_limit_bytes

    def _agent_cwd(self, workspace: str) -> str:
        """Workspace path AS THE AGENT SEES IT (container executors remap
        the host workspace to a fixed mount point)."""
        return workspace

    def _env(self, workspace: str) -> dict:
        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": workspace,
            "LANG": os.environ.get("LANG", "C.UTF-8"),
        }
        env.update(self.extra_env)
        return env

    def _spawn(self, workspace: str) -> subprocess.Popen:
        """Launch the agent process for one turn.  The base class applies
        rlimits via the trusted exec launcher; ``ContainerAgentExecutor``
        (``helix_tpu.services.containers``) overrides this to run the same
        ACP conversation inside a mount/pid/user-namespace container."""
        launcher_spec = json.dumps({
            "argv": self.argv,
            "limits": {
                "cpu_s": self.cpu_limit_s,
                "memory_bytes": self.memory_limit_bytes,
                "nofile": 512,
            },
        })
        helix_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = self._env(workspace)
        env["PYTHONPATH"] = helix_root   # for the launcher only
        return subprocess.Popen(
            [sys.executable, "-m", "helix_tpu.services.exec_launcher",
             launcher_spec],
            cwd=workspace,
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
        )

    def run(self, task: SpecTask, workspace: str, mode: str,
            feedback: str = "") -> str:
        prompt = build_agent_prompt(task, mode)
        message = build_agent_message(task, feedback)
        emit, close = (lambda s: None), (lambda: None)
        if self.make_emitter is not None:
            emit, close = self.make_emitter(task, mode)

        proc = self._spawn(workspace)

        # drain stderr off-thread: an agent that can't even start (binary
        # missing, import error) explains itself ONLY here
        stderr_tail: list = []

        def drain_stderr():
            for line in proc.stderr:
                line = line.rstrip("\n")
                if line:
                    stderr_tail.append(line)
                    del stderr_tail[:-20]

        threading.Thread(target=drain_stderr, daemon=True).start()

        chunks: list = []

        def on_update(update: dict):
            kind = update.get("sessionUpdate", "")
            if kind == "agent_message_chunk":
                text = (update.get("content") or {}).get("text", "")
                chunks.append(text)
                emit(_StepView({"kind": "answer", "name": "agent",
                                "result": text}))
            elif kind == "tool_call":
                emit(_StepView({
                    "kind": "tool",
                    "name": update.get("title")
                    or update.get("toolCallId", "tool"),
                    "arguments": update.get("rawInput"),
                    "result": update.get("status", ""),
                }))
            else:
                emit(_StepView({"kind": "tool", "name": kind or "update",
                                "arguments": None,
                                "result": update.get("text", "")}))

        def kill_tree():
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

        timer = threading.Timer(self.time_limit, kill_tree)
        timer.daemon = True
        timer.start()
        try:
            client = ACPClient(proc, on_update=on_update)
            client.request(
                "initialize", {"protocolVersion": 1}, self.rpc_timeout
            )
            sess = client.request(
                "session/new", {"cwd": self._agent_cwd(workspace)},
                self.rpc_timeout,
            )
            sid = sess.get("sessionId", "")
            result = client.request(
                "session/prompt",
                {
                    "sessionId": sid,
                    "prompt": [{"type": "text",
                                "text": f"{prompt}\n\n{message}"}],
                },
                # the prompt turn does the actual work — give it the whole
                # wall-clock budget, the outer timer still bounds it
                self.time_limit,
            )
            stop = result.get("stopReason", "end_turn")
            if stop not in ("end_turn", "max_turn_requests"):
                raise ACPError(f"agent stopped abnormally: {stop}")
        except ACPError as e:
            tail = "\n".join(stderr_tail[-10:])
            if tail:
                raise ACPError(f"{e}\nagent stderr:\n{tail}") from e
            raise
        finally:
            timer.cancel()
            try:
                proc.stdin.close()
            except OSError:
                pass
            kill_tree()
            proc.wait()
            close()
        return "".join(chunks).strip()[-2000:]
