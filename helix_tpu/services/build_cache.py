"""Shared per-project build caches for sandboxed agent runs.

Reference: the sandbox node shares ONE BuildKit daemon + registry across
all agent desktops so a cold 43-minute stack build becomes ~0.5 s warm
(``api/pkg/hydra/manager.go:16-52``, ``design/2026-02-21-smart-load-blog``),
and ``api/cmd/docker-wrapper`` intercepts ``docker build`` to route every
container build through it.

This build's agents run in process sandboxes over plain directories, so
the same capability maps to toolchain cache redirection: every sandboxed
build of a project points its package/compiler caches at ONE shared
per-project directory, so task N+1's ``pip install`` / ``npm ci`` /
``cargo build`` hits task N's warm cache instead of re-downloading and
re-compiling.  The moving parts:

- ``env_for(project)`` -> env vars redirecting the common toolchain caches
  (pip, uv, npm, Go build+module, ccache, cargo registry, generic
  XDG_CACHE_HOME) into ``<root>/<project-slug>/``.  Injected into the
  sandbox child env by ``SandboxExecutor`` — the agent needs no wrapper
  binary because cache location is an env contract for these tools.
- usage accounting + ``gc(max_bytes)``: least-recently-USED project
  caches are evicted first (use = an ``env_for`` call, touched on disk),
  mirroring hydra's disk-pressure-driven GC
  (``api/pkg/hydra/disk_pressure.go``, ``workspace_gc.go``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import shutil
import threading
import time

log = logging.getLogger("helix.buildcache")

# env var -> subdirectory under the project cache
_CACHE_ENV = {
    "PIP_CACHE_DIR": "pip",
    "UV_CACHE_DIR": "uv",
    "NPM_CONFIG_CACHE": "npm",
    "GOMODCACHE": "gomod",
    "GOCACHE": "gobuild",
    "CCACHE_DIR": "ccache",
    "CARGO_HOME": "cargo",
    "XDG_CACHE_HOME": "xdg",
}


def _slug(name: str) -> str:
    s = re.sub(r"[^A-Za-z0-9._-]+", "-", name).strip("-")
    return s or "default"


@dataclasses.dataclass
class CacheInfo:
    project: str
    bytes: int
    last_used: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class BuildCacheManager:
    """One shared cache tree per project under ``root``."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def project_dir(self, project: str) -> str:
        return os.path.join(self.root, _slug(project))

    def env_for(self, project: str) -> dict:
        """Cache-redirection env for one sandboxed build; creating the
        directories counts as a use (LRU freshness)."""
        base = self.project_dir(project)
        env = {}
        with self._lock:
            for var, sub in _CACHE_ENV.items():
                d = os.path.join(base, sub)
                os.makedirs(d, exist_ok=True)
                env[var] = d
            os.utime(base)
        return env

    # ------------------------------------------------------------------
    def _tree_bytes(self, path: str) -> int:
        total = 0
        for r, _, files in os.walk(path):
            for f in files:
                try:
                    total += os.lstat(os.path.join(r, f)).st_size
                except OSError:
                    pass
        return total

    def list(self) -> list:
        out = []
        with self._lock:
            for name in sorted(os.listdir(self.root)):
                p = os.path.join(self.root, name)
                if not os.path.isdir(p):
                    continue
                try:
                    used = os.stat(p).st_mtime
                except OSError:
                    continue
                out.append(CacheInfo(
                    project=name,
                    bytes=self._tree_bytes(p),
                    last_used=used,
                ))
        return out

    def total_bytes(self) -> int:
        return sum(c.bytes for c in self.list())

    def drop(self, project: str) -> bool:
        p = self.project_dir(project)
        with self._lock:
            if not os.path.isdir(p):
                return False
            shutil.rmtree(p, ignore_errors=True)
        return True

    def gc(self, max_bytes: int) -> list:
        """Evict least-recently-used project caches until the tree fits
        ``max_bytes``.  Returns the evicted project names."""
        infos = self.list()
        total = sum(c.bytes for c in infos)
        evicted = []
        if total <= max_bytes:
            return evicted
        for c in sorted(infos, key=lambda c: c.last_used):
            if total <= max_bytes:
                break
            if self.drop(c.project):
                log.info(
                    "build-cache gc: evicted %s (%d bytes, idle %.0fs)",
                    c.project, c.bytes, time.time() - c.last_used,
                )
                evicted.append(c.project)
                total -= c.bytes
        return evicted
