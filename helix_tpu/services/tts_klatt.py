"""Rule-based speech synthesis: text -> phonemes -> Klatt-style formant
synthesis.

The reference runs a neural TTS sidecar (``tts-server/``).  This image
has no speech weights and no egress, so the backend is the classic
knowledge-based pipeline (the DECtalk/MITalk family — Klatt 1980,
"Software for a cascade/parallel formant synthesizer"; NRL 1976
letter-to-sound report), implemented from the published principles:

1. text normalisation — numbers to words, abbreviations, punctuation to
   phrase breaks;
2. grapheme-to-phoneme — a context-sensitive letter-to-sound rule set
   (longest-match rules with left/right context classes, NRL-style);
3. prosody — declining F0 contour per phrase, phrase-final lengthening,
   pauses at punctuation;
4. acoustic synthesis — a cascade formant synthesizer: voiced glottal
   source + noise source through three time-varying second-order
   resonators, with per-phoneme formant targets (Peterson–Barney /
   Klatt tables), linear formant transitions for coarticulation, stop
   closures + bursts, aspiration for voiceless onsets.

Output is intelligible machine speech, not natural speech — the honest
ceiling of a weightless synthesizer.  The neural seam stays:
``TTSService(synthesize=...)`` accepts any backend.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

SR = 16000
FRAME_S = 0.005                # coefficient update interval


# ---------------------------------------------------------------------------
# phoneme inventory: name -> (F1, F2, F3, duration_ms, kind)
# kind: v=vowel, n=nasal, l=liquid/glide, f=voiceless fricative,
#       z=voiced fricative, p=voiceless stop, b=voiced stop,
#       a=affricate(vl), j=affricate(vd), h=aspirate, sil=silence
# Formant targets from the published Peterson–Barney / Klatt tables.
# ---------------------------------------------------------------------------

PHONES = {
    # vowels
    "AA": (730, 1090, 2440, 160, "v"),   # father
    "AE": (660, 1720, 2410, 150, "v"),   # cat
    "AH": (640, 1190, 2390, 110, "v"),   # but
    "AO": (570, 840, 2410, 160, "v"),    # law
    "EH": (530, 1840, 2480, 130, "v"),   # bet
    "ER": (490, 1350, 1690, 150, "v"),   # bird
    "IH": (390, 1990, 2550, 110, "v"),   # bit
    "IY": (270, 2290, 3010, 140, "v"),   # beet
    "OW": (450, 1030, 2380, 160, "v"),   # boat
    "UH": (440, 1020, 2240, 110, "v"),   # book
    "UW": (300, 870, 2240, 150, "v"),    # boot
    "AX": (500, 1500, 2500, 70, "v"),    # about (schwa)
    # diphthongs synthesized as two targets (see DIPHTHONGS)
    "AY": (730, 1090, 2440, 200, "v"),
    "AW": (730, 1090, 2440, 200, "v"),
    "EY": (530, 1840, 2480, 180, "v"),
    "OY": (570, 840, 2410, 200, "v"),
    # nasals
    "M": (280, 900, 2200, 70, "n"),
    "N": (280, 1700, 2600, 70, "n"),
    "NG": (280, 2300, 2750, 90, "n"),
    # liquids / glides
    "L": (360, 1100, 2600, 70, "l"),
    "R": (420, 1300, 1600, 80, "l"),
    "W": (300, 700, 2200, 70, "l"),
    "Y": (270, 2200, 3000, 60, "l"),
    # voiceless fricatives (noise center freq encoded in F2 slot)
    "S": (200, 6000, 7000, 110, "f"),
    "SH": (200, 2600, 3500, 120, "f"),
    "F": (200, 1400, 6000, 100, "f"),
    "TH": (200, 1600, 6500, 90, "f"),
    "HH": (500, 1500, 2500, 60, "h"),
    # voiced fricatives
    "Z": (250, 6000, 7000, 90, "z"),
    "ZH": (250, 2600, 3500, 100, "z"),
    "V": (250, 1400, 6000, 70, "z"),
    "DH": (250, 1600, 6500, 60, "z"),
    # stops (F2 = burst center)
    "P": (200, 800, 2000, 90, "p"),
    "T": (200, 4500, 5500, 90, "p"),
    "K": (200, 2200, 3200, 95, "p"),
    "B": (250, 800, 2000, 70, "b"),
    "D": (250, 4000, 5000, 70, "b"),
    "G": (250, 2000, 3000, 75, "b"),
    # affricates
    "CH": (200, 2600, 3500, 120, "a"),
    "JH": (250, 2600, 3500, 100, "j"),
    # silence / pause
    "SIL": (0, 0, 0, 120, "sil"),
    "PAU": (0, 0, 0, 250, "sil"),
}

DIPHTHONGS = {
    "AY": ("AA", "IY"), "AW": ("AA", "UW"),
    "EY": ("EH", "IY"), "OY": ("AO", "IY"),
}


# ---------------------------------------------------------------------------
# letter-to-sound rules (NRL-style): (left, letters, right, phones)
# context classes: '#'=one or more vowels, '^'=consonant, '.'=voiced
# consonant, '$'=zero or more consonants, ' '=word boundary, ''=any.
# Scanned in order; first match wins; longest letter groups first.
# ---------------------------------------------------------------------------

VOWELS = set("aeiouy")
CONSONANTS = set("bcdfghjklmnpqrstvwxz")
VOICED_C = set("bdvgjlmnrwz")

RULES: List[Tuple[str, str, str, str]] = [
    # punctuation handled upstream; common whole words first
    (" ", "the", " ", "DH AX"),
    (" ", "a", " ", "AX"),
    (" ", "to", " ", "T UW"),
    (" ", "of", " ", "AH V"),
    (" ", "and", " ", "AE N D"),
    (" ", "is", " ", "IH Z"),
    (" ", "are", " ", "AA R"),
    (" ", "was", " ", "W AH Z"),
    (" ", "you", " ", "Y UW"),
    (" ", "i", " ", "AY"),
    (" ", "one", " ", "W AH N"),
    (" ", "two", " ", "T UW"),
    (" ", "have", " ", "HH AE V"),
    (" ", "do", " ", "D UW"),
    (" ", "does", " ", "D AH Z"),
    (" ", "done", " ", "D AH N"),
    # multi-letter graphemes
    ("", "tion", "", "SH AX N"),
    ("", "sion", "", "ZH AX N"),
    ("", "ough", " ", "OW"),
    ("", "ought", "", "AO T"),
    ("", "igh", "", "AY"),
    ("", "eigh", "", "EY"),
    ("", "tch", "", "CH"),
    ("", "ch", "", "CH"),
    ("", "sh", "", "SH"),
    ("", "ph", "", "F"),
    ("", "th", "", "TH"),     # (voiced 'th' handled by word rules above)
    ("", "wh", "", "W"),
    ("", "gh", "", ""),       # silent (light)
    ("", "ck", "", "K"),
    ("", "ng", " ", "NG"),
    ("", "ng", "", "NG G"),
    ("", "qu", "", "K W"),
    ("", "kn", "", "N"),      # knee
    (" ", "wr", "", "R"),     # write
    ("", "dge", "", "JH"),
    # vowel digraphs
    ("", "ee", "", "IY"),
    ("", "ea", "", "IY"),
    ("", "oo", "k", "UH"),
    ("", "oo", "", "UW"),
    ("", "ou", "", "AW"),
    ("", "ow", " ", "OW"),
    ("", "ow", "", "AW"),
    ("", "oa", "", "OW"),
    ("", "oi", "", "OY"),
    ("", "oy", "", "OY"),
    ("", "ai", "", "EY"),
    ("", "ay", "", "EY"),
    ("", "au", "", "AO"),
    ("", "aw", "", "AO"),
    ("", "ei", "", "EY"),
    ("", "ey", " ", "IY"),
    ("", "ie", " ", "AY"),
    ("", "ie", "", "IY"),
    ("", "ue", "", "UW"),
    ("", "ui", "", "UW"),
    # magic-e: vowel ^ e(word end) -> long vowel
    ("", "a", "^e ", "EY"),
    ("", "i", "^e ", "AY"),
    ("", "o", "^e ", "OW"),
    ("", "u", "^e ", "UW"),
    ("", "e", "^e ", "IY"),
    # single vowels
    ("", "e", " ", ""),        # final silent e
    ("", "e", "d ", "AX"),     # -ed (approx)
    ("", "a", "", "AE"),
    ("", "e", "", "EH"),
    ("", "i", "", "IH"),
    ("", "o", " ", "OW"),      # final open o (hello, go)
    ("", "o", "", "AA"),
    ("", "u", "", "AH"),
    ("", "y", " ", "IY"),
    (" ", "y", "", "Y"),
    ("", "y", "", "IH"),
    # consonants with context
    ("", "c", "e", "S"), ("", "c", "i", "S"), ("", "c", "y", "S"),
    ("", "c", "", "K"),
    ("", "g", "e", "JH"), ("", "g", "i", "JH"), ("", "g", "y", "JH"),
    ("", "g", "", "G"),
    ("#", "s", " ", "Z"),      # plural after vowel
    ("", "s", "", "S"),
    ("", "x", "", "K S"),
    ("", "j", "", "JH"),
    ("", "b", "", "B"), ("", "d", "", "D"), ("", "f", "", "F"),
    ("", "h", "", "HH"), ("", "k", "", "K"), ("", "l", "", "L"),
    ("", "m", "", "M"), ("", "n", "", "N"), ("", "p", "", "P"),
    ("", "r", "", "R"), ("", "t", "", "T"), ("", "v", "", "V"),
    ("", "w", "", "W"), ("", "z", "", "Z"),
]

_ONES = ["zero", "one", "two", "three", "four", "five", "six", "seven",
         "eight", "nine", "ten", "eleven", "twelve", "thirteen",
         "fourteen", "fifteen", "sixteen", "seventeen", "eighteen",
         "nineteen"]
_TENS = ["", "", "twenty", "thirty", "forty", "fifty", "sixty",
         "seventy", "eighty", "ninety"]


def number_to_words(n: int) -> str:
    if n < 0:
        return "minus " + number_to_words(-n)
    if n < 20:
        return _ONES[n]
    if n < 100:
        t, r = divmod(n, 10)
        return _TENS[t] + (" " + _ONES[r] if r else "")
    if n < 1000:
        h, r = divmod(n, 100)
        return (_ONES[h] + " hundred"
                + (" " + number_to_words(r) if r else ""))
    if n < 1_000_000:
        k, r = divmod(n, 1000)
        return (number_to_words(k) + " thousand"
                + (" " + number_to_words(r) if r else ""))
    m, r = divmod(n, 1_000_000)
    return (number_to_words(m) + " million"
            + (" " + number_to_words(r) if r else ""))


_ABBREV = {
    "dr": "doctor", "mr": "mister", "mrs": "missus", "st": "street",
    "etc": "etcetera", "vs": "versus", "e.g": "for example",
    "i.e": "that is",
}


def normalize(text: str) -> str:
    """Numbers to words, abbreviations expanded, case folded."""
    text = text.lower()
    text = re.sub(
        r"\d+", lambda m: " " + number_to_words(int(m.group())) + " ", text
    )
    words = []
    for w in re.split(r"(\s+)", text):
        words.append(_ABBREV.get(w.strip("."), w))
    return "".join(words)


def _ctx_match(ctx: str, s: str, pos: int, left: bool) -> bool:
    """Match one context pattern against the string at pos."""
    if not ctx:
        return True
    step = -1 if left else 1
    i = pos
    for c in (reversed(ctx) if left else ctx):
        ch = s[i] if 0 <= i < len(s) else " "
        if c == "#":
            if ch not in VOWELS:
                return False
        elif c == "^":
            if ch not in CONSONANTS:
                return False
        elif c == ".":
            if ch not in VOICED_C:
                return False
        elif c == " ":
            if ch.isalpha():
                return False
        else:
            if ch != c:
                return False
        i += step
    return True


def to_phonemes(text: str) -> List[str]:
    """Letter-to-sound: normalised text -> phoneme list with PAU breaks."""
    text = normalize(text)
    out: List[str] = []
    for sentence in re.split(r"[.!?;:]+", text):
        sentence = sentence.strip()
        if not sentence:
            continue
        for clause in sentence.split(","):
            clause = " " + re.sub(r"[^a-z.' ]", " ", clause).strip() + " "
            i = 1
            while i < len(clause) - 0:
                if clause[i] == " " or clause[i] in ".'":
                    i += 1
                    continue
                matched = False
                for left, letters, right, phones in RULES:
                    n = len(letters)
                    if clause[i:i + n] != letters:
                        continue
                    if not _ctx_match(left, clause, i - 1, left=True):
                        continue
                    if not _ctx_match(right, clause, i + n, left=False):
                        continue
                    if phones:
                        out.extend(phones.split())
                    i += n
                    matched = True
                    break
                if not matched:
                    i += 1
            out.append("SIL")
        if out and out[-1] == "SIL":
            out[-1] = "PAU"
    # collapse doubled consonants (hello -> one L): adjacent identical
    # non-vowel phones are one articulation
    collapsed: List[str] = []
    for ph in out:
        if (
            collapsed
            and ph == collapsed[-1]
            and PHONES.get(ph, (0, 0, 0, 0, "v"))[4] not in ("v", "sil")
        ):
            continue
        collapsed.append(ph)
    return collapsed


# ---------------------------------------------------------------------------
# cascade formant synthesizer
# ---------------------------------------------------------------------------


def _resonator_coeffs(f: float, bw: float):
    """Klatt second-order resonator: y = A x + B y1 + C y2."""
    c = -np.exp(-2 * np.pi * bw / SR)
    b = 2 * np.exp(-np.pi * bw / SR) * np.cos(2 * np.pi * f / SR)
    a = 1 - b - c
    return a, b, c


class _Resonator:
    """Stateful biquad run through scipy.signal.lfilter (vectorised —
    a pure-Python per-sample loop holds the GIL for ~10M iterations on
    long inputs and starves the serving event loop)."""

    def __init__(self):
        self._zi = np.zeros(2)

    def run(self, x: np.ndarray, f: float, bw: float) -> np.ndarray:
        from scipy.signal import lfilter

        a, b, c = _resonator_coeffs(max(f, 1.0), bw)
        # y[n] = a x[n] + b y[n-1] + c y[n-2]
        y, self._zi = lfilter([a], [1.0, -b, -c], x, zi=self._zi)
        return y


def _glottal_source(n: int, f0: np.ndarray) -> np.ndarray:
    """Impulse train at f0[n] (per-sample), shaped by a one-pole lowpass
    (approximate glottal pulse spectrum, -12 dB/oct)."""
    phase = np.cumsum(f0 / SR)
    pulses = np.diff(np.floor(phase), prepend=0.0) > 0
    src = pulses.astype(np.float64)
    # -12dB/oct shaping: one-pole lowpass, vectorised
    from scipy.signal import lfilter

    y = lfilter([1.0], [1.0, -0.9], src)
    return y - y.mean()


def _expand_targets(phonemes: List[str]):
    """Per-FRAME formant/amplitude targets with linear transitions."""
    segs = []
    for ph in phonemes:
        if ph in DIPHTHONGS:
            a, b = DIPHTHONGS[ph]
            fa, fb = PHONES[a], PHONES[b]
            d = PHONES[ph][3]
            segs.append((fa[0], fa[1], fa[2], d * 0.55, "v"))
            segs.append((fb[0], fb[1], fb[2], d * 0.45, "v"))
        else:
            f1, f2, f3, d, kind = PHONES[ph]
            segs.append((f1, f2, f3, d, kind))
    return segs


def synthesize(text: str, f0_base: float = 120.0,
               speed: float = 1.0) -> np.ndarray:
    """text -> float32 PCM in [-1, 1] at 16 kHz."""
    phonemes = to_phonemes(text)
    if not phonemes:
        return np.zeros(int(0.1 * SR), np.float32)
    segs = _expand_targets(phonemes)

    # per-frame parameter tracks
    frames = []           # (f1, f2, f3, voiced_amp, noise_amp, noise_cf)
    n_total = len(segs)
    for si, (f1, f2, f3, dur_ms, kind) in enumerate(segs):
        # phrase-final lengthening
        if si >= n_total - 2:
            dur_ms *= 1.3
        nfr = max(int(dur_ms / 1000.0 / speed / FRAME_S), 1)
        nfr = min(nfr, 400)   # bound any single segment at 2 s
        if kind == "sil":
            frames += [(500, 1500, 2500, 0.0, 0.0, 0)] * nfr
        elif kind == "v":
            frames += [(f1, f2, f3, 1.0, 0.0, 0)] * nfr
        elif kind in ("n", "l"):
            frames += [(f1, f2, f3, 0.6, 0.0, 0)] * nfr
        elif kind == "f":          # voiceless fricative: noise only
            frames += [(f1, f2, f3, 0.0, 0.8, f2)] * nfr
        elif kind == "z":          # voiced fricative: mixed
            frames += [(f1, f2, f3, 0.4, 0.5, f2)] * nfr
        elif kind == "h":
            frames += [(f1, f2, f3, 0.0, 0.4, 1500)] * nfr
        elif kind in ("p", "b", "a", "j"):
            # closure + burst (+ aspiration when voiceless)
            closure = max(int(0.045 / FRAME_S), 1)
            burst = max(int(0.018 / FRAME_S), 1)
            voiced_leak = 0.15 if kind in ("b", "j") else 0.0
            frames += [(f1, f2, f3, voiced_leak, 0.0, 0)] * closure
            frames += [(f1, f2, f3, 0.0, 1.0, f2)] * burst
            if kind in ("a", "j"):   # affricate: frication tail
                frames += [(f1, f2, f3, 0.0, 0.7, f2)] * (burst * 2)
            elif kind == "p":        # aspiration
                frames += [(f1, f2, f3, 0.0, 0.3, 1500)] * burst

    nfr = len(frames)
    arr = np.array(frames, np.float64)
    # formant smoothing for coarticulation (3-frame boxcar twice ~ 30ms)
    for col in range(3):
        track = arr[:, col]
        for _ in range(2):
            track = np.convolve(
                track, np.ones(5) / 5.0, mode="same"
            )
        arr[:, col] = track

    n = nfr * int(FRAME_S * SR)
    spf = int(FRAME_S * SR)

    # F0 contour: declination across the whole utterance + slight fall
    # within the final phrase
    f0 = np.linspace(f0_base * 1.15, f0_base * 0.85, n)
    voiced_amp = np.repeat(arr[:, 3], spf)[:n]
    noise_amp = np.repeat(arr[:, 4], spf)[:n]

    voiced = _glottal_source(n, f0) * voiced_amp
    rng = np.random.default_rng(0)
    noise = rng.standard_normal(n) * 0.3

    r1, r2, r3 = _Resonator(), _Resonator(), _Resonator()
    rn = _Resonator()
    out = np.zeros(n)
    for fi in range(nfr):
        s, e = fi * spf, (fi + 1) * spf
        f1v, f2v, f3v = arr[fi, 0], arr[fi, 1], arr[fi, 2]
        chunk = voiced[s:e]
        # cascade through three formants
        y = r1.run(chunk, f1v, 60)
        y = r2.run(y, min(f2v, SR / 2 - 500), 90)
        y = r3.run(y, min(f3v, SR / 2 - 200), 150)
        out[s:e] += y
        na = noise_amp[s:e]
        if na.max() > 0:
            cf = arr[fi, 5] if arr[fi, 5] > 0 else f2v
            nz = rn.run(noise[s:e], min(cf, SR / 2 - 500), 600)
            out[s:e] += nz * na

    peak = np.abs(out).max()
    if peak > 0:
        out = out / peak * 0.85
    return out.astype(np.float32)
