"""Organization domain verification and email-domain auto-join.

The reference claims domains per organization and verifies control via a
well-known token (``/api/v1/organization-domains`` +
``/.well-known/helix-domain-verify/{token}`` in
``api/pkg/server/server.go``); users whose email matches a verified
domain join the org automatically.

Flow: claim(org, domain) -> token; the domain owner serves the token at
``https://{domain}/.well-known/helix-domain-verify/{token}``; verify()
fetches it (injectable fetch, crawler SSRF posture) and flips the claim
to verified.  ``org_for_email`` drives auto-join on user creation.  The
control plane also answers its own well-known path for domains it hosts,
so a deployment fronting its org's domain self-verifies.
"""

from __future__ import annotations

import re
import time
import uuid
from typing import Callable, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS org_domains (
  id TEXT PRIMARY KEY,
  org_id TEXT NOT NULL,
  domain TEXT NOT NULL UNIQUE,
  token TEXT NOT NULL UNIQUE,
  verified INTEGER NOT NULL DEFAULT 0,
  auto_join_role TEXT NOT NULL DEFAULT 'member',
  created_at REAL NOT NULL,
  verified_at REAL
);
"""

_DOMAIN_RE = re.compile(
    r"^(?=.{1,253}$)([a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?\.)+"
    r"[a-z]{2,63}$"
)


class OrgDomains:
    def __init__(self, auth, fetch: Optional[Callable] = None):
        """fetch(url) -> body str; defaults to the crawler's SSRF-guarded
        fetcher."""
        self.auth = auth
        self._db = auth._db
        self._conn = auth._conn
        self._lock = auth._lock
        self._db.migrate("org_domains", [(1, "initial", _SCHEMA)])
        self._fetch = fetch

    def _default_fetch(self, url: str) -> str:
        from helix_tpu.knowledge.crawler import default_fetch

        body, _ctype = default_fetch(url, timeout=10.0)
        return body

    # -- claims --------------------------------------------------------------
    def claim(self, org_id: str, domain: str,
              auto_join_role: str = "member") -> dict:
        domain = domain.strip().lower().rstrip(".")
        if not _DOMAIN_RE.match(domain):
            raise ValueError(f"invalid domain {domain!r}")
        did = f"dom_{uuid.uuid4().hex[:12]}"
        token = uuid.uuid4().hex + uuid.uuid4().hex
        import os

        claim_ttl = float(
            os.environ.get("HELIX_DOMAIN_CLAIM_TTL_S", str(72 * 3600))
        )
        with self._lock:
            if self._conn.execute(
                "SELECT 1 FROM orgs WHERE id=?", (org_id,)
            ).fetchone() is None:
                raise KeyError(org_id)
            dup = self._conn.execute(
                "SELECT id, verified, created_at FROM org_domains"
                " WHERE domain=?",
                (domain,),
            ).fetchone()
            if dup:
                # an UNVERIFIED claim is not ownership: it expires after
                # claim_ttl so a squatter cannot block the real owner
                if dup[1] or time.time() - dup[2] < claim_ttl:
                    raise ValueError(
                        f"domain {domain!r} is already claimed"
                    )
                self._conn.execute(
                    "DELETE FROM org_domains WHERE id=?", (dup[0],)
                )
            self._conn.execute(
                "INSERT INTO org_domains(id, org_id, domain, token,"
                " auto_join_role, created_at) VALUES(?,?,?,?,?,?)",
                (did, org_id, domain, token, auto_join_role, time.time()),
            )
            self._db.commit()
        return self.get(did)

    def get(self, did: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, org_id, domain, token, verified,"
                " auto_join_role, created_at, verified_at FROM org_domains"
                " WHERE id=?",
                (did,),
            ).fetchone()
        if row is None:
            return None
        return {
            "id": row[0], "org_id": row[1], "domain": row[2],
            "token": row[3], "verified": bool(row[4]),
            "auto_join_role": row[5], "created_at": row[6],
            "verified_at": row[7],
            "well_known_url": (
                f"https://{row[2]}/.well-known/helix-domain-verify/"
                f"{row[3]}"
            ),
        }

    def list(self, org_id: Optional[str] = None) -> List[dict]:
        q = "SELECT id FROM org_domains"
        args: tuple = ()
        if org_id:
            q += " WHERE org_id=?"
            args = (org_id,)
        q += " ORDER BY created_at"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [self.get(r[0]) for r in rows]

    def delete(self, did: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM org_domains WHERE id=?", (did,)
            )
            self._db.commit()
        return cur.rowcount > 0

    # -- verification --------------------------------------------------------
    def verify(self, did: str) -> dict:
        """Fetch the well-known URL; the body must contain the token."""
        claim = self.get(did)
        if claim is None:
            raise KeyError(did)
        fetch = self._fetch or self._default_fetch
        body = fetch(claim["well_known_url"])
        if claim["token"] not in (body or ""):
            raise PermissionError(
                "well-known token mismatch: serve the token at "
                + claim["well_known_url"]
            )
        with self._lock:
            self._conn.execute(
                "UPDATE org_domains SET verified=1, verified_at=?"
                " WHERE id=?",
                (time.time(), did),
            )
            self._db.commit()
        return self.get(did)

    def token_body(self, token: str) -> Optional[str]:
        """Answer OUR well-known path — but ONLY for domains the operator
        declared this deployment fronts (HELIX_PUBLIC_DOMAINS, comma
        separated).  Answering for every row would let any user claim the
        deployment's own domain and self-verify it, hijacking email
        auto-join."""
        import os

        fronted = {
            d.strip().lower()
            for d in os.environ.get("HELIX_PUBLIC_DOMAINS", "").split(",")
            if d.strip()
        }
        if not fronted:
            return None
        with self._lock:
            row = self._conn.execute(
                "SELECT token, domain FROM org_domains WHERE token=?",
                (token,),
            ).fetchone()
        if row is None or row[1] not in fronted:
            return None
        return row[0]

    # -- auto-join -----------------------------------------------------------
    def org_for_email(self, email: str) -> Optional[dict]:
        """Verified-domain match for an email -> {org_id, role}."""
        domain = email.rsplit("@", 1)[-1].lower()
        with self._lock:
            row = self._conn.execute(
                "SELECT org_id, auto_join_role FROM org_domains"
                " WHERE domain=? AND verified=1",
                (domain,),
            ).fetchone()
        if row is None:
            return None
        return {"org_id": row[0], "role": row[1]}

    def auto_join(self, user) -> Optional[dict]:
        """Join a user to their email-domain org (used at user create)."""
        if not user.email or "@" not in user.email:
            return None
        hit = self.org_for_email(user.email)
        if hit is None:
            return None
        self.auth.add_member(hit["org_id"], user.id, role=hit["role"])
        return hit
