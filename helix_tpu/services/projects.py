"""Projects: the grouping layer over spec-task kanbans.

The reference organizes everything under projects — boards of spec
tasks, attached git repositories, labels, pins, per-project usage
(``api/pkg/server/server.go`` ``/api/v1/projects*`` family backed by the
project store).  Our spec tasks always carried a ``project`` field; this
service gives it a real entity: CRUD + labels + pin + repository
attachments + task-progress aggregation, all on the consolidated
control-plane database (one migration path, cross-entity transactions).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS projects (
  id TEXT PRIMARY KEY,
  name TEXT NOT NULL UNIQUE,
  description TEXT NOT NULL DEFAULT '',
  owner TEXT NOT NULL DEFAULT '',
  labels TEXT NOT NULL DEFAULT '[]',
  pinned INTEGER NOT NULL DEFAULT 0,
  created_at REAL NOT NULL,
  updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS project_repos (
  project_id TEXT NOT NULL,
  repo TEXT NOT NULL,
  is_primary INTEGER NOT NULL DEFAULT 0,
  attached_at REAL NOT NULL,
  PRIMARY KEY (project_id, repo)
);
"""


class ProjectService:
    def __init__(self, db_or_path=":memory:", task_store=None):
        from helix_tpu.control.db import Database

        self._db = Database.resolve(db_or_path)
        self._conn = self._db.conn
        self._lock = self._db.lock
        self._db.migrate("projects", [(1, "initial", _SCHEMA)])
        self.task_store = task_store

    # -- CRUD --------------------------------------------------------------
    def create(self, name: str, description: str = "", owner: str = ""
               ) -> dict:
        if not name or "/" in name:
            raise ValueError("invalid project name")
        pid = f"prj_{uuid.uuid4().hex[:12]}"
        now = time.time()
        with self._lock:
            dup = self._conn.execute(
                "SELECT id FROM projects WHERE name=?", (name,)
            ).fetchone()
            if dup:
                raise ValueError(f"project {name!r} already exists")
            self._conn.execute(
                "INSERT INTO projects(id, name, description, owner, labels,"
                " pinned, created_at, updated_at) VALUES(?,?,?,?,?,0,?,?)",
                (pid, name, description, owner, "[]", now, now),
            )
            self._db.commit()
        return self.get(pid)

    def get(self, pid_or_name: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, name, description, owner, labels, pinned,"
                " created_at, updated_at FROM projects WHERE id=? OR name=?",
                (pid_or_name, pid_or_name),
            ).fetchone()
        if row is None:
            return None
        return self._to_dict(row)

    def _to_dict(self, row) -> dict:
        return {
            "id": row[0], "name": row[1], "description": row[2],
            "owner": row[3], "labels": json.loads(row[4]),
            "pinned": bool(row[5]), "created_at": row[6],
            "updated_at": row[7],
            "repositories": self.repositories(row[0]),
        }

    def list(self, owner: Optional[str] = None) -> List[dict]:
        q = ("SELECT id, name, description, owner, labels, pinned,"
             " created_at, updated_at FROM projects")
        args: tuple = ()
        if owner:
            q += " WHERE owner=?"
            args = (owner,)
        q += " ORDER BY pinned DESC, updated_at DESC"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [self._to_dict(r) for r in rows]

    def update(self, pid: str, **fields) -> dict:
        allowed = {"name", "description", "labels", "pinned"}
        sets, args = [], []
        for k, v in fields.items():
            if k not in allowed or v is None:
                continue
            if k == "labels":
                v = json.dumps(list(v))
            if k == "pinned":
                v = 1 if v else 0
            sets.append(f"{k}=?")
            args.append(v)
        if sets:
            import sqlite3

            sets.append("updated_at=?")
            args.append(time.time())
            with self._lock:
                try:
                    cur = self._conn.execute(
                        f"UPDATE projects SET {', '.join(sets)} WHERE id=?",
                        (*args, pid),
                    )
                except sqlite3.IntegrityError:
                    raise ValueError(
                        "project name already exists"
                    ) from None
                self._db.commit()
                if cur.rowcount == 0:
                    raise KeyError(pid)
        out = self.get(pid)
        if out is None:
            raise KeyError(pid)
        return out

    def delete(self, pid: str) -> bool:
        with self._db.transaction():
            cur = self._conn.execute(
                "DELETE FROM projects WHERE id=?", (pid,)
            )
            self._conn.execute(
                "DELETE FROM project_repos WHERE project_id=?", (pid,)
            )
        return cur.rowcount > 0

    # -- repositories ------------------------------------------------------
    def attach_repo(self, pid: str, repo: str, primary: bool = False
                    ) -> None:
        if self.get(pid) is None:
            raise KeyError(pid)
        with self._db.transaction():
            if primary:
                self._conn.execute(
                    "UPDATE project_repos SET is_primary=0"
                    " WHERE project_id=?",
                    (pid,),
                )
            self._conn.execute(
                "INSERT OR REPLACE INTO project_repos(project_id, repo,"
                " is_primary, attached_at) VALUES(?,?,?,?)",
                (pid, repo, 1 if primary else 0, time.time()),
            )

    def detach_repo(self, pid: str, repo: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM project_repos WHERE project_id=? AND repo=?",
                (pid, repo),
            )
            self._db.commit()
        return cur.rowcount > 0

    def repositories(self, pid: str) -> List[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT repo, is_primary FROM project_repos"
                " WHERE project_id=? ORDER BY is_primary DESC, repo",
                (pid,),
            ).fetchall()
        return [{"repo": r[0], "primary": bool(r[1])} for r in rows]

    # -- aggregation -------------------------------------------------------
    def tasks_progress(self, pid: str) -> dict:
        """Kanban progress for the project board (status -> count), the
        /projects/{id}/tasks-progress shape."""
        p = self.get(pid)
        if p is None:
            raise KeyError(pid)
        counts: dict = {}
        total = done = 0
        if self.task_store is not None:
            for t in self.task_store.list_tasks(project=p["name"]):
                counts[t.status] = counts.get(t.status, 0) + 1
                total += 1
                if t.status == "done":
                    done += 1
        return {
            "project": p["name"], "total": total, "done": done,
            "by_status": counts,
            "percent": round(100.0 * done / total, 1) if total else 0.0,
        }
