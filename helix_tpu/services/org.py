"""Helix Org: a multi-agent "organization" of bots on a reporting DAG.

The counterpart of the reference's largest uncovered subsystem
(``api/pkg/org/`` — DDD-layered bot org-chart: bots in a reporting DAG
(``domain/orgchart/reporting.go:5-17``), topics/channels, dispatch,
activations/wake bus, Slack routing, stream cron), rebuilt at this
framework's scale:

- **Bots**: named agents with a role prompt and a model; many-to-many
  reporting lines form a DAG (cycles rejected on edge insert via an
  ancestor walk, mirroring the reference's add-parent handler).
  Bots flagged ``agent=True`` answer through a REAL agent session (the
  skill loop in ``helix_tpu.agent``) instead of a one-shot completion.
- **Channels**: topics with member bots; posting a message *activates*
  the responsible bot (explicit mention first, else the channel owner),
  which answers with channel history as context.
- **Escalation**: a bot that answers with ``ESCALATE: <why>`` hands the
  thread to its manager(s) up the chain — bounded by the DAG depth.  A
  FAILED activation (agent crash, provider down) escalates the same way
  instead of dying in-channel, so an org never silently drops a thread.
- **Platform routing**: external chat platforms (Slack/Teams/Discord)
  bind to org channels through the shared trigger adapters
  (``helix_tpu.control.triggers.normalize_platform_payload``); inbound
  events post into the bound channel and bot replies flow back through a
  ``send`` callback (the reference's Slack routing,
  ``api/pkg/org/infrastructure``).
- **Activations**: cron-scheduled wakes (``add_activation``) — the
  reference's stream-cron/activations — fire bots into their channel on
  a 5-field cron schedule via ``tick()``.
- **Wake bus**: ``wake(bot_id, note)`` queues an activation the
  dispatcher drains (the reference's activations + wake bus, scaled to
  one process).
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading
import time
import uuid
from typing import Callable, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS org_bots (
    id TEXT PRIMARY KEY,
    org TEXT NOT NULL DEFAULT 'default',
    name TEXT NOT NULL,
    role TEXT DEFAULT '',
    model TEXT DEFAULT '',
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS org_reporting (
    org TEXT NOT NULL,
    manager_id TEXT NOT NULL,
    report_id TEXT NOT NULL,
    PRIMARY KEY (org, manager_id, report_id)
);
CREATE TABLE IF NOT EXISTS org_channels (
    id TEXT PRIMARY KEY,
    org TEXT NOT NULL DEFAULT 'default',
    name TEXT NOT NULL,
    topic TEXT DEFAULT '',
    owner_bot TEXT DEFAULT '',
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS org_channel_members (
    channel_id TEXT NOT NULL,
    bot_id TEXT NOT NULL,
    PRIMARY KEY (channel_id, bot_id)
);
CREATE TABLE IF NOT EXISTS org_messages (
    id TEXT PRIMARY KEY,
    channel_id TEXT NOT NULL,
    author TEXT NOT NULL,       -- 'user:<id>' or 'bot:<id>'
    body TEXT NOT NULL,
    created_at REAL NOT NULL
);
"""

_SCHEMA_V2 = """
ALTER TABLE org_bots ADD COLUMN agent INTEGER NOT NULL DEFAULT 0;
CREATE TABLE IF NOT EXISTS org_bindings (
    platform TEXT NOT NULL,      -- slack | teams | discord
    external_id TEXT NOT NULL,   -- the platform's channel id
    channel_id TEXT NOT NULL,    -- org channel it routes into
    PRIMARY KEY (platform, external_id)
);
CREATE TABLE IF NOT EXISTS org_activations (
    id TEXT PRIMARY KEY,
    bot_id TEXT NOT NULL,
    channel_id TEXT NOT NULL,
    schedule TEXT NOT NULL,      -- 5-field cron
    note TEXT DEFAULT '',
    enabled INTEGER NOT NULL DEFAULT 1,
    last_fired REAL NOT NULL DEFAULT 0,
    created_at REAL NOT NULL
);
"""

ESCALATE_MARKER = "ESCALATE:"


class OrgError(Exception):
    pass


@dataclasses.dataclass
class Bot:
    id: str
    org: str
    name: str
    role: str = ""
    model: str = ""
    agent: bool = False   # answer via a real agent session (skill loop)

    def to_dict(self):
        return dataclasses.asdict(self)


class OrgService:
    def __init__(
        self,
        db_path=":memory:",
        llm: Optional[Callable] = None,
        history_limit: int = 20,
        max_escalations: int = 4,
        agent_runner: Optional[Callable] = None,
    ):
        """``llm(prompt, messages, model) -> str`` produces a bot's reply
        (the control plane wires its provider manager in).
        ``agent_runner(bot, prompt, messages) -> str`` runs an agent-backed
        bot through a real skill-loop session (``helix_tpu.agent``); bots
        created with ``agent=True`` use it when wired."""
        from helix_tpu.control.db import Database

        self._db = Database.resolve(db_path)
        self._conn = self._db.conn
        self._lock = self._db.lock
        self._db.migrate(
            "org",
            [(1, "initial", _SCHEMA), (2, "routing+activations", _SCHEMA_V2)],
        )
        self.llm = llm
        self.agent_runner = agent_runner
        self.history_limit = history_limit
        self.max_escalations = max_escalations
        self._wake_queue: list[tuple[str, str]] = []

    # -- bots + reporting DAG ---------------------------------------------
    def create_bot(self, name: str, role: str = "", model: str = "",
                   org: str = "default", agent: bool = False) -> Bot:
        if not name or not name.strip():
            raise OrgError("bot name is required")
        name = name.strip()
        bot = Bot(
            id=f"bot_{uuid.uuid4().hex[:12]}", org=org, name=name,
            role=role, model=model, agent=agent,
        )
        with self._lock:
            self._conn.execute(
                "INSERT INTO org_bots(id, org, name, role, model, agent, "
                "created_at) VALUES(?,?,?,?,?,?,?)",
                (bot.id, org, name, role, model, int(agent), time.time()),
            )
            self._db.commit()
        return bot

    @staticmethod
    def _bot_row(r) -> Bot:
        return Bot(r[0], r[1], r[2], r[3], r[4], bool(r[5]))

    def get_bot(self, bid: str) -> Optional[Bot]:
        with self._lock:
            r = self._conn.execute(
                "SELECT id, org, name, role, model, agent FROM org_bots "
                "WHERE id=? OR name=?",
                (bid, bid),
            ).fetchone()
        return self._bot_row(r) if r else None

    def bots(self, org: str = "default") -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, org, name, role, model, agent FROM org_bots "
                "WHERE org=? ORDER BY created_at",
                (org,),
            ).fetchall()
        return [self._bot_row(r) for r in rows]

    def delete_bot(self, bid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM org_bots WHERE id=?", (bid,)
            )
            # deleting an endpoint drops every reporting line touching it
            # (reference: 'the store enforces this structurally')
            self._conn.execute(
                "DELETE FROM org_reporting WHERE manager_id=? OR report_id=?",
                (bid, bid),
            )
            self._conn.execute(
                "DELETE FROM org_channel_members WHERE bot_id=?", (bid,)
            )
            # channels owned by the deleted bot fall back to
            # mention-routing rather than silently never answering
            self._conn.execute(
                "UPDATE org_channels SET owner_bot='' WHERE owner_bot=?",
                (bid,),
            )
            self._db.commit()
            return cur.rowcount > 0

    def managers_of(self, bid: str) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT manager_id FROM org_reporting WHERE report_id=?",
                (bid,),
            ).fetchall()
        return [r[0] for r in rows]

    def reports_of(self, bid: str) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT report_id FROM org_reporting WHERE manager_id=?",
                (bid,),
            ).fetchall()
        return [r[0] for r in rows]

    def add_reporting_line(self, manager_id: str, report_id: str,
                           org: str = "default") -> None:
        """report_id reports to manager_id.  Cycles rejected via ancestor
        walk (``orgchart/reporting.go`` + the add-parent handler)."""
        if manager_id == report_id:
            raise OrgError("bot cannot report to itself")
        for bid in (manager_id, report_id):
            if self.get_bot(bid) is None:
                raise OrgError(f"unknown bot {bid}")
        # would manager_id become a descendant of report_id? then cycle
        seen = set()
        frontier = [manager_id]
        while frontier:
            cur = frontier.pop()
            if cur == report_id:
                raise OrgError(
                    f"reporting line {report_id}->{manager_id} would "
                    f"create a cycle"
                )
            if cur in seen:
                continue
            seen.add(cur)
            frontier.extend(self.managers_of(cur))
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO org_reporting(org, manager_id, "
                "report_id) VALUES(?,?,?)",
                (org, manager_id, report_id),
            )
            self._db.commit()

    def chart(self, org: str = "default") -> dict:
        """The org chart the UI renders: bots + edges."""
        with self._lock:
            edges = self._conn.execute(
                "SELECT manager_id, report_id FROM org_reporting WHERE "
                "org=?",
                (org,),
            ).fetchall()
        return {
            "bots": [b.to_dict() for b in self.bots(org)],
            "reporting": [
                {"manager": m, "report": r} for m, r in edges
            ],
        }

    # -- channels ----------------------------------------------------------
    def create_channel(self, name: str, topic: str = "",
                       owner_bot: str = "", members: tuple = (),
                       org: str = "default") -> str:
        if not name or not name.strip():
            raise OrgError("channel name is required")
        cid = f"chn_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO org_channels(id, org, name, topic, owner_bot, "
                "created_at) VALUES(?,?,?,?,?,?)",
                (cid, org, name, topic, owner_bot, time.time()),
            )
            for b in {*members, *( (owner_bot,) if owner_bot else () )}:
                self._conn.execute(
                    "INSERT OR IGNORE INTO org_channel_members(channel_id, "
                    "bot_id) VALUES(?,?)",
                    (cid, b),
                )
            self._db.commit()
        return cid

    def channels(self, org: str = "default") -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, name, topic, owner_bot FROM org_channels WHERE "
                "org=? ORDER BY created_at",
                (org,),
            ).fetchall()
        return [
            {"id": r[0], "name": r[1], "topic": r[2], "owner_bot": r[3]}
            for r in rows
        ]

    def messages(self, channel_id: str, limit: int = 50) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, author, body, created_at FROM org_messages "
                "WHERE channel_id=? ORDER BY created_at DESC LIMIT ?",
                (channel_id, limit),
            ).fetchall()
        return [
            {"id": r[0], "author": r[1], "body": r[2], "created_at": r[3]}
            for r in reversed(rows)
        ]

    def _append(self, channel_id: str, author: str, body: str) -> dict:
        mid = f"msg_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO org_messages(id, channel_id, author, body, "
                "created_at) VALUES(?,?,?,?,?)",
                (mid, channel_id, author, body, time.time()),
            )
            self._db.commit()
        return {"id": mid, "author": author, "body": body}

    # -- dispatch ----------------------------------------------------------
    def _responsible_bot(self, channel: dict, body: str) -> Optional[Bot]:
        """Explicit @mention of a member wins; else the channel owner
        (the reference's topic routing, scaled down)."""
        with self._lock:
            members = [
                r[0] for r in self._conn.execute(
                    "SELECT bot_id FROM org_channel_members WHERE "
                    "channel_id=?",
                    (channel["id"],),
                ).fetchall()
            ]
        import re as _re

        # longest-name-first + word boundary so '@dev2' never routes to a
        # member merely named 'dev'
        bots = sorted(
            filter(None, (self.get_bot(b) for b in members)),
            key=lambda b: -len(b.name),
        )
        for bot in bots:
            if _re.search(
                rf"@{_re.escape(bot.name)}(?![\w-])", body
            ):
                return bot
        return self.get_bot(channel["owner_bot"]) if channel["owner_bot"] else None

    def post(self, channel_id: str, body: str, author: str = "user:anon",
             to_bot: Optional[Bot] = None) -> list:
        """Post to a channel; the responsible bot answers (escalating up
        the reporting chain when it says so).  Returns new messages.
        ``to_bot`` forces the addressee (wake-bus activations)."""
        chan = next(
            (c for c in self.channels_all() if c["id"] == channel_id), None
        )
        if chan is None:
            raise OrgError(f"unknown channel {channel_id}")
        out = [self._append(channel_id, author, body)]
        bot = to_bot if to_bot is not None else self._responsible_bot(
            chan, body
        )
        hops = 0
        visited = set()
        while bot is not None and hops <= self.max_escalations:
            if bot.id in visited:
                break
            visited.add(bot.id)
            reply = self._activate(bot, chan)
            out.append(self._append(channel_id, f"bot:{bot.name}", reply))
            if not reply.startswith(ESCALATE_MARKER):
                break
            managers = [
                m for m in (
                    self.get_bot(x) for x in self.managers_of(bot.id)
                ) if m is not None
            ]
            bot = managers[0] if managers else None
            hops += 1
        return out

    def channels_all(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, name, topic, owner_bot FROM org_channels"
            ).fetchall()
        return [
            {"id": r[0], "name": r[1], "topic": r[2], "owner_bot": r[3]}
            for r in rows
        ]

    def _activate(self, bot: Bot, chan: dict) -> str:
        if self.llm is None and not (bot.agent and self.agent_runner):
            return f"(no llm wired; {bot.name} saw the message)"
        history = self.messages(chan["id"], self.history_limit)
        msgs = [
            {
                "role": "assistant"
                if m["author"] == f"bot:{bot.name}"
                else "user",
                "content": f"{m['author']}: {m['body']}",
            }
            for m in history
        ]
        prompt = (
            f"You are {bot.name}, {bot.role or 'a bot'} in channel "
            f"'{chan['name']}' (topic: {chan['topic'] or 'general'}). "
            f"Answer the channel. If this is outside your remit, reply "
            f"starting with '{ESCALATE_MARKER} <reason>' to hand it to "
            f"your manager."
        )
        try:
            if bot.agent and self.agent_runner is not None:
                # a REAL agent session: skill loop, tools, step records
                return self.agent_runner(bot, prompt, msgs)
            return self.llm(prompt, msgs, bot.model)
        except Exception as e:  # noqa: BLE001 — a failed activation
            # escalates up the chain instead of dying in-channel: the
            # manager (possibly on another model/provider) gets the thread
            return (
                f"{ESCALATE_MARKER} activation failed "
                f"({type(e).__name__}: {e})"
            )

    # -- platform routing (Slack/Teams/Discord -> org channels) ------------
    def bind_channel(self, platform: str, external_id: str,
                     channel_id: str) -> None:
        """Route a platform channel into an org channel (the reference's
        Slack routing: messages in the bound Slack channel activate the
        org's bots and replies flow back)."""
        if not any(c["id"] == channel_id for c in self.channels_all()):
            raise OrgError(f"unknown channel {channel_id}")
        with self._lock:
            self._conn.execute(
                "INSERT INTO org_bindings(platform, external_id, "
                "channel_id) VALUES(?,?,?) ON CONFLICT(platform, "
                "external_id) DO UPDATE SET channel_id=excluded.channel_id",
                (platform, external_id, channel_id),
            )
            self._db.commit()

    def binding_for(self, platform: str, external_id: str) -> Optional[str]:
        with self._lock:
            r = self._conn.execute(
                "SELECT channel_id FROM org_bindings WHERE platform=? AND "
                "external_id=?",
                (platform, external_id),
            ).fetchone()
        return r[0] if r else None

    def bindings(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT platform, external_id, channel_id FROM org_bindings"
            ).fetchall()
        return [
            {"platform": r[0], "external_id": r[1], "channel_id": r[2]}
            for r in rows
        ]

    def handle_platform_event(self, kind: str, payload: dict,
                              send: Optional[Callable] = None):
        """Inbound platform webhook -> bound org channel -> bot replies
        back out through ``send(external_id, text, thread)``.

        Reuses the shared trigger adapters so Slack URL-verification,
        bot-echo suppression and Teams mention stripping behave exactly
        like app triggers do.  Returns (verdict, doc):
        ``("challenge", doc)`` — respond with doc verbatim;
        ``("ignore", reason)``; ``("posted", messages)``.
        """
        from helix_tpu.control.triggers import normalize_platform_payload

        verdict, doc = normalize_platform_payload(kind, payload)
        if verdict != "fire":
            return verdict, doc
        channel_id = self.binding_for(kind, doc.get("channel", ""))
        if channel_id is None:
            return "ignore", f"no binding for {kind}:{doc.get('channel')}"
        author = f"{kind}:{doc.get('user') or 'unknown'}"
        out = self.post(channel_id, doc.get("message", ""), author=author)
        if send is not None:
            for m in out:
                if m["author"].startswith("bot:"):
                    send(
                        doc.get("channel", ""),
                        f"[{m['author'][4:]}] {m['body']}",
                        doc.get("thread", ""),
                    )
        return "posted", out

    # -- scheduled activations (stream cron) -------------------------------
    def add_activation(self, bot_id: str, channel_id: str, schedule: str,
                       note: str = "") -> str:
        """Cron-scheduled wake: the bot activates into its channel on the
        schedule (the reference's activations / stream cron)."""
        from helix_tpu.control.triggers import CronSchedule

        CronSchedule.parse(schedule)   # validate now, not at tick time
        if self.get_bot(bot_id) is None:
            raise OrgError(f"unknown bot {bot_id}")
        if not any(c["id"] == channel_id for c in self.channels_all()):
            raise OrgError(f"unknown channel {channel_id}")
        aid = f"act_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO org_activations(id, bot_id, channel_id, "
                "schedule, note, enabled, last_fired, created_at) "
                "VALUES(?,?,?,?,?,1,0,?)",
                (aid, bot_id, channel_id, schedule, note, time.time()),
            )
            self._db.commit()
        return aid

    def activations(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, bot_id, channel_id, schedule, note, enabled, "
                "last_fired FROM org_activations ORDER BY created_at"
            ).fetchall()
        return [
            {"id": r[0], "bot_id": r[1], "channel_id": r[2],
             "schedule": r[3], "note": r[4], "enabled": bool(r[5]),
             "last_fired": r[6]}
            for r in rows
        ]

    def remove_activation(self, aid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM org_activations WHERE id=?", (aid,)
            )
            self._db.commit()
            return cur.rowcount > 0

    def set_activation_enabled(self, aid: str, enabled: bool) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE org_activations SET enabled=? WHERE id=?",
                (int(enabled), aid),
            )
            self._db.commit()

    def tick(self, now: Optional[float] = None) -> int:
        """Fire activations matching the current minute (the org's cron
        loop; the control plane calls this from the trigger ticker).
        Debounced to once per minute per activation."""
        from helix_tpu.control.triggers import CronSchedule

        now = now if now is not None else time.time()
        st = time.localtime(now)
        fired = 0
        for a in self.activations():
            if not a["enabled"]:
                continue
            try:
                if not CronSchedule.parse(a["schedule"]).matches(st):
                    continue
            except ValueError:
                continue
            if now - a["last_fired"] < 59:
                continue
            with self._lock:
                self._conn.execute(
                    "UPDATE org_activations SET last_fired=? WHERE id=?",
                    (now, a["id"]),
                )
                self._db.commit()
            bot = self.get_bot(a["bot_id"])
            if bot is None:
                continue
            self.post(
                a["channel_id"],
                a["note"] or f"scheduled activation for {bot.name}",
                author="system:cron", to_bot=bot,
            )
            fired += 1
        return fired

    # -- wake bus ----------------------------------------------------------
    def wake(self, bot_id: str, note: str = "") -> None:
        """Queue an activation outside any channel post (the reference's
        wake bus)."""
        self._wake_queue.append((bot_id, note))

    def drain_wakes(self, channel_id: str) -> list:
        """Run queued activations against a channel; returns new messages."""
        out = []
        while self._wake_queue:
            bot_id, note = self._wake_queue.pop(0)
            bot = self.get_bot(bot_id)
            if bot is None:
                continue
            # dispatch to the WOKEN bot, regardless of mentions/ownership
            out.extend(
                self.post(
                    channel_id, note or f"wake {bot.name}",
                    author="system", to_bot=bot,
                )
            )
        return out
