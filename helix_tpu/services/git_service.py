"""Internal git hosting service.

Mirrors the reference's git service inside ``api/pkg/services``
(``git_http_server.go`` + ``git_repository_service*.go``): bare repositories
owned by the control plane, smart-HTTP protocol for real ``git clone/push``
from agent workspaces, branch/diff/log/merge primitives used by the
spec-task pipeline.  Implementation shells out to the system git (the
reference does the same on the sandbox side); the smart-HTTP endpoints call
``upload-pack``/``receive-pack --stateless-rpc`` exactly as git's own
http-backend does.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Optional


class GitError(RuntimeError):
    pass


def _safe_ref(ref: str) -> str:
    """Reject ref/path values that could be parsed as git OPTIONS when
    interpolated into argv (e.g. ``--open-files-in-pager=cmd`` on
    ``git grep`` executes the command; ``--output=path`` on ``git log``
    writes server files).  Every user-facing branch/path query param must
    pass through here before reaching a git command."""
    if not ref or ref.startswith("-") or "\x00" in ref:
        raise GitError(f"invalid ref or path {ref!r}")
    return ref


def _run(args, cwd=None, input_bytes=None, check=True) -> bytes:
    p = subprocess.run(
        args, cwd=cwd, input=input_bytes,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if check and p.returncode != 0:
        raise GitError(
            f"{' '.join(args)} failed ({p.returncode}): "
            f"{p.stderr.decode(errors='replace')[:500]}"
        )
    return p.stdout


class GitService:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    # -- repositories --------------------------------------------------------
    def _repo_path(self, name: str) -> str:
        safe = name.replace("/", "_")
        return os.path.join(self.root, f"{safe}.git")

    def create_repo(self, name: str, default_branch: str = "main") -> str:
        path = self._repo_path(name)
        if os.path.exists(path):
            raise GitError(f"repo '{name}' already exists")
        _run(["git", "init", "--bare", "-b", default_branch, path])
        # seed an empty initial commit so clones have a HEAD
        with tempfile.TemporaryDirectory() as tmp:
            _run(["git", "clone", "-q", path, tmp])
            # cloning an EMPTY repo puts the clone on the local
            # init.defaultBranch (often 'master' on older git), ignoring
            # the bare repo's HEAD — pin the unborn branch so the seed
            # commit lands on (and pushes to) the declared default
            _run(["git", "-C", tmp, "symbolic-ref", "HEAD",
                  f"refs/heads/{_safe_ref(default_branch)}"])
            _run(["git", "-C", tmp, "config", "user.email", "helix@local"])
            _run(["git", "-C", tmp, "config", "user.name", "helix"])
            readme = os.path.join(tmp, "README.md")
            with open(readme, "w") as f:
                f.write(f"# {name}\n")
            _run(["git", "-C", tmp, "add", "-A"])
            _run(["git", "-C", tmp, "commit", "-q", "-m", "initial commit"])
            _run(["git", "-C", tmp, "push", "-q", "origin", default_branch])
        return path

    def repo_exists(self, name: str) -> bool:
        return os.path.isdir(self._repo_path(name))

    def list_repos(self) -> list:
        return sorted(
            d[:-4] for d in os.listdir(self.root) if d.endswith(".git")
        )

    def delete_repo(self, name: str) -> None:
        import shutil

        shutil.rmtree(self._repo_path(name), ignore_errors=True)

    # -- workspace operations -------------------------------------------------
    def clone_workspace(
        self, name: str, dest: str, branch: Optional[str] = None
    ) -> str:
        args = ["git", "clone", "-q"]
        if branch:
            args += ["-b", branch]
        args += [self._repo_path(name), dest]
        _run(args)
        _run(["git", "-C", dest, "config", "user.email", "agent@helix.local"])
        _run(["git", "-C", dest, "config", "user.name", "helix-agent"])
        return dest

    def refresh_workspace(
        self, dest: str, branch: Optional[str] = None
    ) -> None:
        """Bring an EXISTING clone (e.g. a golden hardlink clone that
        already carries .git + warm build artifacts) up to date: fetch
        origin and hard-switch to ``branch`` (default branch when None).
        Non-git files the snapshot carried stay in place — that warmth
        is the point of golden caches."""
        _run(["git", "-C", dest, "fetch", "-q", "origin"])
        if branch is None:
            head = _run(
                ["git", "-C", dest, "symbolic-ref", "-q", "--short",
                 "refs/remotes/origin/HEAD"], check=False,
            ).decode().strip()
            branch = head.split("/", 1)[1] if "/" in head else "main"
        _run(["git", "-C", dest, "checkout", "-q", "-B", branch,
              f"origin/{branch}"])

    def commit_and_push(
        self, workspace: str, message: str, branch: str
    ) -> Optional[str]:
        """Commit all changes and push to ``branch``; returns commit sha or
        None when the tree is clean."""
        _run(["git", "-C", workspace, "add", "-A"])
        status = _run(["git", "-C", workspace, "status", "--porcelain"])
        if not status.strip():
            return None
        _run(["git", "-C", workspace, "commit", "-q", "-m", message])
        sha = _run(["git", "-C", workspace, "rev-parse", "HEAD"]).decode().strip()
        _run(["git", "-C", workspace, "push", "-q", "-f", "origin",
              f"HEAD:{branch}"])
        return sha

    # -- repo queries ----------------------------------------------------------
    def branches(self, name: str) -> list:
        out = _run(
            ["git", "-C", self._repo_path(name), "for-each-ref",
             "--format=%(refname:short)", "refs/heads"]
        )
        return sorted(out.decode().split())

    def log(self, name: str, branch: str = "main", limit: int = 20) -> list:
        try:
            out = _run(
                ["git", "-C", self._repo_path(name), "log",
                 f"--max-count={int(limit)}",
                 "--format=%H%x00%an%x00%at%x00%s",
                 _safe_ref(branch), "--"],
            )
        except GitError:
            return []
        entries = []
        for line in out.decode().splitlines():
            sha, author, at, subject = line.split("\x00")
            entries.append(
                {"sha": sha, "author": author, "time": int(at),
                 "subject": subject}
            )
        return entries

    def branch_exists(self, name: str, branch: str) -> bool:
        try:
            _run(
                ["git", "-C", self._repo_path(name), "rev-parse", "--verify",
                 "--quiet", f"refs/heads/{branch}"],
            )
            return True
        except GitError:
            return False

    def tree(self, name: str, branch: str = "main", path: str = "") -> list:
        """One directory level (the /git/repositories/{id}/tree shape):
        [{path, type: blob|tree, size}]."""
        _safe_ref(branch)
        if path:
            _safe_ref(path)
        spec = f"{branch}:{path}" if path else branch
        try:
            out = _run(
                ["git", "-C", self._repo_path(name), "ls-tree", "--long",
                 spec, "--"],
            )
        except GitError:
            return []
        entries = []
        for line in out.decode().splitlines():
            # <mode> <type> <sha> <size>\t<name>
            meta, fname = line.split("\t", 1)
            parts = meta.split()
            entries.append({
                "path": (path.rstrip("/") + "/" if path else "") + fname,
                "name": fname,
                "type": parts[1],
                "size": 0 if parts[3] == "-" else int(parts[3]),
            })
        return sorted(
            entries, key=lambda e: (e["type"] != "tree", e["name"])
        )

    def grep(self, name: str, pattern: str, branch: str = "main",
             max_results: int = 200) -> list:
        """Regex search over a branch's tree (the /git/repositories/{id}/
        grep shape): [{path, line, text}]."""
        try:
            out = _run(
                ["git", "-C", self._repo_path(name), "grep", "-nIE",
                 "--max-count", "50", "-e", pattern, _safe_ref(branch),
                 "--"],
                check=False,
            )
        except GitError:
            return []
        hits = []
        for line in out.decode(errors="replace").splitlines():
            # <branch>:<path>:<lineno>:<text>
            try:
                _ref, path, lineno, text = line.split(":", 3)
            except ValueError:
                continue
            hits.append({
                "path": path, "line": int(lineno), "text": text[:400],
            })
            if len(hits) >= max_results:
                break
        return hits

    def diff(self, name: str, base: str, head: str) -> str:
        out = _run(
            ["git", "-C", self._repo_path(name), "diff",
             f"{base}...{head}"],
        )
        return out.decode(errors="replace")

    def file_at(self, name: str, branch: str, path: str) -> Optional[str]:
        try:
            out = _run(
                ["git", "-C", self._repo_path(name), "show",
                 f"{_safe_ref(branch)}:{_safe_ref(path)}"],
            )
        except GitError:
            return None
        return out.decode(errors="replace")

    def merge(self, name: str, base: str, head: str, message: str) -> str:
        """Merge ``head`` into ``base`` (no-ff) inside a scratch clone;
        returns the merge commit sha."""
        with self._lock, tempfile.TemporaryDirectory() as tmp:
            _run(["git", "clone", "-q", "-b", base, self._repo_path(name), tmp])
            _run(["git", "-C", tmp, "config", "user.email", "helix@local"])
            _run(["git", "-C", tmp, "config", "user.name", "helix"])
            _run(["git", "-C", tmp, "fetch", "-q", "origin", head])
            _run(["git", "-C", tmp, "merge", "--no-ff", "-q", "-m", message,
                  "FETCH_HEAD"])
            sha = _run(["git", "-C", tmp, "rev-parse", "HEAD"]).decode().strip()
            _run(["git", "-C", tmp, "push", "-q", "origin", f"HEAD:{base}"])
        return sha

    # -- smart HTTP (git clone/push against the control plane) -----------------
    def info_refs(self, name: str, service: str) -> bytes:
        """GET /git/{name}/info/refs?service=git-upload-pack|git-receive-pack"""
        cmd = service.replace("git-", "")
        head = f"# service={service}\n"
        pkt = f"{len(head) + 4:04x}{head}0000".encode()
        out = _run(
            ["git", cmd, "--stateless-rpc", "--advertise-refs",
             self._repo_path(name)]
        )
        return pkt + out

    def service_rpc(self, name: str, service: str, body: bytes) -> bytes:
        """POST /git/{name}/git-upload-pack | git-receive-pack"""
        cmd = service.replace("git-", "")
        return _run(
            ["git", cmd, "--stateless-rpc", self._repo_path(name)],
            input_bytes=body,
        )
