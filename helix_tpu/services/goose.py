"""Goose recipe parsing + parameter substitution.

Reference: ``api/pkg/goose/recipe.go`` — parse Block's Goose recipe YAML
just enough to (1) list declared parameters for the task-creation UI,
(2) substitute provided values Jinja-style (``{{ var }}``), and
(3) reject obviously bogus recipes (no version / malformed YAML).
Unknown variables and complex expressions are left intact for goose's
own Jinja evaluator at agent runtime.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import yaml

_VAR_RE = re.compile(r"\{\{\s*([A-Za-z_][A-Za-z0-9_]*)\s*\}\}")


class RecipeError(ValueError):
    pass


@dataclasses.dataclass
class RecipeParameter:
    key: str
    input_type: str = ""
    requirement: str = ""
    description: str = ""
    default: Optional[str] = None
    options: tuple = ()

    def to_dict(self) -> dict:
        d = {"key": self.key}
        for f in ("input_type", "requirement", "description"):
            if getattr(self, f):
                d[f] = getattr(self, f)
        if self.default is not None:
            d["default"] = self.default
        if self.options:
            d["options"] = list(self.options)
        return d


@dataclasses.dataclass
class Recipe:
    version: str
    title: str = ""
    description: str = ""
    parameters: tuple = ()

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "title": self.title,
            "description": self.description,
            "parameters": [p.to_dict() for p in self.parameters],
        }


def parse(content: str) -> Recipe:
    try:
        doc = yaml.safe_load(content)
    except yaml.YAMLError as e:
        raise RecipeError(f"malformed recipe YAML: {e}") from e
    if not isinstance(doc, dict):
        raise RecipeError("recipe must be a YAML mapping")
    version = doc.get("version")
    if not version:
        raise RecipeError("recipe has no version field")
    params = []
    for p in doc.get("parameters") or []:
        if not isinstance(p, dict) or not p.get("key"):
            raise RecipeError("parameter without a key")
        params.append(
            RecipeParameter(
                key=p["key"],
                input_type=p.get("input_type", ""),
                requirement=p.get("requirement", ""),
                description=p.get("description", ""),
                default=(
                    str(p["default"]) if "default" in p else None
                ),
                options=tuple(p.get("options") or ()),
            )
        )
    return Recipe(
        version=str(version),
        title=doc.get("title", ""),
        description=doc.get("description", ""),
        parameters=tuple(params),
    )


def missing_required(recipe: Recipe, values: dict) -> list:
    """Required parameters with no value and no default."""
    return [
        p.key
        for p in recipe.parameters
        if p.requirement == "required"
        and p.key not in values
        and p.default is None
    ]


def substitute(content: str, values: dict,
               recipe: Optional[Recipe] = None) -> str:
    """Replace ``{{ var }}`` with provided values (falling back to
    declared defaults); anything unresolvable stays intact for goose's
    full Jinja evaluator."""
    defaults = {}
    if recipe is not None:
        defaults = {
            p.key: p.default
            for p in recipe.parameters
            if p.default is not None
        }

    def repl(m: "re.Match") -> str:
        key = m.group(1)
        if key in values:
            return str(values[key])
        if key in defaults:
            return str(defaults[key])
        return m.group(0)

    return _VAR_RE.sub(repl, content)
