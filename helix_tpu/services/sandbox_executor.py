"""Isolated agent execution: each task turn runs in its own OS process.

Fills the ``Executor`` seam (``spec_tasks.py``) the way the reference's
``HydraExecutor`` fills its executor interface (``api/pkg/external-agent/
hydra_executor.go:130-569``: container per session, image by agent type,
idle/GC reaping) — scaled to this build's single-host runtime: a child
process per agent turn with

- its own session (``setsid``) so the whole process tree dies together,
- RLIMIT_AS / RLIMIT_CPU / RLIMIT_NOFILE resource limits,
- a scrubbed environment (no parent secrets; only the control-plane API
  endpoint + key the agent is supposed to use),
- cwd = the task's git workspace (its only filesystem scope of interest),
- a wall-clock budget enforced by the parent (kill the process group).

stdout lines stream into the watchable desktop session live (the
reference's "user watches the agent's desktop" loop, SURVEY.md §3.4).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
from typing import Optional

from helix_tpu.services.spec_tasks import (
    Executor,
    SpecTask,
    build_agent_message,
    build_agent_prompt,
)


class SandboxError(RuntimeError):
    pass


class SandboxExecutor(Executor):
    def __init__(
        self,
        api_base: str,
        api_key: str = "",
        model: str = "",
        max_iterations: int = 12,
        make_emitter=None,
        time_limit: float = 900.0,
        cpu_limit_s: int = 600,
        memory_limit_bytes: int = 2 << 30,
        allow_shell: bool = True,
    ):
        self.api_base = api_base
        self.api_key = api_key
        self.model = model
        self.max_iterations = max_iterations
        self.make_emitter = make_emitter
        self.time_limit = time_limit
        self.cpu_limit_s = cpu_limit_s
        self.memory_limit_bytes = memory_limit_bytes
        self.allow_shell = allow_shell

    def _env(self, workspace: str) -> dict:
        """Scrubbed environment: the agent gets the API endpoint it is
        meant to use and nothing else from the parent."""
        helix_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        return {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": workspace,
            "LANG": os.environ.get("LANG", "C.UTF-8"),
            "PYTHONPATH": helix_root,
            "JAX_PLATFORMS": "cpu",   # a sandbox child never touches chips
            "HELIX_API_BASE": self.api_base,
            "HELIX_API_KEY": self.api_key,
        }

    # ------------------------------------------------------------------
    def run(self, task: SpecTask, workspace: str, mode: str,
            feedback: str = "") -> str:
        prompt = build_agent_prompt(task, mode)
        message = build_agent_message(task, feedback)
        job = {
            "prompt": prompt,
            "message": message,
            "model": self.model,
            "max_iterations": self.max_iterations,
            "shell": self.allow_shell,
            # resource limits applied by the trusted child launcher
            # (sandbox_runner.main) before any agent code runs — no
            # preexec_fn: forked-interpreter Python in a threaded parent
            # can deadlock (subprocess docs)
            "limits": {
                "cpu_s": self.cpu_limit_s,
                "memory_bytes": self.memory_limit_bytes,
                "nofile": 512,
            },
        }
        emit, close = (lambda s: None), (lambda: None)
        if self.make_emitter is not None:
            emit, close = self.make_emitter(task, mode)

        proc = subprocess.Popen(
            [sys.executable, "-m", "helix_tpu.services.sandbox_runner"],
            cwd=workspace,
            env=self._env(workspace),
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            start_new_session=True,  # own group: parent kills the tree
        )
        result: dict = {}
        error: dict = {}

        def kill_tree():
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

        timer = threading.Timer(self.time_limit, kill_tree)
        timer.daemon = True
        timer.start()
        try:
            proc.stdin.write(json.dumps(job))
            proc.stdin.close()
            for line in proc.stdout:
                line = line.rstrip("\n")
                if line.startswith("STEP "):
                    try:
                        doc = json.loads(line[5:])
                    except json.JSONDecodeError:
                        continue
                    emit(_StepView(doc))
                elif line.startswith("RESULT "):
                    try:
                        result = json.loads(line[7:])
                    except json.JSONDecodeError:
                        # stderr is merged into stdout: a logging line
                        # that merely starts with the keyword is output,
                        # not protocol
                        emit(_StepView({"kind": "tool", "name": "stdout",
                                        "arguments": None, "result": line}))
                elif line.startswith("ERROR "):
                    try:
                        error = json.loads(line[6:])
                    except json.JSONDecodeError:
                        emit(_StepView({"kind": "tool", "name": "stdout",
                                        "arguments": None, "result": line}))
                elif line:
                    # raw agent/tool output: mirror it into the session
                    emit(_StepView({"kind": "tool", "name": "stdout",
                                    "arguments": None, "result": line}))
            rc = proc.wait()
        finally:
            timer.cancel()
            kill_tree()   # reap any stragglers in the group
            close()
        if error:
            raise SandboxError(error.get("error", "agent failed"))
        if rc != 0 and not result:
            raise SandboxError(
                f"sandbox exited rc={rc} (killed after {self.time_limit}s?)"
            )
        return result.get("answer", "")


class _StepView:
    """Duck-typed StepInfo for emitters fed from the child's wire format."""

    def __init__(self, doc: dict):
        self.step = doc.get("step", 0)
        self.kind = doc.get("kind", "tool")
        self.name = doc.get("name", "")
        self.arguments = doc.get("arguments")
        self.result = doc.get("result", "") or ""
        self.error = doc.get("error", "") or ""
        self.duration_ms = doc.get("duration_ms", 0)
