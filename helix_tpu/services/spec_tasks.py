"""Spec-driven task orchestration: the Kanban engine.

Mirrors the reference's headline feature (``api/pkg/services/
spec_task_orchestrator.go:299-330,605-912``): tasks flow
backlog -> planning -> spec_review -> (revision loops) -> implementing ->
pr_review -> done, driven by a polling orchestration loop; a planning agent
writes a spec to a ``helix-specs`` branch of the project's internal repo,
human design review gates implementation, an implementation agent codes on
a task branch, and an internal pull request (diff + review + merge) closes
the loop.  Agent execution is pluggable (``Executor``) — the reference
launches desktop containers via hydra; this build's default executor runs
the in-process agent loop against a git workspace, and a sandbox executor
can slot in without touching the orchestrator.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import sqlite3
import tempfile
import threading
import time
import traceback
import uuid
from typing import Callable, Optional

log = logging.getLogger("helix.spectasks")

from helix_tpu.services.git_service import GitService

_SCHEMA = """
CREATE TABLE IF NOT EXISTS spec_tasks (
    id TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    title TEXT NOT NULL,
    description TEXT DEFAULT '',
    status TEXT NOT NULL DEFAULT 'backlog',
    spec_branch TEXT DEFAULT '',
    task_branch TEXT DEFAULT '',
    spec_path TEXT DEFAULT '',
    pr_id TEXT DEFAULT '',
    error TEXT DEFAULT '',
    ci_attempts INTEGER DEFAULT 0,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS design_reviews (
    id TEXT PRIMARY KEY,
    task_id TEXT NOT NULL,
    author TEXT,
    comment TEXT NOT NULL,
    decision TEXT NOT NULL,      -- approve | request_changes | comment
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS pull_requests (
    id TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    task_id TEXT,
    title TEXT,
    base TEXT NOT NULL,
    head TEXT NOT NULL,
    status TEXT NOT NULL DEFAULT 'open',   -- open | merged | closed
    merge_sha TEXT DEFAULT '',
    ci_status TEXT DEFAULT 'pending',  -- pending|running|passed|failed|none
    ci_log TEXT DEFAULT '',
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
"""

# columns added after round 1 — bring pre-existing DBs forward
_MIGRATIONS = (
    "ALTER TABLE pull_requests ADD COLUMN ci_status TEXT DEFAULT 'pending'",
    "ALTER TABLE pull_requests ADD COLUMN ci_log TEXT DEFAULT ''",
    "ALTER TABLE spec_tasks ADD COLUMN ci_attempts INTEGER DEFAULT 0",
)

STATUSES = (
    "backlog", "planning", "spec_review", "spec_revision",
    "implementation_queued", "implementing", "pr_review", "done",
    "failed", "cancelled",
)


@dataclasses.dataclass
class SpecTask:
    id: str
    project: str
    title: str
    description: str = ""
    status: str = "backlog"
    spec_branch: str = ""
    task_branch: str = ""
    spec_path: str = ""
    pr_id: str = ""
    error: str = ""
    ci_attempts: int = 0

    def to_dict(self):
        return dataclasses.asdict(self)


class TaskStore:
    def __init__(self, db_path=":memory:"):
        from helix_tpu.control.db import Database

        self._db = Database.resolve(db_path)
        self._conn = self._db.conn
        self._lock = self._db.lock
        # lifecycle observer: on_update(task) after every status persist
        # (the control plane publishes these to the durable TASKS stream)
        self.on_update = None
        self._db.migrate("spec_tasks", [(1, "initial", _SCHEMA)])
        with self._lock:
            # pre-migration-framework DBs: bring columns forward (these
            # predate the schema_migrations table, so they stay try/except)
            for mig in _MIGRATIONS:
                try:
                    self._conn.execute(mig)
                except sqlite3.OperationalError:
                    pass  # column already exists
            self._db.commit()

    # -- tasks ---------------------------------------------------------------
    def create_task(self, project: str, title: str, description: str = "") -> SpecTask:
        t = SpecTask(
            id=f"tsk_{uuid.uuid4().hex[:12]}", project=project,
            title=title, description=description,
        )
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO spec_tasks(id, project, title, description, "
                "status, created_at, updated_at) VALUES(?,?,?,?,?,?,?)",
                (t.id, project, title, description, t.status, now, now),
            )
            self._db.commit()
        return t

    def _row_to_task(self, r) -> SpecTask:
        return SpecTask(
            id=r[0], project=r[1], title=r[2], description=r[3], status=r[4],
            spec_branch=r[5], task_branch=r[6], spec_path=r[7], pr_id=r[8],
            error=r[9], ci_attempts=r[10] or 0,
        )

    _COLS = (
        "id, project, title, description, status, spec_branch, task_branch, "
        "spec_path, pr_id, error, ci_attempts"
    )

    def get_task(self, tid: str) -> Optional[SpecTask]:
        with self._lock:
            r = self._conn.execute(
                f"SELECT {self._COLS} FROM spec_tasks WHERE id=?", (tid,)
            ).fetchone()
        return self._row_to_task(r) if r else None

    def list_tasks(self, project: Optional[str] = None,
                   status: Optional[str] = None) -> list:
        q = f"SELECT {self._COLS} FROM spec_tasks"
        conds, args = [], []
        if project:
            conds.append("project=?")
            args.append(project)
        if status:
            conds.append("status=?")
            args.append(status)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += " ORDER BY created_at"
        with self._lock:
            rows = self._conn.execute(q, tuple(args)).fetchall()
        return [self._row_to_task(r) for r in rows]

    def update_task(self, t: SpecTask) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE spec_tasks SET status=?, spec_branch=?, "
                "task_branch=?, spec_path=?, pr_id=?, error=?, "
                "ci_attempts=?, updated_at=? WHERE id=?",
                (
                    t.status, t.spec_branch, t.task_branch, t.spec_path,
                    t.pr_id, t.error, t.ci_attempts, time.time(), t.id,
                ),
            )
            self._db.commit()
        if self.on_update is not None:
            try:
                self.on_update(t)
            except Exception:  # noqa: BLE001 — observers must not break
                pass           # the kanban loop

    # -- design reviews -------------------------------------------------------
    def add_review(self, task_id: str, author: str, comment: str,
                   decision: str) -> str:
        rid = f"rev_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO design_reviews(id, task_id, author, comment, "
                "decision, created_at) VALUES(?,?,?,?,?,?)",
                (rid, task_id, author, comment, decision, time.time()),
            )
            self._db.commit()
        return rid

    def reviews(self, task_id: str) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, author, comment, decision, created_at FROM "
                "design_reviews WHERE task_id=? ORDER BY created_at",
                (task_id,),
            ).fetchall()
        return [
            {"id": r[0], "author": r[1], "comment": r[2], "decision": r[3],
             "created_at": r[4]}
            for r in rows
        ]

    # -- pull requests --------------------------------------------------------
    def create_pr(self, project: str, task_id: str, title: str,
                  base: str, head: str) -> str:
        pid = f"pr_{uuid.uuid4().hex[:12]}"
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO pull_requests(id, project, task_id, title, "
                "base, head, status, created_at, updated_at) "
                "VALUES(?,?,?,?,?,?, 'open', ?, ?)",
                (pid, project, task_id, title, base, head, now, now),
            )
            self._db.commit()
        return pid

    _PR_COLS = (
        "id, project, task_id, title, base, head, status, merge_sha, "
        "ci_status, ci_log"
    )

    @staticmethod
    def _row_to_pr(r) -> dict:
        return {
            "id": r[0], "project": r[1], "task_id": r[2], "title": r[3],
            "base": r[4], "head": r[5], "status": r[6], "merge_sha": r[7],
            "ci_status": r[8], "ci_log": r[9],
        }

    def get_pr(self, pid: str) -> Optional[dict]:
        with self._lock:
            r = self._conn.execute(
                f"SELECT {self._PR_COLS} FROM pull_requests WHERE id=?",
                (pid,),
            ).fetchone()
        return self._row_to_pr(r) if r else None

    def list_prs(self, project: Optional[str] = None,
                 status: Optional[str] = None) -> list:
        q = f"SELECT {self._PR_COLS} FROM pull_requests"
        conds, args = [], []
        if project:
            conds.append("project=?")
            args.append(project)
        if status:
            conds.append("status=?")
            args.append(status)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        with self._lock:
            rows = self._conn.execute(q, tuple(args)).fetchall()
        return [self._row_to_pr(r) for r in rows]

    def update_pr(self, pid: str, status: str, merge_sha: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE pull_requests SET status=?, merge_sha=?, updated_at=? "
                "WHERE id=?",
                (status, merge_sha, time.time(), pid),
            )
            self._db.commit()

    def set_pr_ci(self, pid: str, ci_status: str, ci_log: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE pull_requests SET ci_status=?, ci_log=?, "
                "updated_at=? WHERE id=?",
                (ci_status, ci_log[:20000], time.time(), pid),
            )
            self._db.commit()


class CIRunner:
    """CI seam (reference: ``spec_task_orchestrator_ci.go`` +
    ``spec_task_orchestrator.go:1074-1201`` PR/CI polling).

    ``run(project, workspace)`` checks out is already done by the caller;
    returns (passed, log) where passed is True/False, or None when the
    project defines no CI."""

    def run(self, project: str, workspace: str):  # pragma: no cover
        raise NotImplementedError


class LocalCIRunner(CIRunner):
    """Runs the project's ``.helix-ci.sh`` (if present) in an isolated
    subprocess — the internal-CI analogue of the reference's external CI
    status polling."""

    def __init__(self, timeout: float = 600.0):
        self.timeout = timeout

    def run(self, project: str, workspace: str):
        import signal
        import subprocess

        script = os.path.join(workspace, ".helix-ci.sh")
        if not os.path.exists(script):
            return None, ""
        p = subprocess.Popen(
            ["sh", ".helix-ci.sh"], cwd=workspace,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            start_new_session=True,
        )
        try:
            log, _ = p.communicate(timeout=self.timeout)
        except subprocess.TimeoutExpired:
            # kill the whole session, not just the sh leader — a hung
            # pytest child must not outlive its workspace
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.wait()
            return False, f"CI timed out after {self.timeout}s"
        return p.returncode == 0, log or ""


class ExternalGitSync:
    """Seam for mirroring internal PRs to an external host (GitHub/GitLab/
    ADO — reference: ``git_repository_service*.go`` pull/push sync + PR
    list cache).  The default no-op keeps everything internal; a concrete
    sync pushes the branch, opens the external PR, and reports its state
    back through ``poll``."""

    def push_pr(self, project: str, pr: dict) -> None:  # pragma: no cover
        pass

    def poll(self, project: str, pr: dict) -> Optional[dict]:
        """Return {'status': 'open|merged|closed', 'ci_status': ...} from
        the external host, or None when the PR is internal-only."""
        return None


PLAN_PROMPT = (
    "You are a software planning agent. Write a concise implementation "
    "spec for the task into the file specs/{task_id}.md using the "
    "filesystem tool, then answer with a one-line summary."
)
IMPL_PROMPT = (
    "You are a software implementation agent. Read the spec at "
    "{spec_path} and implement it by writing files in the workspace "
    "with the filesystem tool, then answer with a one-line summary."
)


def build_agent_prompt(task: "SpecTask", mode: str) -> str:
    return (PLAN_PROMPT if mode == "plan" else IMPL_PROMPT).format(
        task_id=task.id, spec_path=task.spec_path or "specs/"
    )


def build_agent_message(task: "SpecTask", feedback: str = "") -> str:
    message = f"Task: {task.title}\n\n{task.description}"
    if feedback:
        message += f"\n\nReview feedback to address:\n{feedback}"
    return message


class Executor:
    """Agent-execution seam (reference: ``external-agent/executor.go:13-37``).

    ``run(task, workspace, mode)`` runs an agent in ``workspace`` (a git
    clone) and returns a summary string; mode is "plan" or "implement"."""

    def run(self, task: SpecTask, workspace: str, mode: str,
            feedback: str = "") -> str:  # pragma: no cover - interface
        raise NotImplementedError


class AgentExecutor(Executor):
    """Default executor: the in-process agent loop with filesystem access to
    the workspace (the TPU build's stand-in for a desktop container agent)."""

    def __init__(self, llm, model: str = "", max_iterations: int = 12,
                 make_emitter=None):
        """``make_emitter(task, mode)`` may return (emit_fn, close_fn) to
        observe agent steps live — the control plane uses it to stream the
        agent's activity into a watchable desktop session."""
        self.llm = llm
        self.model = model
        self.max_iterations = max_iterations
        self.make_emitter = make_emitter

    def run(self, task, workspace, mode, feedback: str = "") -> str:
        import asyncio

        from helix_tpu.agent.agent import Agent, AgentConfig
        from helix_tpu.agent.skill import SkillRegistry
        from helix_tpu.agent.skills import filesystem_skill

        prompt = build_agent_prompt(task, mode)
        emit, close = (lambda s: None), (lambda: None)
        if self.make_emitter is not None:
            emit, close = self.make_emitter(task, mode)
        agent = Agent(
            AgentConfig(
                prompt=prompt, model=self.model,
                max_iterations=self.max_iterations,
            ),
            SkillRegistry([filesystem_skill(workspace)]),
            self.llm,
            emitter=emit,
        )
        message = build_agent_message(task, feedback)
        try:
            answer, steps = asyncio.run(agent.run(message))
        finally:
            close()
        return answer


class SpecTaskOrchestrator:
    """The polling state machine (``spec_task_orchestrator.go:140,299-330``)."""

    def __init__(
        self,
        store: TaskStore,
        git: GitService,
        executor: Executor,
        poll_interval: float = 2.0,
        workspace_root: Optional[str] = None,
        ci: Optional[CIRunner] = None,
        external_git: Optional[ExternalGitSync] = None,
        max_ci_attempts: int = 2,
        notify: Optional[Callable] = None,
        workspaces=None,   # WorkspaceManager: golden caches + GC
    ):
        self.store = store
        self.git = git
        self.executor = executor
        self.ci = ci if ci is not None else LocalCIRunner()
        self.external_git = external_git or ExternalGitSync()
        self.max_ci_attempts = max_ci_attempts
        # notify(kind, title, body, **meta) — email/Slack/Discord fan-out
        self.notify = notify or (lambda *a, **k: None)
        self.workspaces = workspaces
        self.poll_interval = poll_interval
        self.workspace_root = workspace_root or tempfile.mkdtemp(
            prefix="helix-workspaces-"
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-project serialisation (reference: backlogProjectLocks)
        self._project_locks: dict[str, threading.Lock] = {}
        self._plock = threading.Lock()

    def _lock_for(self, project: str) -> threading.Lock:
        with self._plock:
            return self._project_locks.setdefault(project, threading.Lock())

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="helix-spectask", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.process_once()
            except Exception:  # noqa: BLE001 — orchestrator must survive
                traceback.print_exc()
            self._stop.wait(self.poll_interval)

    # -- the state machine -----------------------------------------------------
    def process_once(self) -> int:
        """One pass over actionable statuses; returns tasks progressed."""
        n = 0
        for task in self.store.list_tasks(status="backlog"):
            with self._lock_for(task.project):
                self._handle_backlog(task)
            n += 1
        for task in self.store.list_tasks(status="planning"):
            self._handle_planning(task)
            n += 1
        for task in self.store.list_tasks(status="spec_revision"):
            self._handle_planning(task, revision=True)
            n += 1
        for task in self.store.list_tasks(status="implementation_queued"):
            self._handle_implementation(task)
            n += 1
        for task in self.store.list_tasks(status="pr_review"):
            if self._handle_pr_review(task):
                n += 1
        return n

    def _fail(self, task: SpecTask, err: str):
        task.status = "failed"
        task.error = err[:2000]
        self.store.update_task(task)
        self.notify(
            "task_failed", f"Task failed: {task.title}",
            task.error[:500], task_id=task.id, project=task.project,
        )

    def _handle_backlog(self, task: SpecTask):
        if not self.git.repo_exists(task.project):
            self.git.create_repo(task.project)
        task.status = "planning"
        task.spec_branch = "helix-specs"
        task.spec_path = f"specs/{task.id}.md"
        self.store.update_task(task)

    def _workspace(self, task: SpecTask, suffix: str,
                   branch: Optional[str] = None) -> str:
        """Fresh agent workspace: a hardlink clone of the project's
        golden snapshot when one exists (warm deps + .git — reference:
        hydra golden caches seeding dev-container workspaces), else a
        plain git clone.  Either way the tree ends on ``branch``."""
        if self.workspaces is not None:
            try:
                if self.workspaces.golden_info(task.project) is not None:
                    ws = self.workspaces.clone_workspace(
                        task.project, f"{task.id}-{suffix}"
                    )
                    self.git.refresh_workspace(ws, branch)
                    return ws
            except Exception:  # noqa: BLE001 — a bad snapshot falls
                log.debug("golden seed failed", exc_info=True)  # back
        ws = os.path.join(self.workspace_root, f"{task.id}-{suffix}")
        shutil.rmtree(ws, ignore_errors=True)
        self.git.clone_workspace(task.project, ws, branch=branch)
        return ws

    def _release_workspace(self, task: SpecTask, suffix: str,
                           ws: str) -> None:
        shutil.rmtree(ws, ignore_errors=True)
        if self.workspaces is not None:
            try:
                self.workspaces.release_workspace(f"{task.id}-{suffix}")
            except Exception:  # noqa: BLE001
                pass

    def _handle_planning(self, task: SpecTask, revision: bool = False):
        ws = None
        try:
            ws = self._workspace(task, "plan")
            feedback = ""
            if revision:
                feedback = "\n".join(
                    r["comment"]
                    for r in self.store.reviews(task.id)
                    if r["decision"] == "request_changes"
                )
            self.executor.run(task, ws, "plan", feedback=feedback)
            spec_file = os.path.join(ws, task.spec_path)
            if not os.path.exists(spec_file):
                raise RuntimeError(
                    f"planning agent produced no spec at {task.spec_path}"
                )
            self.git.commit_and_push(
                ws, f"spec: {task.title} ({task.id})", task.spec_branch
            )
            task.status = "spec_review"
            self.store.update_task(task)
        except Exception as e:  # noqa: BLE001
            self._fail(task, f"planning failed: {e}")
        finally:
            if ws is not None:
                self._release_workspace(task, "plan", ws)

    def review_spec(self, task_id: str, author: str, decision: str,
                    comment: str = "") -> SpecTask:
        """Human design-review gate (reference: design-review comments +
        approve -> implementation queue)."""
        task = self.store.get_task(task_id)
        if task is None:
            raise KeyError(task_id)
        if task.status != "spec_review":
            raise ValueError(f"task is {task.status}, not spec_review")
        self.store.add_review(task_id, author, comment, decision)
        if decision == "approve":
            task.status = "implementation_queued"
            task.task_branch = f"task/{task.id}"
        elif decision == "request_changes":
            task.status = "spec_revision"
        self.store.update_task(task)
        return task

    def _handle_implementation(self, task: SpecTask):
        task.status = "implementing"
        self.store.update_task(task)
        ws = None
        try:
            # CI-fix retries continue on the task branch (incremental),
            # first attempts start from the default branch
            retry_branch = (
                task.task_branch
                if task.ci_attempts > 0
                and self.git.branch_exists(task.project, task.task_branch)
                else None
            )
            ws = self._workspace(task, "impl", branch=retry_branch)
            # bring the spec into the working tree
            spec = self.git.file_at(
                task.project, task.spec_branch, task.spec_path
            )
            if spec:
                os.makedirs(
                    os.path.dirname(os.path.join(ws, task.spec_path)),
                    exist_ok=True,
                )
                with open(os.path.join(ws, task.spec_path), "w") as f:
                    f.write(spec)
            # red-CI feedback from earlier attempts rides into the agent
            # (the reference's CINotifier ci_passed/failed messages)
            feedback = "\n".join(
                r["comment"]
                for r in self.store.reviews(task.id)
                if r["decision"] == "ci_failed"
            )
            self.executor.run(task, ws, "implement", feedback=feedback)
            sha = self.git.commit_and_push(
                ws, f"{task.title} ({task.id})", task.task_branch
            )
            if sha is None and not feedback:
                raise RuntimeError("implementation agent changed nothing")
            if self.workspaces is not None:
                # promote the post-implementation tree (checkout + any
                # deps the agent installed) as the project's golden
                # cache: the next agent's workspace hardlink-clones it
                # (reference: hydra/golden.go promote-session-to-golden)
                try:
                    self.workspaces.promote_golden(task.project, ws)
                except Exception:  # noqa: BLE001 — cache only
                    log.debug("golden promote failed", exc_info=True)
            task.pr_id = self.store.create_pr(
                task.project, task.id, task.title, "main", task.task_branch
            )
            task.status = "pr_review"
            self.store.update_task(task)
            self.external_git.push_pr(
                task.project, self.store.get_pr(task.pr_id)
            )
        except Exception as e:  # noqa: BLE001
            self._fail(task, f"implementation failed: {e}")
        finally:
            if ws is not None:
                self._release_workspace(task, "impl", ws)

    def _handle_pr_review(self, task: SpecTask) -> bool:
        """PR/CI completion loop (``spec_task_orchestrator.go:1074-1201``):
        run CI on pending PRs; feed failures back into a bounded
        re-implementation loop; reflect external PR state when a sync is
        configured.  Returns True when something progressed."""
        pr = self.store.get_pr(task.pr_id) if task.pr_id else None
        if pr is None or pr["status"] != "open":
            return False
        # external PR state (no-op for internal-only PRs)
        ext = self.external_git.poll(task.project, pr)
        if ext:
            if ext.get("status") == "merged":
                self.store.update_pr(pr["id"], "merged",
                                     ext.get("merge_sha", ""))
                task.status = "done"
                self.store.update_task(task)
                return True
            if ext.get("status") == "closed":
                # externally rejected (closed without merging): honour it —
                # the task must not merge internally after a maintainer
                # said no on the forge
                self.store.update_pr(pr["id"], "closed")
                task.status = "cancelled"
                task.error = "external PR closed without merging"
                self.store.update_task(task)
                self.notify(
                    "task_cancelled",
                    f"External PR rejected: {task.title}",
                    f"PR {pr['id']} was closed on the external forge",
                    task_id=task.id, project=task.project,
                )
                return True
            if ext.get("ci_status") == "passed":
                if pr["ci_status"] != "passed":
                    self.store.set_pr_ci(pr["id"], "passed",
                                         ext.get("ci_log", ""))
                    return True
                return False
            if ext.get("ci_status") == "failed":
                if pr["ci_status"] != "failed":
                    self.store.set_pr_ci(pr["id"], "failed",
                                         ext.get("ci_log", ""))
                    self._ci_failed(task, pr, ext.get("ci_log", ""))
                    return True
                return False
        # 'running' is retryable: the run is synchronous, so a persisted
        # 'running' means a crash mid-CI — re-run rather than wedge
        if pr["ci_status"] not in ("pending", "running"):
            return False
        self.store.set_pr_ci(pr["id"], "running")
        ws = None
        try:
            # CI gets the golden warmth too (deps already installed)
            ws = self._workspace(task, "ci", branch=pr["head"])
            passed, ci_log = self.ci.run(task.project, ws)
        except Exception as e:  # noqa: BLE001 — CI infra failure != red CI
            self.store.set_pr_ci(task.pr_id, "pending")
            task.error = f"ci infra error: {e}"[:2000]
            self.store.update_task(task)
            return False
        finally:
            if ws is not None:
                self._release_workspace(task, "ci", ws)
        if passed is None:
            self.store.set_pr_ci(pr["id"], "none")
            return True
        if passed:
            self.store.set_pr_ci(pr["id"], "passed", ci_log)
            return True
        self.store.set_pr_ci(pr["id"], "failed", ci_log)
        self._ci_failed(task, pr, ci_log)
        return True

    def _ci_failed(self, task: SpecTask, pr: dict, log: str) -> None:
        """CINotifier-equivalent: feed the red CI back into the agent loop,
        bounded by max_ci_attempts (``spec_task_orchestrator.go:34-40``)."""
        self.notify(
            "ci_failed", f"CI failed: {task.title}",
            log[-500:], task_id=task.id, pr_id=pr["id"],
        )
        if task.ci_attempts < self.max_ci_attempts:
            task.ci_attempts += 1
            self.store.add_review(
                task.id, "ci", f"CI failed:\n{log[-4000:]}", "ci_failed"
            )
            self.store.update_pr(pr["id"], "closed")
            task.pr_id = ""
            task.status = "implementation_queued"
            self.store.update_task(task)
        else:
            self._fail(
                task,
                f"CI failed after {task.ci_attempts} fix attempts:\n"
                f"{log[-2000:]}",
            )

    def merge_pr(self, pr_id: str) -> dict:
        """Approve + merge the task PR; task -> done (``handleDone``)."""
        pr = self.store.get_pr(pr_id)
        if pr is None:
            raise KeyError(pr_id)
        if pr["status"] != "open":
            raise ValueError(f"PR is {pr['status']}")
        sha = self.git.merge(
            pr["project"], pr["base"], pr["head"],
            f"Merge {pr['head']}: {pr['title']}",
        )
        self.store.update_pr(pr_id, "merged", sha)
        if pr["task_id"]:
            task = self.store.get_task(pr["task_id"])
            if task:
                task.status = "done"
                self.store.update_task(task)
                self.notify(
                    "task_done", f"Task done: {task.title}",
                    f"PR {pr['id']} merged ({sha[:10]})",
                    task_id=task.id, project=task.project,
                )
        return {**pr, "status": "merged", "merge_sha": sha}

    def pr_diff(self, pr_id: str) -> str:
        pr = self.store.get_pr(pr_id)
        if pr is None:
            raise KeyError(pr_id)
        return self.git.diff(pr["project"], pr["base"], pr["head"])
