"""Container-grade agent isolation on Linux namespaces.

The reference runs coding agents inside hydra dev containers — inner
dockerd, shared BuildKit, golden snapshots
(``api/pkg/hydra/manager.go:16-52``, ``external-agent/
hydra_executor.go:130-569``).  This environment ships no container
engine, so the equivalent isolation is built directly on the primitives
engines themselves use: **user + mount + PID namespaces** (``unshare``)
with a private tmpfs root assembled from bind mounts.

What the agent sees inside:

- a root filesystem holding ONLY the system toolchains (``/usr``,
  ``/opt``, merged-usr symlinks) — the host's ``/root``, ``/home``,
  control-plane DBs and checkpoints do not exist in its mount namespace
  (the rlimit sandbox of round 3 shared the host view; this closes that);
- the task workspace bind-mounted RW at ``/workspace`` (its HOME and
  cwd) — the one writable host surface;
- a fresh PID namespace (the agent is pid 1's child; nothing else is
  visible or signalable), private ``/tmp`` and ``/dev`` subset;
- rlimits applied inside (cpu-seconds + address space), so runaway
  agents die without operator action.

Writes to system binds fail at the host-kernel level: the namespace's
uid 0 maps to the unprivileged host uid, which has no write permission
on ``/usr``.  Golden snapshots compose with this orthogonally: the
``WorkspaceManager`` promote/clone machinery snapshots ``/workspace``
content (built envs, caches), and task N+1's container mounts the clone
— the hydra golden flow with copy-on-write scoped to the workspace.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import threading
from typing import Optional, Sequence

from helix_tpu.services.external_agent import ExternalAgentExecutor

# Stage-1 script run by sh inside the new namespaces (argv: R WS then the
# agent command).  Assembles the private root and enters it.  Propagation
# is private in the new mount namespace, so none of these mounts are
# visible to the host.
_SETUP = r"""
set -e
R="$1"; WS="$2"; shift 2
mount -t tmpfs tmpfs "$R"
mkdir -p "$R/usr" "$R/proc" "$R/tmp" "$R/dev" "$R/etc" "$R/workspace"
ro_bind() {
    # bind + explicit read-only remount: permission bits alone do not
    # protect the system binds when the control plane itself runs as
    # root (the mapped uid then owns them)
    mount --rbind "$1" "$2"
    mount -o remount,bind,ro "$2" 2>/dev/null || true
}
ro_bind /usr "$R/usr"
if [ -d /opt ]; then mkdir -p "$R/opt"; ro_bind /opt "$R/opt"; fi
for d in bin sbin lib lib32 lib64 libx32; do
    if [ -e "/$d" ]; then ln -s "usr/$d" "$R/$d" 2>/dev/null || true; fi
done
mount -t proc proc "$R/proc"
mount -t tmpfs tmpfs "$R/tmp"
for f in null zero urandom random; do
    touch "$R/dev/$f"; mount --bind "/dev/$f" "$R/dev/$f"
done
echo 'root:x:0:0:root:/workspace:/bin/sh' > "$R/etc/passwd"
echo 'root:x:0:' > "$R/etc/group"
if [ -d /etc/ssl ]; then
    mkdir -p "$R/etc/ssl"; ro_bind /etc/ssl "$R/etc/ssl"
fi
if [ -d /etc/alternatives ]; then
    mkdir -p "$R/etc/alternatives"
    ro_bind /etc/alternatives "$R/etc/alternatives"
fi
OLDIFS="$IFS"; IFS=:
for p in $HELIX_CONTAINER_BINDS; do
    [ -n "$p" ] || continue
    mkdir -p "$R$p"; ro_bind "$p" "$R$p"
done
IFS="$OLDIFS"
mount --rbind "$WS" "$R/workspace"
if [ -n "$HELIX_CONTAINER_CPU_S" ]; then
    ulimit -t "$HELIX_CONTAINER_CPU_S" 2>/dev/null || true
fi
if [ -n "$HELIX_CONTAINER_MEM_KB" ]; then
    ulimit -v "$HELIX_CONTAINER_MEM_KB" 2>/dev/null || true
fi
exec chroot "$R" /bin/sh -c 'cd /workspace && exec "$@"' helix-container "$@"
"""

_probe_lock = threading.Lock()
_probe_result: Optional[bool] = None


def runtime_available() -> bool:
    """Can this host create user+mount+pid namespaces?  (Kernels with
    ``kernel.unprivileged_userns_clone=0`` or seccomp-blocked unshare —
    e.g. inside an unprivileged container — cannot; callers fall back to
    the rlimit process sandbox and say so.)  Cached after first probe."""
    global _probe_result
    with _probe_lock:
        if _probe_result is None:
            try:
                p = subprocess.run(
                    ["unshare", "--user", "--map-root-user", "--mount",
                     "--pid", "--fork", "/bin/sh", "-c",
                     "mount -t tmpfs tmpfs /tmp && echo ok"],
                    capture_output=True, timeout=20,
                )
                _probe_result = p.returncode == 0 and b"ok" in p.stdout
            except (OSError, subprocess.TimeoutExpired):
                _probe_result = False
        return _probe_result


def container_command(
    argv: Sequence[str],
    workspace: str,
    staging_dir: str,
    ro_binds: Sequence[str] = (),
    cpu_limit_s: Optional[int] = None,
    memory_limit_bytes: Optional[int] = None,
) -> tuple[list, dict]:
    """-> (full argv, env additions) running ``argv`` containerised with
    ``workspace`` mounted RW at /workspace.  ``ro_binds`` appear at their
    host paths (for agent installs outside /usr//opt); writes to them
    fail at the host-permission level like the system binds."""
    env = {
        "HELIX_CONTAINER_BINDS": ":".join(ro_binds),
        "HELIX_CONTAINER_CPU_S":
            "" if cpu_limit_s is None else str(int(cpu_limit_s)),
        "HELIX_CONTAINER_MEM_KB":
            "" if memory_limit_bytes is None
            else str(int(memory_limit_bytes) // 1024),
    }
    cmd = [
        "unshare", "--user", "--map-root-user", "--mount", "--pid",
        "--fork", "/bin/sh", "-c", _SETUP, "helix-container-setup",
        staging_dir, workspace, *argv,
    ]
    return cmd, env


class ContainerAgentExecutor(ExternalAgentExecutor):
    """ACP agent executor whose turns run inside a namespace container.

    Drop-in for ``ExternalAgentExecutor`` on the orchestrator's Executor
    seam: same ACP conversation, same emitter stream, but the agent's
    filesystem view is the private root above with the task workspace at
    ``/workspace`` (reference: hydra's dev-container execution,
    ``api/pkg/external-agent/hydra_executor.go:130-569``)."""

    def __init__(self, argv: list, ro_binds: Sequence[str] = (), **kw):
        super().__init__(argv, **kw)
        self.ro_binds = tuple(ro_binds)
        if not runtime_available():
            raise RuntimeError(
                "namespace container runtime unavailable on this host "
                "(unprivileged user namespaces disabled) — use "
                "ExternalAgentExecutor (rlimit sandbox) instead"
            )

    def _agent_cwd(self, workspace: str) -> str:
        return "/workspace"   # how the mount appears inside

    def _env(self, workspace: str) -> dict:
        env = super()._env(workspace)
        env["HOME"] = "/workspace"
        return env

    def _spawn(self, workspace: str) -> subprocess.Popen:
        staging = tempfile.mkdtemp(prefix="helix-ctr-")
        cmd, extra = container_command(
            self.argv, workspace, staging,
            ro_binds=self.ro_binds,
            cpu_limit_s=self.cpu_limit_s,
            memory_limit_bytes=self.memory_limit_bytes,
        )
        env = self._env(workspace)
        env.update(extra)
        proc = subprocess.Popen(
            cmd,
            cwd=workspace,
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
        )
        # the staging dir only anchors the in-namespace tmpfs; reap it
        # once the container exits (nothing is ever written to it on the
        # host side)
        def reap():
            proc.wait()
            shutil.rmtree(staging, ignore_errors=True)

        threading.Thread(target=reap, daemon=True).start()
        return proc


def run_in_container(
    argv: Sequence[str],
    workspace: str,
    ro_binds: Sequence[str] = (),
    timeout: float = 120.0,
    env: Optional[dict] = None,
) -> subprocess.CompletedProcess:
    """One-shot containerised command (build steps, CI inside the
    sandbox).  Returns the CompletedProcess; raises on runtime absence."""
    if not runtime_available():
        raise RuntimeError("namespace container runtime unavailable")
    staging = tempfile.mkdtemp(prefix="helix-ctr-")
    cmd, extra = container_command(argv, workspace, staging,
                                   ro_binds=ro_binds)
    full_env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": "/workspace",
        "LANG": os.environ.get("LANG", "C.UTF-8"),
        **(env or {}),
        **extra,
    }
    try:
        return subprocess.run(
            cmd, cwd=workspace, env=full_env, capture_output=True,
            text=True, timeout=timeout,
        )
    finally:
        shutil.rmtree(staging, ignore_errors=True)
