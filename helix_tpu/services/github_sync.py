"""External forge sync: mirror internal PRs to GitHub and poll PR/CI state.

Fills the ``ExternalGitSync`` seam (``spec_tasks.py``) the way the
reference's git-repository service syncs internal repos with
GitHub/GitLab/ADO/Gitea and polls external PRs + CI back into the
orchestrator (``api/pkg/services/git_repository_service*.go``,
``spec_task_orchestrator.go:1074-1201``):

- ``push_pr`` pushes the task branch (and base) from the control plane's
  bare repo to the external clone URL, then opens a pull request through
  the REST API;
- ``poll`` reads the PR (merged/closed/open) and the head commit's
  combined status, translating to the orchestrator's
  ``ci_passed``/``ci_failed`` transitions — a red external CI re-queues
  the task with feedback, an external merge completes it.

Configuration is per-project: ``{"clone_url": ..., "repo": "owner/name"}``.
The API base is configurable so self-hosted GitHub Enterprise (and the
test suite's fake forge) work unchanged.  Sync is best-effort by design:
a forge outage must never fail a task, so push errors are recorded on
``last_error`` and polling returns None (internal flow continues).
"""

from __future__ import annotations

import json
import logging
import subprocess
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from helix_tpu.services.git_service import GitService
from helix_tpu.services.spec_tasks import ExternalGitSync

log = logging.getLogger(__name__)


class GitHubSync(ExternalGitSync):
    def __init__(
        self,
        git: GitService,
        api_base: str = "https://api.github.com",
        token: str = "",
        repos: Optional[dict] = None,
        timeout: float = 15.0,
        min_poll_interval: float = 30.0,
    ):
        self.git = git
        self.api_base = api_base.rstrip("/")
        self.token = token
        self.repos = dict(repos or {})   # project -> {clone_url, repo}
        self.timeout = timeout
        # the orchestrator ticks every ~2s; polling GitHub that often
        # burns ~3600 req/h per open PR against a 5000 req/h limit. Cache
        # each PR's last answer for min_poll_interval instead.
        self.min_poll_interval = min_poll_interval
        self.last_error: str = ""
        self._pr_numbers: dict = {}      # internal pr id -> external number
        self._poll_cache: dict = {}      # internal pr id -> (ts, result)
        self._lock = threading.Lock()

    # -- REST ---------------------------------------------------------------
    def _api(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            f"{self.api_base}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Accept": "application/vnd.github+json",
                "Content-Type": "application/json",
                **(
                    {"Authorization": f"Bearer {self.token}"}
                    if self.token
                    else {}
                ),
            },
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    # -- sync surface --------------------------------------------------------
    def push_branch(
        self, project: str, branch: str, force: bool = False
    ) -> None:
        cfg = self.repos.get(project)
        if not cfg:
            return
        bare = self.git._repo_path(project)
        # The token travels via the environment + an inline credential
        # helper — never on the command line (visible in /proc) and never
        # in the URL git echoes into error output.
        import os as _os

        env = dict(_os.environ)
        args = ["git", "-C", bare]
        if self.token and cfg["clone_url"].startswith("http"):
            env["HELIX_GIT_TOKEN"] = self.token
            helper = (
                '!f() { echo username=x-access-token; '
                'echo "password=$HELIX_GIT_TOKEN"; }; f'
            )
            args += ["-c", f"credential.helper={helper}"]
        args += ["push", *(["-f"] if force else []), cfg["clone_url"],
                 f"refs/heads/{branch}:refs/heads/{branch}"]
        p = subprocess.run(
            args, capture_output=True, text=True, timeout=120, env=env,
        )
        if p.returncode != 0:
            err = (p.stderr or "").replace(self.token or "\x00", "***")
            raise RuntimeError(
                f"push {project}:{branch} failed: {err[:300]}"
            )

    def push_pr(self, project: str, pr: dict) -> None:
        cfg = self.repos.get(project)
        if not cfg:
            return
        try:
            # base: NEVER forced — the external base may hold merges the
            # internal repo doesn't (external merges are not synced back);
            # a non-fast-forward here just means the forge is ahead, which
            # is fine for opening the PR against it
            try:
                self.push_branch(project, pr["base"])
            except RuntimeError as e:
                log.info("base push skipped (forge ahead): %s", e)
            # head: ours alone, forced so CI-fix rounds can rewrite it
            self.push_branch(project, pr["head"], force=True)
            doc = self._api(
                "POST", f"/repos/{cfg['repo']}/pulls",
                {
                    "title": pr.get("title") or pr["head"],
                    "head": pr["head"],
                    "base": pr["base"],
                    "body": f"helix task PR {pr['id']}",
                },
            )
            with self._lock:
                self._pr_numbers[pr["id"]] = doc["number"]
            self.last_error = ""
        except Exception as e:  # noqa: BLE001 — forge outage != task failure
            self.last_error = f"push_pr {pr['id']}: {e}"
            log.warning("external PR sync failed: %s", self.last_error)

    def _find_number(self, cfg: dict, pr: dict) -> Optional[int]:
        """Recover the external PR number by head branch (survives control
        plane restarts — the map is in-memory only)."""
        owner = cfg["repo"].split("/")[0]
        q = urllib.parse.urlencode(
            {"head": f"{owner}:{pr['head']}", "state": "all"}
        )
        docs = self._api("GET", f"/repos/{cfg['repo']}/pulls?{q}")
        if isinstance(docs, list) and docs:
            return docs[0]["number"]
        return None

    def poll(self, project: str, pr: dict) -> Optional[dict]:
        cfg = self.repos.get(project)
        if not cfg:
            return None
        import time as _time

        with self._lock:
            cached = self._poll_cache.get(pr["id"])
            if cached and _time.monotonic() - cached[0] < self.min_poll_interval:
                return cached[1]
        result = self._poll_uncached(cfg, pr)
        with self._lock:
            if result and result.get("status") in ("merged", "closed"):
                # terminal: the orchestrator stops polling this PR —
                # keeping the entries would leak per PR forever.  A
                # post-terminal poll recovers the number via _find_number.
                self._poll_cache.pop(pr["id"], None)
                self._pr_numbers.pop(pr["id"], None)
            else:
                self._poll_cache[pr["id"]] = (_time.monotonic(), result)
        return result

    def _poll_uncached(self, cfg: dict, pr: dict) -> Optional[dict]:
        try:
            with self._lock:
                number = self._pr_numbers.get(pr["id"])
            if number is None:
                number = self._find_number(cfg, pr)
                if number is None:
                    return None
                with self._lock:
                    self._pr_numbers[pr["id"]] = number
            doc = self._api("GET", f"/repos/{cfg['repo']}/pulls/{number}")
            if doc.get("merged") or doc.get("merged_at"):
                return {
                    "status": "merged",
                    "merge_sha": doc.get("merge_commit_sha", ""),
                }
            if doc.get("state") == "closed":
                return {"status": "closed"}
            sha = (doc.get("head") or {}).get("sha", "")
            if not sha:
                return {"status": "open", "ci_status": "pending"}
            st = self._api(
                "GET", f"/repos/{cfg['repo']}/commits/{sha}/status"
            )
            ci = {
                "success": "passed",
                "failure": "failed",
                "error": "failed",
            }.get(st.get("state", "pending"), "pending")
            ci_log = "\n".join(
                f"{s.get('context')}: "
                f"{s.get('description') or s.get('state')}"
                for s in st.get("statuses", [])
            )
            return {"status": "open", "ci_status": ci, "ci_log": ci_log}
        except Exception as e:  # noqa: BLE001 — keep the kanban moving
            self.last_error = f"poll {pr['id']}: {e}"
            log.warning("external PR poll failed: %s", self.last_error)
            return None
