"""Workspace manager: golden caches, hardlink clones, GC, disk pressure.

Reference: hydra's per-GPU-host workspace machinery —
**golden cache snapshots** per project cloned via overlayfs/ZFS zvols so
a new agent desktop starts from a warm build environment
(``api/pkg/hydra/golden.go:17-31``, ``golden_zvol.go``), a durable
**orphan reaper** computing a DB live-set and GC-ing everything else
(``api/pkg/hydra/workspace_gc.go``, ``external-agent/gc_reaper.go``),
and a **disk-pressure monitor** (``api/pkg/hydra/disk_pressure.go``).

This build's agents run in process sandboxes over plain directories, so
the same capabilities map to filesystem primitives:

- golden snapshots are directory trees captured from a prepared
  workspace; **clones hardlink file content** (`os.link`) so a clone of
  a multi-GB dependency tree costs directory entries, not bytes — the
  overlay/zvol trick without kernel support.  Agents that WRITE a file
  break the link only if they truncate in place; git and package
  managers replace files, which is hardlink-safe.
- GC walks the workspace root against a caller-supplied live-set.
- disk pressure samples `os.statvfs` and reports watermarks.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import threading
import time
import uuid
from typing import Callable, Optional

log = logging.getLogger("helix.workspaces")


@dataclasses.dataclass
class GoldenInfo:
    project: str
    snapshot_id: str
    created_at: float
    files: int
    bytes: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _tree_stats(root: str) -> tuple:
    files = 0
    size = 0
    for r, _, fs in os.walk(root):
        for f in fs:
            p = os.path.join(r, f)
            try:
                st = os.lstat(p)
            except OSError:
                continue
            files += 1
            size += st.st_size
    return files, size


def clone_tree(src: str, dst: str, hardlink: bool = True) -> None:
    """Clone ``src`` into ``dst``: directories recreated, regular files
    hardlinked (same filesystem; falls back to byte copy), symlinks
    copied.  ``hardlink=False`` forces byte copies — REQUIRED whenever
    either side will see in-place writes from arbitrary user shell
    (interactive sandboxes): aliased inodes would let one side silently
    mutate the other."""
    os.makedirs(dst, exist_ok=True)
    for r, dirs, files in os.walk(src):
        rel = os.path.relpath(r, src)
        target_dir = os.path.join(dst, rel) if rel != "." else dst
        for d in dirs:
            os.makedirs(os.path.join(target_dir, d), exist_ok=True)
        for f in files:
            sp = os.path.join(r, f)
            tp = os.path.join(target_dir, f)
            if os.path.islink(sp):
                os.symlink(os.readlink(sp), tp)
                continue
            if hardlink:
                try:
                    os.link(sp, tp)
                    continue
                except OSError:
                    pass
            shutil.copy2(sp, tp)


class WorkspaceManager:
    """Owns a workspace root: golden snapshots + clones + GC + pressure."""

    def __init__(self, root: str):
        self.root = root
        self.golden_root = os.path.join(root, ".golden")
        self.clones_root = os.path.join(root, "clones")
        os.makedirs(self.golden_root, exist_ok=True)
        os.makedirs(self.clones_root, exist_ok=True)
        self._lock = threading.Lock()

    # -- golden snapshots ---------------------------------------------------
    @staticmethod
    def _safe_name(name: str) -> str:
        """Project/owner names become path segments: one flat component,
        no separators or dot-traversal (the HTTP layer passes route
        segments through verbatim)."""
        if (
            not name
            or name in (".", "..")
            or "/" in name
            or "\\" in name
            or "\x00" in name
        ):
            raise ValueError(f"invalid workspace name {name!r}")
        return name

    def _golden_dir(self, project: str) -> str:
        return os.path.join(self.golden_root, self._safe_name(project))

    def seed_from_golden(self, project: str, dst: str,
                         hardlink: bool = True) -> GoldenInfo:
        """Populate ``dst`` from the project's golden snapshot; raises
        KeyError when none exists.  Interactive consumers (dev sandboxes
        running arbitrary shell) must pass hardlink=False."""
        info = self.golden_info(project)
        if info is None:
            raise KeyError(f"no golden snapshot for {project!r}")
        clone_tree(self._golden_dir(project), dst, hardlink=hardlink)
        return info

    def promote_golden(self, project: str, workspace: str,
                       hardlink: bool = True) -> GoldenInfo:
        """Capture ``workspace`` as the project's golden snapshot
        (reference: promote-session-to-golden, hydra/golden.go:33-49).
        Atomic swap: built next to the old snapshot, renamed over it.
        ``hardlink=False`` when the source keeps running user shell."""
        snap_id = f"gold-{uuid.uuid4().hex[:10]}"
        final = self._golden_dir(project)
        tmp = final + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        clone_tree(workspace, tmp, hardlink=hardlink)
        # never snapshot VCS-internal lock files mid-operation
        files, size = _tree_stats(tmp)
        info = GoldenInfo(
            project=project, snapshot_id=snap_id,
            created_at=time.time(), files=files, bytes=size,
        )
        with open(os.path.join(tmp, ".golden.json"), "w") as f:
            json.dump(info.to_dict(), f)
        with self._lock:
            old = final + ".old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.exists(final):
                os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        return info

    def golden_info(self, project: str) -> Optional[GoldenInfo]:
        path = os.path.join(self._golden_dir(project), ".golden.json")
        try:
            with open(path) as f:
                return GoldenInfo(**json.load(f))
        except (OSError, ValueError, TypeError):
            return None

    def list_golden(self) -> list:
        out = []
        for name in sorted(os.listdir(self.golden_root)):
            info = self.golden_info(name)
            if info is not None:
                out.append(info.to_dict())
        return out

    def drop_golden(self, project: str) -> bool:
        with self._lock:
            path = self._golden_dir(project)
            if not os.path.exists(path):
                return False
            shutil.rmtree(path, ignore_errors=True)
            return True

    # -- clones -------------------------------------------------------------
    def clone_workspace(self, project: str, owner_id: str) -> str:
        """New workspace for ``owner_id`` seeded from the golden snapshot
        when one exists (warm deps/git), else empty.  Hardlink clones
        make warm starts ~free (the 193x BuildKit-cache effect,
        ``design/2026-02-21-smart-load-blog.md``, by filesystem means)."""
        dst = os.path.join(self.clones_root, self._safe_name(owner_id))
        shutil.rmtree(dst, ignore_errors=True)
        golden = self._golden_dir(project)
        with self._lock:
            if os.path.isdir(golden):
                clone_tree(golden, dst)
                # the marker belongs to the snapshot, not the clone
                try:
                    os.remove(os.path.join(dst, ".golden.json"))
                except OSError:
                    pass
            else:
                os.makedirs(dst, exist_ok=True)
        return dst

    def release_workspace(self, owner_id: str) -> None:
        shutil.rmtree(
            os.path.join(self.clones_root, self._safe_name(owner_id)),
            ignore_errors=True,
        )

    # -- GC (orphan reaper) -------------------------------------------------
    def gc(self, live_ids: Callable[[], set], min_age_s: float = 3600.0,
           ) -> list:
        """Remove clone workspaces whose owner is not in the live-set and
        whose mtime is older than ``min_age_s`` (grace for races between
        workspace creation and DB persistence — reference gc_reaper)."""
        live = set(live_ids())
        removed = []
        now = time.time()
        for name in os.listdir(self.clones_root):
            if name in live:
                continue
            path = os.path.join(self.clones_root, name)
            try:
                age = now - os.stat(path).st_mtime
            except OSError:
                continue
            if age < min_age_s:
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed.append(name)
        if removed:
            log.info("workspace gc removed %d orphans", len(removed))
        return removed

    # -- disk pressure ------------------------------------------------------
    def disk_pressure(self, high_pct: float = 85.0,
                      critical_pct: float = 95.0) -> dict:
        st = os.statvfs(self.root)
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        used_pct = 100.0 * (1 - free / total) if total else 0.0
        level = "ok"
        if used_pct >= critical_pct:
            level = "critical"
        elif used_pct >= high_pct:
            level = "high"
        return {
            "total_bytes": total,
            "free_bytes": free,
            "used_pct": round(used_pct, 1),
            "level": level,
        }

    def start_pressure_loop(
        self, interval_s: float = 60.0,
        on_pressure: Optional[Callable[[dict], None]] = None,
        gc_live_ids: Optional[Callable[[], set]] = None,
    ):
        """Background monitor: at 'high' it triggers an early GC; at
        'critical' it also drops golden snapshots (rebuildable caches go
        first, reference disk_pressure.go)."""
        stop = threading.Event()

        def run():
            while not stop.is_set():
                p = self.disk_pressure()
                if p["level"] != "ok":
                    if on_pressure is not None:
                        on_pressure(p)
                    if gc_live_ids is not None:
                        self.gc(gc_live_ids, min_age_s=0)
                    if p["level"] == "critical":
                        for info in self.list_golden():
                            self.drop_golden(info["project"])
                stop.wait(interval_s)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return stop
