"""Service connections: stored credentials for external services.

The reference keeps token-based connections to external forges and
services per user/org (``/api/v1/service-connections`` +
``/api/v1/git-provider-connections/{}/repositories`` in
``api/pkg/server/server.go``) — the credential store behind forge sync,
repo import, and provider-backed skills.

Tokens are envelope-encrypted with the control plane's master key (the
same posture as user secrets — a leaked DB row is ciphertext), never
returned by list/get APIs, and resolved in-process by consumers
(``GitHubSync`` takes its token from here instead of the environment).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS service_connections (
  id TEXT PRIMARY KEY,
  owner TEXT NOT NULL,
  provider TEXT NOT NULL,           -- github | gitlab | generic
  name TEXT NOT NULL,
  base_url TEXT NOT NULL DEFAULT '',
  api_base TEXT NOT NULL DEFAULT '',
  token_ciphertext BLOB NOT NULL,
  created_at REAL NOT NULL
);
"""

PROVIDERS = ("github", "gitlab", "generic")

_DEFAULT_API = {
    "github": "https://api.github.com",
    "gitlab": "https://gitlab.com/api/v4",
}


class ServiceConnections:
    def __init__(self, auth, http=None):
        """auth: the Authenticator (shared DB + envelope crypto);
        http: injectable requests-like session for forge API calls."""
        self.auth = auth
        self._db = auth._db
        self._conn = auth._conn
        self._lock = auth._lock
        self._db.migrate("service_connections", [(1, "initial", _SCHEMA)])
        if http is None:
            import requests

            http = requests.Session()
        self._http = http

    @staticmethod
    def _check_url(url: str) -> None:
        """SSRF guard: a user-supplied api_base/base_url must not point
        the control plane's outbound requests at internal services or
        cloud metadata (same posture as the crawler's default_fetch)."""
        if not url:
            return
        import os
        import urllib.parse

        from helix_tpu.knowledge.crawler import _host_is_private

        p = urllib.parse.urlsplit(url)
        if p.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme in {url!r}")
        if (
            os.environ.get("HELIX_CRAWLER_ALLOW_PRIVATE") != "1"
            and _host_is_private(p.hostname or "")
        ):
            raise ValueError(
                f"refusing private address {url!r} "
                "(HELIX_CRAWLER_ALLOW_PRIVATE=1 to allow intranet forges)"
            )

    # -- CRUD ----------------------------------------------------------------
    def create(self, owner: str, provider: str, token: str,
               name: str = "", base_url: str = "",
               api_base: str = "") -> dict:
        if provider not in PROVIDERS:
            raise ValueError(f"provider must be one of {PROVIDERS}")
        if not token:
            raise ValueError("token is required")
        self._check_url(base_url)
        self._check_url(api_base)
        cid = f"svc_{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._conn.execute(
                "INSERT INTO service_connections(id, owner, provider,"
                " name, base_url, api_base, token_ciphertext, created_at)"
                " VALUES(?,?,?,?,?,?,?,?)",
                (
                    cid, owner, provider, name or provider,
                    base_url,
                    api_base or _DEFAULT_API.get(provider, ""),
                    self.auth.encrypt(token.encode()),
                    time.time(),
                ),
            )
            self._db.commit()
        return self.get(cid)

    def get(self, cid: str) -> Optional[dict]:
        row = self._row(cid)
        if row is None:
            return None
        return self._to_dict(row)

    def _row(self, cid: str):
        with self._lock:
            return self._conn.execute(
                "SELECT id, owner, provider, name, base_url, api_base,"
                " token_ciphertext, created_at FROM service_connections"
                " WHERE id=?",
                (cid,),
            ).fetchone()

    @staticmethod
    def _to_dict(row) -> dict:
        # token NEVER leaves the store through the API shape
        return {
            "id": row[0], "owner": row[1], "provider": row[2],
            "name": row[3], "base_url": row[4], "api_base": row[5],
            "created_at": row[7],
        }

    def list(self, owner: Optional[str] = None) -> List[dict]:
        q = ("SELECT id, owner, provider, name, base_url, api_base,"
             " token_ciphertext, created_at FROM service_connections")
        args: tuple = ()
        if owner:
            q += " WHERE owner=?"
            args = (owner,)
        q += " ORDER BY created_at"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [self._to_dict(r) for r in rows]

    def delete(self, cid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM service_connections WHERE id=?", (cid,)
            )
            self._db.commit()
        return cur.rowcount > 0

    # -- consumers -----------------------------------------------------------
    def token(self, cid: str) -> Optional[str]:
        """Decrypted token for IN-PROCESS consumers (forge sync, skills)."""
        row = self._row(cid)
        if row is None:
            return None
        return self.auth.decrypt(row[6]).decode()

    def repositories(self, cid: str, per_page: int = 50) -> List[dict]:
        """List repositories visible to the connection (the
        /git-provider-connections/{}/repositories surface)."""
        row = self._row(cid)
        if row is None:
            raise KeyError(cid)
        provider, api_base = row[2], row[5]
        # re-check at use time: the env may have changed, and rows could
        # predate the guard
        self._check_url(api_base)
        tok = self.auth.decrypt(row[6]).decode()
        if provider == "github":
            r = self._http.get(
                f"{api_base}/user/repos",
                params={"per_page": per_page, "sort": "pushed"},
                headers={"Authorization": f"Bearer {tok}"},
                timeout=20, allow_redirects=False,
            )
            if 300 <= getattr(r, "status_code", 200) < 400:
                # a redirecting forge could bounce the (SSRF-checked)
                # request at an internal target — refuse, like the
                # crawler does per hop
                raise ValueError("forge API redirected; refusing")
            r.raise_for_status()
            return [
                {
                    "full_name": x.get("full_name", ""),
                    "clone_url": x.get("clone_url", ""),
                    "default_branch": x.get("default_branch", "main"),
                    "private": bool(x.get("private")),
                }
                for x in r.json()
            ]
        if provider == "gitlab":
            r = self._http.get(
                f"{api_base}/projects",
                params={"membership": "true", "per_page": per_page},
                headers={"PRIVATE-TOKEN": tok},
                timeout=20, allow_redirects=False,
            )
            if 300 <= getattr(r, "status_code", 200) < 400:
                raise ValueError("forge API redirected; refusing")
            r.raise_for_status()
            return [
                {
                    "full_name": x.get("path_with_namespace", ""),
                    "clone_url": x.get("http_url_to_repo", ""),
                    "default_branch": x.get("default_branch", "main"),
                    "private": x.get("visibility") != "public",
                }
                for x in r.json()
            ]
        raise ValueError(
            f"repository listing not supported for {provider!r}"
        )

    def github_sync(self, cid: str, git, repos: Optional[dict] = None):
        """A GitHubSync wired with this connection's token + api_base
        (the forge bridge resolves credentials from here, not the
        environment)."""
        from helix_tpu.services.github_sync import GitHubSync

        row = self._row(cid)
        if row is None:
            raise KeyError(cid)
        return GitHubSync(
            git,
            api_base=row[5] or "https://api.github.com",
            token=self.auth.decrypt(row[6]).decode(),
            repos=repos,
        )
