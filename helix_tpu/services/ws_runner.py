"""External agent runners over WebSocket: remote agents work the kanban.

Reference: the external-agent runner WS pattern — agent processes connect
to the control plane (``server.go:798`` "/ws/external-agent-runner",
``serve.go:305-307`` GPTScript-style external runners) and receive work;
the Zed flow additionally syncs code through the internal git server
rather than a shared filesystem.

Protocol (JSON frames):
  runner -> server: {"type": "register", "name", "agent", "concurrency"}
  server -> runner: {"type": "task", "task_id", "mode", "title",
                     "description", "spec_path", "feedback",
                     "git_url", "branch"}
  runner -> server: {"type": "log",    "task_id", "text"}      (streamed)
  runner -> server: {"type": "result", "task_id", "output"}
  runner -> server: {"type": "error",  "task_id", "error"}

The orchestrator runs executors on its own thread, so the executor blocks
on a threading.Event while the asyncio side sends/receives frames; a
disconnect fails all of that runner's in-flight tasks immediately (the
orchestrator's bounded retries then re-dispatch).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Callable, Optional

log = logging.getLogger("helix.wsrunner")


class PendingTask:
    def __init__(self, task_id: str):
        self.task_id = task_id
        self.event = threading.Event()
        self.output: Optional[str] = None
        self.error: Optional[str] = None


class WSRunner:
    """One connected runner (server side)."""

    def __init__(self, name: str, agent: str, send_fn: Callable[[dict], None],
                 concurrency: int = 1):
        self.name = name
        self.agent = agent
        self.send = send_fn              # thread-safe frame sender
        self.concurrency = max(1, concurrency)
        self.pending: dict[str, PendingTask] = {}
        self.connected_at = time.time()

    @property
    def busy(self) -> int:
        return len(self.pending)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "agent": self.agent,
            "concurrency": self.concurrency,
            "in_flight": self.busy,
            "connected_at": self.connected_at,
        }


class WSRunnerRegistry:
    """Connected external runners + dispatch bookkeeping."""

    def __init__(self):
        self._runners: dict[str, WSRunner] = {}
        self._lock = threading.Lock()

    def register(self, runner: WSRunner) -> None:
        with self._lock:
            self._runners[runner.name] = runner

    def unregister(self, name: str, expected: Optional[WSRunner] = None,
                   ) -> None:
        """Disconnect: fail every in-flight task on this runner so the
        orchestrator's retry loop can re-dispatch (reference: runner
        crash reconciliation).

        ``expected`` guards against a stale connection's late cleanup
        (heartbeat timeout) removing a runner that has since RECONNECTED
        under the same name: only the registry entry matching this exact
        connection object is removed."""
        with self._lock:
            current = self._runners.get(name)
            if expected is not None and current is not expected:
                # stale connection's late cleanup: fail ITS tasks, leave
                # the (re-registered or already-removed) entry alone
                runner = expected
            else:
                runner = current
                self._runners.pop(name, None)
        if runner is None:
            return
        for p in list(runner.pending.values()):
            p.error = f"runner '{name}' disconnected"
            p.event.set()
        runner.pending.clear()

    def list(self) -> list:
        with self._lock:
            return [r.to_dict() for r in self._runners.values()]

    def broadcast(self, frame: dict) -> int:
        """Best-effort frame to every connected runner (settings sync —
        reference: settings-sync-daemon pushing Zed/agent settings into
        running desktops). Returns how many runners received it."""
        with self._lock:
            runners = list(self._runners.values())
        n = 0
        for r in runners:
            try:
                r.send(frame)
                n += 1
            except Exception:  # noqa: BLE001 — a dead socket is handled
                pass           # by its own connection teardown
        return n

    def pick(self, agent: Optional[str] = None) -> Optional[WSRunner]:
        """Least-loaded runner with free capacity (optionally filtered by
        agent type)."""
        with self._lock:
            candidates = [
                r for r in self._runners.values()
                if (agent is None or r.agent == agent)
                and r.busy < r.concurrency
            ]
        if not candidates:
            return None
        return min(candidates, key=lambda r: r.busy)

    def handle_frame(self, runner_name: str, frame: dict,
                     on_log=None) -> None:
        """Process one runner->server frame (log/result/error)."""
        with self._lock:
            runner = self._runners.get(runner_name)
        if runner is None:
            return
        tid = frame.get("task_id", "")
        p = runner.pending.get(tid)
        kind = frame.get("type")
        if kind == "log":
            if on_log is not None:
                on_log(tid, frame.get("text", ""))
            return
        if p is None:
            return
        if kind == "result":
            p.output = frame.get("output", "")
        elif kind == "error":
            p.error = frame.get("error", "unknown runner error")
        else:
            return
        runner.pending.pop(tid, None)
        p.event.set()


class WSRunnerExecutor:
    """Executor that dispatches kanban work to a connected WS runner.

    The workspace is NOT shared: the task frame carries the internal git
    smart-HTTP URL + branch (``git_url_fn(task, mode)``), the runner
    clones/pushes like the reference's Zed agents do."""

    def __init__(
        self,
        registry: WSRunnerRegistry,
        git_url_fn: Callable,
        agent: Optional[str] = None,
        timeout_s: float = 1800.0,
    ):
        self.registry = registry
        self.git_url_fn = git_url_fn
        self.agent = agent
        self.timeout_s = timeout_s

    def run(self, task, workspace: str, mode: str,
            feedback: str = "") -> str:
        runner = self.registry.pick(self.agent)
        if runner is None:
            raise RuntimeError(
                "no external runner connected"
                + (f" for agent '{self.agent}'" if self.agent else "")
            )
        tid = f"wst-{uuid.uuid4().hex[:10]}"
        pending = PendingTask(tid)
        runner.pending[tid] = pending
        git_url, branch = self.git_url_fn(task, mode)
        frame = {
            "type": "task",
            "task_id": tid,
            "mode": mode,
            "title": task.title,
            "description": task.description,
            "spec_path": getattr(task, "spec_path", ""),
            "feedback": feedback,
            "git_url": git_url,
            "branch": branch,
        }
        try:
            runner.send(frame)
        except Exception as e:
            runner.pending.pop(tid, None)
            raise RuntimeError(f"runner send failed: {e}") from e
        if not pending.event.wait(self.timeout_s):
            runner.pending.pop(tid, None)
            raise RuntimeError(
                f"external runner timed out after {self.timeout_s:.0f}s"
            )
        if pending.error is not None:
            raise RuntimeError(pending.error)
        self._sync_workspace(workspace, branch)
        return pending.output or ""

    @staticmethod
    def _sync_workspace(workspace: str, branch: str) -> None:
        """The runner pushed its work to the internal repo; materialise
        that branch into the orchestrator's local workspace so the rest
        of the pipeline (spec existence check, PR diff base) sees it.
        commit_and_push afterwards is a clean-tree no-op."""
        import os
        import subprocess

        if not os.path.isdir(os.path.join(workspace, ".git")):
            return
        try:
            subprocess.run(
                ["git", "-C", workspace, "fetch", "-q", "origin", branch],
                check=True, capture_output=True,
            )
            subprocess.run(
                ["git", "-C", workspace, "checkout", "-q", "-B", branch,
                 "FETCH_HEAD"],
                check=True, capture_output=True,
            )
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                "runner reported success but its branch "
                f"'{branch}' could not be fetched: "
                f"{e.stderr.decode(errors='replace')[:300]}"
            ) from e
