"""Interactive dev sandboxes — the org-scoped sandbox REST family.

The reference exposes ephemeral dev sandboxes per organization with an
interactive surface: run commands, stream logs, kill, browse/read the
workspace, screenshot the attached desktop
(``/organizations/{}/sandboxes`` + ``/commands|files|screenshot`` in
``api/pkg/server/server.go``, backed by hydra dev containers).

Ours are process sandboxes (the same posture as the spec-task sandbox:
setsid group, scrubbed env, rlimits applied in the child before any user
command runs) over a per-sandbox workspace directory, with an optional
GUI desktop attached for the screenshot/VNC-ish surface.  The container
executor (``services/containers.py``) is the stronger-isolation seam
when a runtime exists.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

COMMAND_LOG_LINES = 2000


class Command:
    def __init__(self, shell: str, cwd: str, env: dict,
                 cpu_s: int, memory_bytes: int, timeout_s: float):
        self.id = f"cmd_{uuid.uuid4().hex[:12]}"
        self.shell = shell
        self.status = "running"
        self.exit_code: Optional[int] = None
        self.started = time.time()
        self.finished: Optional[float] = None
        self._log: deque = deque(maxlen=COMMAND_LOG_LINES)
        self._lock = threading.Lock()
        # the trusted child launcher applies rlimits before exec'ing the
        # user command (no preexec_fn: fork+threads deadlock hazard)
        launcher = (
            "import resource, os, sys\n"
            f"resource.setrlimit(resource.RLIMIT_CPU, ({cpu_s}, {cpu_s}))\n"
            f"resource.setrlimit(resource.RLIMIT_AS,"
            f" ({memory_bytes}, {memory_bytes}))\n"
            "resource.setrlimit(resource.RLIMIT_NOFILE, (512, 512))\n"
            "os.execvp('/bin/sh', ['/bin/sh', '-c', sys.argv[1]])\n"
        )
        self._proc = subprocess.Popen(
            [sys.executable, "-c", launcher, shell],
            cwd=cwd, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, start_new_session=True,
        )
        self._timer = threading.Timer(timeout_s, self.kill)
        self._timer.daemon = True
        self._timer.start()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    def _pump(self) -> None:
        for line in self._proc.stdout:
            with self._lock:
                self._log.append(line.rstrip("\n"))
        rc = self._proc.wait()
        self._timer.cancel()
        with self._lock:
            self.exit_code = rc
            self.status = "exited" if self.status != "killed" else "killed"
            self.finished = time.time()

    def kill(self) -> bool:
        with self._lock:
            if self.status != "running":
                return False
            self.status = "killed"
        try:
            os.killpg(self._proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def log(self, tail: int = 200) -> List[str]:
        with self._lock:
            return list(self._log)[-tail:]

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "id": self.id, "command": self.shell,
                "status": self.status, "exit_code": self.exit_code,
                "started": self.started, "finished": self.finished,
            }


class DevSandbox:
    def __init__(self, org_id: str, name: str, root: str,
                 cpu_s: int = 120, memory_bytes: int = 1 << 30,
                 command_timeout_s: float = 300.0,
                 desktop_session=None):
        self.id = f"sbx_{uuid.uuid4().hex[:12]}"
        self.org_id = org_id
        self.name = name
        self.workspace = os.path.join(root, self.id)
        os.makedirs(self.workspace, exist_ok=True)
        self.created = time.time()
        self.status = "running"
        self.cpu_s = cpu_s
        self.memory_bytes = memory_bytes
        self.command_timeout_s = command_timeout_s
        self.commands: Dict[str, Command] = {}
        self.desktop = desktop_session    # optional GUI desktop
        self._lock = threading.Lock()

    def _env(self) -> dict:
        return {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": self.workspace,
            "LANG": os.environ.get("LANG", "C.UTF-8"),
        }

    def run_command(self, shell: str) -> Command:
        if self.status != "running":
            raise RuntimeError("sandbox is stopped")
        cmd = Command(
            shell, cwd=self.workspace, env=self._env(),
            cpu_s=self.cpu_s, memory_bytes=self.memory_bytes,
            timeout_s=self.command_timeout_s,
        )
        with self._lock:
            self.commands[cmd.id] = cmd
        return cmd

    # -- files (workspace-contained) --------------------------------------
    def _resolve(self, path: str) -> str:
        p = os.path.realpath(
            os.path.join(self.workspace, path.lstrip("/"))
        )
        ws = os.path.realpath(self.workspace)
        if p != ws and not p.startswith(ws + os.sep):
            raise PermissionError("path escapes the sandbox workspace")
        return p

    def list_files(self, path: str = "") -> List[dict]:
        p = self._resolve(path or ".")
        if not os.path.isdir(p):
            return []
        out = []
        for name in sorted(os.listdir(p)):
            fp = os.path.join(p, name)
            try:
                # lstat: a dangling symlink a command created must not
                # 500 the listing
                st = os.lstat(fp)
            except OSError:
                continue
            out.append({
                "name": name,
                "path": os.path.join(path, name).lstrip("/"),
                "is_dir": os.path.isdir(fp),
                "size": st.st_size,
                "modified": st.st_mtime,
            })
        return out

    def read_file(self, path: str, max_bytes: int = 1 << 20) -> bytes:
        with open(self._resolve(path), "rb") as f:
            return f.read(max_bytes)

    def screenshot_png(self) -> Optional[bytes]:
        """PNG of the attached desktop (None without one)."""
        if self.desktop is None:
            return None
        from helix_tpu.desktop.mcp_server import _png

        return _png(self.desktop.source.get_frame())

    def stop(self) -> None:
        self.status = "stopped"
        for cmd in list(self.commands.values()):
            cmd.kill()
        if self.desktop is not None:
            self.desktop.stop()

    def destroy(self) -> None:
        self.stop()
        shutil.rmtree(self.workspace, ignore_errors=True)

    def to_dict(self) -> dict:
        return {
            "id": self.id, "org_id": self.org_id, "name": self.name,
            "status": self.status, "created": self.created,
            "workspace": self.workspace,
            "desktop_id": self.desktop.id if self.desktop else None,
            "commands": len(self.commands),
        }


class DevSandboxService:
    def __init__(self, root: str, desktops=None,
                 max_per_org: int = 8, workspaces=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.desktops = desktops          # DesktopManager (optional)
        self.workspaces = workspaces      # WorkspaceManager: golden snaps
        self.max_per_org = max_per_org
        self._sandboxes: Dict[str, DevSandbox] = {}
        self._lock = threading.Lock()

    def create(self, org_id: str, name: str = "",
               with_desktop: bool = False,
               init_script: str = "", golden: str = "",
               **limits) -> DevSandbox:
        """init_script: shell run in the fresh workspace before the
        sandbox is handed over (the reference's sandbox container init
        scripts — toolchain setup, repo clone, env priming).
        golden: a project whose golden snapshot seeds the workspace
        (hardlink clone — warm toolchains/build caches for ~free, the
        hydra golden.go posture)."""
        # quota check + registration under ONE lock hold (two concurrent
        # creates must not both pass the count and overshoot the quota);
        # sandbox construction is local mkdir work, cheap enough to hold
        desktop = None
        if with_desktop and self.desktops is not None:
            desktop = self.desktops.create(
                name=f"sandbox:{name}", kind="gui"
            )
        try:
            with self._lock:
                n = sum(
                    1 for s in self._sandboxes.values()
                    if s.org_id == org_id and s.status == "running"
                )
                if n >= self.max_per_org:
                    raise RuntimeError(
                        f"org sandbox quota reached ({self.max_per_org})"
                    )
                sb = DevSandbox(
                    org_id, name or "sandbox", self.root,
                    desktop_session=desktop, **limits,
                )
                self._sandboxes[sb.id] = sb
        except BaseException:
            # quota hit OR constructor failure: the desktop session we
            # spun up must not outlive this failed create
            if desktop is not None:
                self.desktops.destroy(desktop.id)
            raise
        if golden:
            try:
                if self.workspaces is None:
                    raise ValueError(
                        "no workspace manager for golden seeds"
                    )
                # BYTE copies, not hardlinks: this sandbox runs
                # arbitrary user shell — aliased inodes would let it
                # mutate the shared golden in place
                self.workspaces.seed_from_golden(
                    golden, sb.workspace, hardlink=False
                )
            except BaseException:
                # a failed seed must not leak the registered sandbox
                # (it would count against the org quota forever)
                self.destroy(sb.id)
                raise
        if init_script:
            sb.run_command(init_script)   # async; status via /commands
        return sb

    def promote_golden(self, sid: str, project: str):
        """Capture a sandbox's workspace as a project's golden snapshot
        — the interactive half of promote-session-to-golden."""
        sb = self.get(sid)
        if sb is None:
            raise KeyError(sid)
        if self.workspaces is None:
            raise ValueError("no workspace manager for golden snapshots")
        # copy-mode: the source sandbox keeps running user shell
        return self.workspaces.promote_golden(
            project, sb.workspace, hardlink=False
        )

    def get(self, sid: str) -> Optional[DevSandbox]:
        return self._sandboxes.get(sid)

    def list(self, org_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            sandboxes = list(self._sandboxes.values())
        return [
            s.to_dict() for s in sandboxes
            if org_id is None or s.org_id == org_id
        ]

    def destroy(self, sid: str) -> bool:
        with self._lock:
            sb = self._sandboxes.pop(sid, None)
        if sb is None:
            return False
        if sb.desktop is not None and self.desktops is not None:
            self.desktops.destroy(sb.desktop.id)
        sb.destroy()
        return True

    def stop_all(self) -> None:
        for sid in list(self._sandboxes):
            self.destroy(sid)
