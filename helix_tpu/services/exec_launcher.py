"""Trusted exec launcher: apply resource limits, then become the agent.

Third-party agent binaries cannot apply their own rlimits the way our
``sandbox_runner`` does, and ``preexec_fn`` in a threaded parent can
deadlock (subprocess docs) — so the parent launches THIS module, which
applies the limits in-process and ``exec``s the target argv. The agent
inherits the limits, the session (``setsid`` by the parent), and the
scrubbed environment. Counterpart of the reference's container-side
entrypoint wrapper (``api/pkg/external-agent/hydra_executor.go:130-569``
runs agents under a container runtime that enforces limits for it).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    spec = json.loads(sys.argv[1])
    from helix_tpu.services.sandbox_runner import _apply_limits

    _apply_limits(spec.get("limits") or {})
    # PYTHONPATH exists only so THIS launcher can import; the agent must
    # not inherit repo access through it (scrubbed-env guarantee)
    os.environ.pop("PYTHONPATH", None)
    argv = spec["argv"]
    os.execvp(argv[0], argv)


if __name__ == "__main__":
    main()
