from helix_tpu.services.git_service import GitService
from helix_tpu.services.spec_tasks import (
    AgentExecutor,
    Executor,
    SpecTask,
    SpecTaskOrchestrator,
    TaskStore,
)

__all__ = [
    "GitService",
    "AgentExecutor",
    "Executor",
    "SpecTask",
    "SpecTaskOrchestrator",
    "TaskStore",
]
