"""helix-tpu: a TPU-native agent-fleet + GenAI serving/training framework.

A ground-up rebuild of the capabilities of helixml/helix (see SURVEY.md) whose
accelerator plane is JAX/XLA/Pallas on TPU instead of vLLM-CUDA containers:

- ``helix_tpu.device``   — TPU topology + HBM accounting (replaces
  ``api/pkg/gpudetect`` + ``api/pkg/runner/gpuarch`` in the reference).
- ``helix_tpu.ops``      — Pallas TPU kernels (flash/paged attention, norms)
  with pure-XLA reference paths for CPU testing.
- ``helix_tpu.models``   — model families (Llama, Phi, Qwen2-VL, BGE) as
  functional JAX code over parameter pytrees.
- ``helix_tpu.parallel`` — mesh construction, logical sharding rules, ring
  attention / sequence parallelism (replaces NCCL-inside-vLLM with XLA
  collectives over ICI/DCN).
- ``helix_tpu.engine``   — the serving engine: paged KV cache, continuous
  batching scheduler, sampling, HBM-accounted multi-model residency
  (replaces the vLLM container + ``api/pkg/composemgr`` hot-swap).
- ``helix_tpu.serving``  — OpenAI/Anthropic-compatible HTTP surface
  (``/v1/chat/completions``, ``/v1/embeddings``, SSE streaming).
- ``helix_tpu.training`` — SPMD LoRA SFT with checkpoint/resume (replaces the
  reference's deleted axolotl path).
- ``helix_tpu.control``  — control-plane: profiles, router, heartbeats,
  session store (mirrors ``api/pkg/inferencerouter``, ``api/pkg/runner``).
"""

__version__ = "0.1.0"
