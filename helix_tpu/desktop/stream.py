"""Desktop session streaming: frame sources -> native codec -> WebSocket.

The headless counterpart of the reference's desktop video path
(``SURVEY.md`` §3.5: compositor -> zero-copy capture -> encoder ladder ->
H.264 over WS -> browser WebCodecs).  On a TPU node there is no GPU
compositor; agent "desktops" render their activity into a framebuffer
(``TextScreenSource`` — the agent terminal view), the native tile codec
(``native/streamcore``) encodes damage, and packets fan out to WebSocket
subscribers; input events flow the reverse way into the source.  The
client-side decoder is the same native library (plus a browser JS decoder
in the web UI).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

import numpy as np

from helix_tpu.desktop.streamcore import StreamEncoder


class TextScreenSource:
    """A scrolling text screen rendered to BGRA — the visible surface of an
    in-process agent (steps, logs, chat), standing in for a compositor."""

    def __init__(self, width: int = 960, height: int = 540,
                 max_lines: int = 2000):
        self.width = width
        self.height = height
        self._lines: list = []
        self._max_lines = max_lines
        self._lock = threading.Lock()
        self._dirty = True
        self._frame = np.zeros((height, width, 4), np.uint8)
        self._input_log: list = []

    def push_line(self, text: str) -> None:
        with self._lock:
            for chunk in text.splitlines() or [""]:
                self._lines.append(chunk[:200])
            self._lines = self._lines[-self._max_lines:]
            self._dirty = True

    def input(self, event: dict) -> None:
        """Input events (keyboard) append to the screen as user input —
        the steering channel of the reference's desktop sessions."""
        self._input_log.append(event)
        if event.get("type") == "text":
            self.push_line(f"> {event.get('text', '')}")

    def get_frame(self) -> np.ndarray:
        with self._lock:
            if not self._dirty:
                return self._frame
            from PIL import Image, ImageDraw

            img = Image.new("RGBA", (self.width, self.height), (18, 18, 24, 255))
            draw = ImageDraw.Draw(img)
            line_h = 14
            max_rows = self.height // line_h - 1
            rows = self._lines[-max_rows:]
            for i, line in enumerate(rows):
                draw.text((8, 4 + i * line_h), line, fill=(220, 220, 210, 255))
            rgba = np.asarray(img, np.uint8)
            self._frame = rgba[:, :, [2, 1, 0, 3]].copy()  # RGBA -> BGRA
            self._dirty = False
            return self._frame


class DesktopSession:
    """One streamed desktop: source + encoder + subscriber fanout.

    ``codec`` picks the wire format: "tiles" (lossless damage tiles, the
    default for text screens) or "video" (the native lossy DCT codec —
    the software H.264 stand-in for GUI desktops,
    ``api/pkg/desktop/ws_stream.go:502-530``)."""

    def __init__(self, source, fps: float = 10.0, name: str = "",
                 codec: str = "tiles"):
        self.id = f"dsk_{uuid.uuid4().hex[:12]}"
        self.name = name
        self.source = source
        self.fps = fps
        self.codec = codec
        if codec == "video":
            from helix_tpu.desktop.video import VideoEncoder

            self.encoder = VideoEncoder(
                source.width, source.height, quality=70,
                target_kbps=2000, fps=fps,
            )
        else:
            self.encoder = StreamEncoder(source.width, source.height)
        self._subs: dict[str, Callable[[bytes], None]] = {}
        self._need_keyframe = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.created = time.time()

    def subscribe(self, cb: Callable[[bytes], None]) -> str:
        sid = uuid.uuid4().hex
        with self._lock:
            self._subs[sid] = cb
            self._need_keyframe = True
        return sid

    def unsubscribe(self, sid: str) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def handle_input(self, event: dict) -> None:
        if event.get("type") == "refresh":
            # a viewer lost a P-frame (backpressure drop) and needs an I
            with self._lock:
                self._need_keyframe = True
            return
        if hasattr(self.source, "input"):
            self.source.input(event)

    def _tick(self) -> Optional[bytes]:
        frame = self.source.get_frame()
        with self._lock:
            kf = self._need_keyframe
            self._need_keyframe = False
            subs = list(self._subs.values())
        packet = self.encoder.encode(frame, keyframe=kf)
        if packet is not None:
            for cb in subs:
                try:
                    cb(packet)
                except Exception:  # noqa: BLE001 — dead subscriber
                    pass
        return packet

    def start(self):
        def run():
            period = 1.0 / self.fps
            while not self._stop.is_set():
                t0 = time.monotonic()
                self._tick()
                dt = time.monotonic() - t0
                self._stop.wait(max(period - dt, 0.01))

        self._thread = threading.Thread(
            target=run, name=f"helix-desktop-{self.id}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()


class ExternalDesktopSession:
    """A desktop whose frames are PRODUCED OUTSIDE this process — the
    guest half runs :mod:`helix_tpu.desktop.bridge` inside a sandbox/VM
    and ships pre-encoded packets up a provider WebSocket; input events
    flow back down the same socket (the reference's desktop-bridge guest
    agent, ``SURVEY.md`` §2.3 #38).

    Shares DesktopSession's subscriber surface so /ws/stream and
    /ws/input work unchanged; there is no local encoder or frame loop —
    the guest owns pacing and encoding."""

    def __init__(self, name: str = "", codec: str = "video",
                 width: int = 960, height: int = 540, fps: float = 10.0):
        self.id = f"dsk_{uuid.uuid4().hex[:12]}"
        self.name = name
        self.codec = codec
        self.fps = fps
        self.created = time.time()

        class _Shape:
            pass

        self.source = _Shape()
        self.source.width = width
        self.source.height = height
        self._subs: dict[str, Callable[[bytes], None]] = {}
        self._input_sink: Optional[Callable[[dict], None]] = None
        self._lock = threading.Lock()
        self._last_keyframe: Optional[bytes] = None
        self.provider_connected = False
        self._packets = 0
        self._bytes = 0

    # -- viewer side (same protocol as DesktopSession) ---------------------
    def subscribe(self, cb: Callable[[bytes], None]) -> str:
        sid = uuid.uuid4().hex
        with self._lock:
            self._subs[sid] = cb
            kf = self._last_keyframe
        # late joiner: replay the last keyframe immediately, then ask the
        # guest for a fresh one
        if kf is not None:
            try:
                cb(kf)
            except Exception:  # noqa: BLE001
                pass
        self.handle_input({"type": "refresh"})
        return sid

    def unsubscribe(self, sid: str) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def handle_input(self, event: dict) -> None:
        with self._lock:
            sink = self._input_sink
        if sink is not None:
            try:
                sink(event)
            except Exception:  # noqa: BLE001 — provider gone mid-send
                pass

    # -- provider side -----------------------------------------------------
    def attach_provider(self, input_sink: Callable[[dict], None]) -> None:
        with self._lock:
            self._input_sink = input_sink
            self.provider_connected = True
        # a (re)connecting guest must start with an I-frame
        self.handle_input({"type": "refresh"})

    def detach_provider(self, input_sink=None) -> None:
        """Compare-and-clear: a lingering dead connection (noticed only at
        heartbeat timeout) must not detach the sink a reconnected provider
        just attached.  ``None`` forces the clear (shutdown)."""
        with self._lock:
            if input_sink is not None and self._input_sink is not input_sink:
                return
            self._input_sink = None
            self.provider_connected = False

    def push_packet(self, packet: bytes) -> None:
        """Guest-encoded packet -> fan out to viewers."""
        is_kf = False
        # both codecs carry a type/keyframe marker: HXV1 byte 12 (0 = I),
        # HXF1 keyframe flag at byte 14 — guard each offset separately so
        # a truncated/malicious guest packet can't IndexError the relay
        if packet[:4] == b"HXV1" and len(packet) >= 13:
            is_kf = packet[12] == 0
        elif packet[:4] == b"HXF1" and len(packet) >= 15:
            is_kf = packet[14] == 1
        with self._lock:
            if is_kf:
                self._last_keyframe = packet
            subs = list(self._subs.values())
            self._packets += 1
            self._bytes += len(packet)
        for cb in subs:
            try:
                cb(packet)
            except Exception:  # noqa: BLE001 — dead subscriber
                pass

    # -- manager protocol --------------------------------------------------
    @property
    def encoder(self):
        class _Stats:
            stats = {
                "packets": self._packets, "bytes_out": self._bytes,
                "provider_connected": self.provider_connected,
            }

        return _Stats()

    def start(self):
        return self

    def stop(self):
        self.detach_provider()


class DesktopManager:
    """Session registry (the hydra dev-container registry analogue)."""

    def __init__(self):
        self._sessions: dict[str, DesktopSession] = {}
        self._lock = threading.Lock()

    def create(self, name: str = "", fps: float = 10.0,
               source=None, kind: str = "text",
               codec: str = "") -> DesktopSession:
        """kind: "text" (agent terminal), "gui" (in-process compositor
        desktop, lossy video codec) or "external" (a desktop-bridge guest
        process provides pre-encoded frames over /ws/provider)."""
        if kind == "external":
            s = ExternalDesktopSession(
                name=name, codec=codec or "video", fps=fps
            )
            with self._lock:
                self._sessions[s.id] = s
            return s
        if source is None:
            if kind == "gui":
                from helix_tpu.desktop.gui import build_agent_desktop

                source, handles = build_agent_desktop()
                source.handles = handles
            else:
                source = TextScreenSource()
        codec = codec or ("video" if kind == "gui" else "tiles")
        s = DesktopSession(source, fps=fps, name=name, codec=codec).start()
        with self._lock:
            self._sessions[s.id] = s
        return s

    def get(self, sid: str) -> Optional[DesktopSession]:
        return self._sessions.get(sid)

    def list(self) -> list:
        with self._lock:
            return [
                {
                    "id": s.id, "name": s.name, "fps": s.fps,
                    "codec": s.codec,
                    "width": s.source.width, "height": s.source.height,
                    "created": s.created,
                    "stats": s.encoder.stats,
                }
                for s in self._sessions.values()
            ]

    def destroy(self, sid: str) -> bool:
        with self._lock:
            s = self._sessions.pop(sid, None)
        if s:
            s.stop()
            return True
        return False

    def stop_all(self):
        for sid in list(self._sessions):
            self.destroy(sid)
