"""ctypes bindings for the native streaming core (native/streamcore).

Builds the shared library on first use if missing (make), mirroring the
reference's pattern of native media elements behind a narrow FFI
(``desktop/wayland-display-core`` cdylib + cgo in ``api/pkg/desktop``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "streamcore",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhxstream.so")
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR], check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.hx_encoder_create.restype = ctypes.c_void_p
        lib.hx_encoder_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.hx_encoder_destroy.argtypes = [ctypes.c_void_p]
        lib.hx_encode.restype = ctypes.c_long
        lib.hx_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.hx_encoder_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hx_decoder_create.restype = ctypes.c_void_p
        lib.hx_decoder_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.hx_decoder_destroy.argtypes = [ctypes.c_void_p]
        lib.hx_decode.restype = ctypes.c_int
        lib.hx_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long
        ]
        lib.hx_decoder_frame.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.hx_decoder_frame.argtypes = [ctypes.c_void_p]
        lib.hx_decoder_frame_id.restype = ctypes.c_uint32
        lib.hx_decoder_frame_id.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class StreamEncoder:
    """Damage-tracking tile encoder. Frames: uint8 [H, W, 4] (BGRA)."""

    def __init__(self, width: int, height: int):
        self._lib = _load()
        self._h = self._lib.hx_encoder_create(width, height)
        if not self._h:
            raise ValueError("bad encoder dimensions")
        self.width = width
        self.height = height

    def encode(self, frame: np.ndarray, keyframe: bool = False) -> Optional[bytes]:
        """-> packet bytes, or None when nothing changed."""
        frame = np.ascontiguousarray(frame, dtype=np.uint8)
        assert frame.shape == (self.height, self.width, 4), frame.shape
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.hx_encode(
            self._h, frame.tobytes(), 1 if keyframe else 0,
            ctypes.byref(out),
        )
        if n < 0:
            raise RuntimeError(f"encode failed: {n}")
        if n == 0:
            return None
        return ctypes.string_at(out, n)

    @property
    def stats(self) -> dict:
        f = ctypes.c_uint64()
        t = ctypes.c_uint64()
        b = ctypes.c_uint64()
        self._lib.hx_encoder_stats(
            self._h, ctypes.byref(f), ctypes.byref(t), ctypes.byref(b)
        )
        return {
            "frames": f.value, "tiles": t.value, "bytes_out": b.value,
        }

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hx_encoder_destroy(self._h)
            self._h = None


class StreamDecoder:
    def __init__(self, width: int, height: int):
        self._lib = _load()
        self._h = self._lib.hx_decoder_create(width, height)
        if not self._h:
            raise ValueError("bad decoder dimensions")
        self.width = width
        self.height = height

    def decode(self, packet: bytes) -> np.ndarray:
        rc = self._lib.hx_decode(self._h, packet, len(packet))
        if rc != 0:
            raise RuntimeError(f"decode failed: {rc}")
        return self.frame

    @property
    def frame(self) -> np.ndarray:
        ptr = self._lib.hx_decoder_frame(self._h)
        buf = ctypes.string_at(
            ptr, self.width * self.height * 4
        )
        return np.frombuffer(buf, np.uint8).reshape(
            self.height, self.width, 4
        )

    @property
    def frame_id(self) -> int:
        return self._lib.hx_decoder_frame_id(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hx_decoder_destroy(self._h)
            self._h = None
