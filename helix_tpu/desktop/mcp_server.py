"""Per-session desktop MCP server.

The reference runs an MCP server inside each desktop session so editor
agents (Zed threads, Claude-family tools) can drive the GUI —
``api/pkg/desktop/mcp_server.go`` (screenshot, type_text, mouse_click,
clipboard, window management over sway/wlroots) exposed through the
control plane at a per-session endpoint
(``api/pkg/server/mcp_backend_desktop.go``).

Ours drives the software compositor desktop (:mod:`helix_tpu.desktop.gui`)
with the same tool inventory, speaking MCP JSON-RPC 2.0:

- transport A: HTTP POST  ``/api/v1/desktops/{id}/mcp``  (one JSON-RPC
  message per request — the streamable-HTTP profile the reference's
  ServeHTTP implements);
- transport B: stdio loop (:func:`serve_stdio`) so
  :class:`helix_tpu.agent.mcp.MCPClient` — and any MCP-speaking editor —
  can spawn it as a subprocess bound to a desktop id.
"""

from __future__ import annotations

import base64
import io
import json
from typing import Optional

PROTOCOL_VERSION = "2024-11-05"


def _png(frame) -> bytes:
    """BGRA numpy frame -> PNG bytes."""
    from PIL import Image

    rgba = frame[:, :, [2, 1, 0, 3]]
    buf = io.BytesIO()
    Image.fromarray(rgba, "RGBA").save(buf, "PNG")
    return buf.getvalue()


class DesktopMCPServer:
    """MCP tool surface over one GUI desktop session."""

    def __init__(self, session):
        """session: DesktopSession whose source is a GuiScreenSource."""
        self.session = session
        self._clipboard = ""

    # -- tool inventory (mirrors mcp_server.go's sway tool set) ------------
    TOOLS = (
        {
            "name": "screenshot",
            "description": "Capture the desktop as a PNG (base64).",
            "inputSchema": {"type": "object", "properties": {}},
        },
        {
            "name": "type_text",
            "description": "Type text into the focused window.",
            "inputSchema": {
                "type": "object",
                "properties": {"text": {"type": "string"}},
                "required": ["text"],
            },
        },
        {
            "name": "press_key",
            "description": "Press a named key (Enter, Backspace, ...).",
            "inputSchema": {
                "type": "object",
                "properties": {"key": {"type": "string"}},
                "required": ["key"],
            },
        },
        {
            "name": "mouse_click",
            "description": "Click at desktop coordinates.",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "x": {"type": "integer"}, "y": {"type": "integer"},
                },
                "required": ["x", "y"],
            },
        },
        {
            "name": "list_windows",
            "description": "List windows (title, geometry, focus).",
            "inputSchema": {"type": "object", "properties": {}},
        },
        {
            "name": "focus_window",
            "description": "Raise + focus a window by title.",
            "inputSchema": {
                "type": "object",
                "properties": {"title": {"type": "string"}},
                "required": ["title"],
            },
        },
        {
            "name": "move_window",
            "description": "Move a window by title to x, y.",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "title": {"type": "string"},
                    "x": {"type": "integer"}, "y": {"type": "integer"},
                },
                "required": ["title", "x", "y"],
            },
        },
        {
            "name": "get_clipboard",
            "description": "Read the desktop clipboard.",
            "inputSchema": {"type": "object", "properties": {}},
        },
        {
            "name": "set_clipboard",
            "description": "Write the desktop clipboard.",
            "inputSchema": {
                "type": "object",
                "properties": {"text": {"type": "string"}},
                "required": ["text"],
            },
        },
    )

    # -- JSON-RPC ----------------------------------------------------------
    def handle(self, msg: dict) -> Optional[dict]:
        """One JSON-RPC message in, one out (None for notifications)."""
        mid = msg.get("id")
        method = msg.get("method", "")
        params = msg.get("params") or {}
        if mid is None and method:  # notification
            return None
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}},
                    "serverInfo": {
                        "name": "helix-desktop",
                        "version": "1.0",
                    },
                }
            elif method == "tools/list":
                result = {"tools": list(self.TOOLS)}
            elif method == "tools/call":
                result = self._call(
                    params.get("name", ""), params.get("arguments") or {}
                )
            elif method == "ping":
                result = {}
            else:
                return {
                    "jsonrpc": "2.0", "id": mid,
                    "error": {"code": -32601,
                              "message": f"unknown method {method!r}"},
                }
            return {"jsonrpc": "2.0", "id": mid, "result": result}
        except Exception as e:  # noqa: BLE001 — tool errors -> MCP error
            return {
                "jsonrpc": "2.0", "id": mid,
                "error": {"code": -32000, "message": str(e)[:500]},
            }

    # -- tools -------------------------------------------------------------
    def _src(self):
        return self.session.source

    def _call(self, name: str, args: dict) -> dict:
        src = self._src()
        if name == "screenshot":
            png = _png(src.get_frame())
            return {"content": [{
                "type": "image", "mimeType": "image/png",
                "data": base64.b64encode(png).decode(),
            }]}
        if name == "type_text":
            src.input({"type": "text", "text": str(args["text"])})
            return _text("typed")
        if name == "press_key":
            src.input({"type": "key", "key": str(args["key"])})
            return _text(f"pressed {args['key']}")
        if name == "mouse_click":
            src.input({
                "type": "pointer", "x": int(args["x"]), "y": int(args["y"]),
                "button": 1, "state": "down",
            })
            src.input({
                "type": "pointer", "x": int(args["x"]), "y": int(args["y"]),
                "state": "up",
            })
            return _text(f"clicked {args['x']},{args['y']}")
        if name == "list_windows":
            return _text(json.dumps(src.window_snapshot()))
        if name == "focus_window":
            w = self._find_window(str(args["title"]))
            # click the titlebar: raises + focuses through the seat path
            src.input({
                "type": "pointer", "x": w.x + 2, "y": w.y + 2,
                "button": 1, "state": "down",
            })
            src.input({"type": "pointer", "x": w.x + 2, "y": w.y + 2,
                       "state": "up"})
            return _text(f"focused {w.title}")
        if name == "move_window":
            w = self._find_window(str(args["title"]))
            src.move_window(w, int(args["x"]), int(args["y"]))
            return _text(f"moved {w.title} to {w.x},{w.y}")
        if name == "get_clipboard":
            return _text(self._clipboard)
        if name == "set_clipboard":
            self._clipboard = str(args["text"])
            return _text("ok")
        raise ValueError(f"unknown tool {name!r}")

    def _find_window(self, title: str):
        for w in self._src().windows:
            if w.title == title:
                return w
        raise ValueError(f"no window titled {title!r}")


def _text(s: str) -> dict:
    return {"content": [{"type": "text", "text": s}]}


def serve_stdio(session) -> None:
    """Blocking stdio MCP loop (newline-delimited JSON-RPC), the transport
    MCPClient and editors spawn."""
    import sys

    srv = DesktopMCPServer(session)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        out = srv.handle(msg)
        if out is not None:
            sys.stdout.write(json.dumps(out) + "\n")
            sys.stdout.flush()
