"""Software GUI toolkit + desktop source for agent desktops.

The reference streams a real Wayland desktop where agents drive GUI apps
(``api/pkg/desktop/ws_stream.go``, ``desktop/wayland-display-core``).  A
TPU node has no GPU or display server, so this module provides the whole
GUI column in software:

- a small widget toolkit (windows with title bars, labels, buttons, text
  inputs, scrolling logs) rendered with PIL into BGRA surfaces;
- :class:`GuiScreenSource`, a desktop source that composites windows via
  the native compositor, routes pointer/keyboard events (hit test -> focus
  -> widget callbacks), supports window dragging and raise-on-click —
  i.e. the job of a display server's seat + the toolkit's event loop;
- a demo "agent console" desktop (:func:`build_agent_desktop`) proving the
  e2e loop the reference sells: watch the agent's GUI, click its buttons,
  type into its inputs, over /ws/stream + /ws/input.

Frames feed either codec (lossless tiles or the lossy video codec) through
the existing :class:`helix_tpu.desktop.stream.DesktopSession`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from helix_tpu.desktop.compositor import Compositor

TITLE_H = 22
_FONT = None


def _font():
    global _FONT
    if _FONT is None:
        from PIL import ImageFont

        _FONT = ImageFont.load_default()
    return _FONT


class Widget:
    """Base widget: a rect inside a window's content area."""

    def __init__(self, x: int, y: int, w: int, h: int):
        self.x, self.y, self.w, self.h = x, y, w, h
        self.focused = False

    def contains(self, px: int, py: int) -> bool:
        return self.x <= px < self.x + self.w and self.y <= py < self.y + self.h

    def draw(self, d) -> None:  # d: PIL ImageDraw
        raise NotImplementedError

    def on_click(self, lx: int, ly: int) -> None:
        pass

    def on_key(self, key: str) -> None:
        pass

    def on_text(self, text: str) -> None:
        pass


class Label(Widget):
    def __init__(self, x, y, text: str, color=(220, 220, 210)):
        super().__init__(x, y, 8 * len(text), 14)
        self.text = text
        self.color = color

    def draw(self, d):
        d.text((self.x, self.y), self.text, fill=self.color, font=_font())


class Button(Widget):
    def __init__(self, x, y, w, h, text: str,
                 on_click: Optional[Callable[[], None]] = None):
        super().__init__(x, y, w, h)
        self.text = text
        self._cb = on_click
        self.clicks = 0

    def draw(self, d):
        d.rectangle(
            [self.x, self.y, self.x + self.w - 1, self.y + self.h - 1],
            fill=(70, 90, 160), outline=(120, 150, 230),
        )
        tw = d.textlength(self.text, font=_font())
        d.text(
            (self.x + (self.w - tw) // 2, self.y + (self.h - 12) // 2),
            self.text, fill=(240, 240, 250), font=_font(),
        )

    def on_click(self, lx, ly):
        self.clicks += 1
        if self._cb:
            self._cb()


class TextInput(Widget):
    def __init__(self, x, y, w, on_submit: Optional[Callable[[str], None]] = None):
        super().__init__(x, y, w, 20)
        self.value = ""
        self._cb = on_submit

    def draw(self, d):
        d.rectangle(
            [self.x, self.y, self.x + self.w - 1, self.y + self.h - 1],
            fill=(28, 28, 36),
            outline=(130, 160, 240) if self.focused else (80, 80, 100),
        )
        shown = self.value[-max(1, self.w // 8 - 2):]
        caret = "_" if self.focused else ""
        d.text((self.x + 4, self.y + 3), shown + caret,
               fill=(230, 230, 220), font=_font())

    def on_text(self, text):
        self.value += text

    def on_key(self, key):
        if key == "Backspace":
            self.value = self.value[:-1]
        elif key in ("Enter", "Return"):
            v, self.value = self.value, ""
            if self._cb:
                self._cb(v)


class LogView(Widget):
    """Scrolling text log (the agent's activity feed)."""

    def __init__(self, x, y, w, h, max_lines: int = 500):
        super().__init__(x, y, w, h)
        self.lines: List[str] = []
        self._max = max_lines

    def push(self, text: str) -> None:
        for chunk in text.splitlines() or [""]:
            self.lines.append(chunk[:200])
        self.lines = self.lines[-self._max:]

    def draw(self, d):
        d.rectangle(
            [self.x, self.y, self.x + self.w - 1, self.y + self.h - 1],
            fill=(14, 14, 18), outline=(60, 60, 75),
        )
        rows = (self.h - 8) // 13
        for i, line in enumerate(self.lines[-rows:]):
            d.text((self.x + 4, self.y + 4 + i * 13), line,
                   fill=(190, 210, 190), font=_font())


class Window:
    """A titled, draggable window backed by one compositor surface."""

    def __init__(self, title: str, x: int, y: int, w: int, h: int):
        self.title = title
        self.x, self.y, self.w, self.h = x, y, w, h
        self.widgets: List[Widget] = []
        self.surface_id: int = 0   # assigned by GuiScreenSource
        self.dirty = True
        self.focus: Optional[Widget] = None

    def add(self, widget: Widget) -> Widget:
        self.widgets.append(widget)
        self.dirty = True
        return widget

    def render(self) -> np.ndarray:
        from PIL import Image, ImageDraw

        img = Image.new("RGBA", (self.w, self.h), (34, 34, 44, 255))
        d = ImageDraw.Draw(img)
        d.rectangle([0, 0, self.w - 1, TITLE_H - 1], fill=(52, 56, 90))
        d.text((8, 4), self.title, fill=(235, 235, 245), font=_font())
        d.rectangle([0, 0, self.w - 1, self.h - 1], outline=(90, 95, 130))
        for wdg in self.widgets:
            base_y = wdg.y
            wdg.y = base_y + TITLE_H
            try:
                wdg.draw(d)
            finally:
                wdg.y = base_y
        rgba = np.asarray(img, np.uint8)
        self.dirty = False
        return rgba[:, :, [2, 1, 0, 3]].copy()   # -> BGRA

    # -- input (coords local to the window) --------------------------------
    def click(self, lx: int, ly: int) -> None:
        cy = ly - TITLE_H
        for wdg in self.widgets:
            was = wdg.focused
            wdg.focused = wdg.contains(lx, cy)
            if wdg.focused:
                self.focus = wdg
            if wdg.focused != was:
                self.dirty = True
        if self.focus is not None and self.focus.contains(lx, cy):
            self.focus.on_click(lx - self.focus.x, cy - self.focus.y)
            self.dirty = True


class GuiScreenSource:
    """A pixel desktop: windows -> native compositor -> BGRA frames, with
    pointer/keyboard routing back into the windows (the seat)."""

    def __init__(self, width: int = 960, height: int = 540):
        self.width = width
        self.height = height
        self.comp = Compositor(width, height)
        self.windows: List[Window] = []
        self._by_surface: dict[int, Window] = {}
        self._lock = threading.Lock()
        self._drag: Optional[Tuple[Window, int, int]] = None
        self._pointer = (width // 2, height // 2)
        self.comp.set_cursor(*self._pointer, True)
        self._input_log: list = []

    def add_window(self, win: Window) -> Window:
        with self._lock:
            win.surface_id = self.comp.create_surface(win.w, win.h)
            self.comp.move(win.surface_id, win.x, win.y)
            self._by_surface[win.surface_id] = win
            self.windows.append(win)
        return win

    def close_window(self, win: Window) -> None:
        with self._lock:
            if win.surface_id:
                self.comp.destroy_surface(win.surface_id)
                self._by_surface.pop(win.surface_id, None)
            if win in self.windows:
                self.windows.remove(win)

    @property
    def focused_window(self) -> Optional[Window]:
        with self._lock:
            return self.windows[-1] if self.windows else None

    def move_window(self, win: Window, x: int, y: int) -> None:
        """Programmatic move (MCP move_window) — same lock discipline as
        the input path; the native compositor has no mutex of its own."""
        with self._lock:
            win.x, win.y = x, y
            self.comp.move(win.surface_id, x, y)

    def window_snapshot(self) -> List[dict]:
        with self._lock:
            focused = self.windows[-1] if self.windows else None
            return [
                {
                    "title": w.title, "x": w.x, "y": w.y,
                    "w": w.w, "h": w.h, "focused": w is focused,
                }
                for w in self.windows
            ]

    # -- stream source protocol --------------------------------------------
    def get_frame(self) -> np.ndarray:
        with self._lock:
            for win in self.windows:
                if win.dirty:
                    self.comp.attach(win.surface_id, win.render())
            self.comp.composite()
            return self.comp.framebuffer

    def input(self, event: dict) -> None:
        """Pointer/keyboard protocol (shared with the web UI viewer):
        {"type": "pointer", "x", "y", ["button", "state"]}  move/click
        {"type": "key", "key": "Backspace"|"Enter"|...}
        {"type": "text", "text": "..."}
        """
        self._input_log.append(event)
        et = event.get("type")
        with self._lock:
            if et == "pointer":
                x = int(event.get("x", 0))
                y = int(event.get("y", 0))
                x = max(0, min(self.width - 1, x))
                y = max(0, min(self.height - 1, y))
                self._pointer = (x, y)
                self.comp.set_cursor(x, y, True)
                if self._drag is not None and not event.get("button"):
                    win, dx, dy = self._drag
                    win.x, win.y = x - dx, y - dy
                    self.comp.move(win.surface_id, win.x, win.y)
                if event.get("state") == "up":
                    self._drag = None
                    return
                if event.get("button") == 1 and event.get("state") == "down":
                    hit = self.comp.hit_test(x, y)
                    if hit is None:
                        return
                    sid, lx, ly = hit
                    win = self._by_surface.get(sid)
                    if win is None:
                        return
                    self.comp.raise_(sid)
                    self.windows.remove(win)
                    self.windows.append(win)
                    if ly < TITLE_H:
                        self._drag = (win, lx, ly)
                    else:
                        win.click(lx, ly)
            elif et in ("key", "text"):
                win = self.windows[-1] if self.windows else None
                if win is None or win.focus is None:
                    return
                if et == "key":
                    win.focus.on_key(event.get("key", ""))
                else:
                    win.focus.on_text(event.get("text", ""))
                win.dirty = True


def build_agent_desktop(width: int = 960, height: int = 540,
                        on_command: Optional[Callable[[str], None]] = None
                        ) -> Tuple[GuiScreenSource, dict]:
    """The demo agent desktop: a console window (activity log + command
    input), an approval dialog, and a status window.  Returns the source
    plus handles for tests/agents to drive it."""
    src = GuiScreenSource(width, height)

    console = Window("agent console", 40, 40, 560, 360)
    log = console.add(LogView(10, 10, 540, 270))
    log.push(f"agent console ready {time.strftime('%H:%M:%S')}")

    def submit(cmd: str) -> None:
        log.push(f"$ {cmd}")
        if on_command:
            on_command(cmd)

    entry = console.add(TextInput(10, 290, 460, on_submit=submit))
    console.add(Button(480, 290, 70, 20, "Run",
                       on_click=lambda: submit(entry.value)))
    src.add_window(console)

    approvals = Window("approval", 640, 80, 260, 140)
    approvals.add(Label(12, 10, "agent requests approval:"))
    state = {"approved": 0, "denied": 0}

    def approve():
        state["approved"] += 1
        log.push("approval GRANTED")

    def deny():
        state["denied"] += 1
        log.push("approval DENIED")

    approvals.add(Button(20, 60, 90, 26, "Approve", on_click=approve))
    approvals.add(Button(140, 60, 90, 26, "Deny", on_click=deny))
    src.add_window(approvals)

    return src, {
        "log": log, "entry": entry, "console": console,
        "approvals": approvals, "state": state,
    }
