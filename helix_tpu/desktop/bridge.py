"""desktop-bridge: the guest agent that runs INSIDE a sandbox and serves
its GUI to the control plane.

The reference ships ``desktop-bridge`` inside each desktop VM/container —
it owns the guest-side compositor hookup and relays video + input between
guest and host (SURVEY.md §2.3 #38).  Ours is the same shape over our
stack: the guest process hosts the software compositor desktop
(:mod:`helix_tpu.desktop.gui`), encodes frames with the native video
codec, and ships packets up ``/api/v1/desktops/{id}/ws/provider``; input
events from viewers come back down the socket and are applied to the
local seat.  The control plane never executes guest code — it only
relays packets, which is what keeps agent GUI isolation real.

Run inside the sandbox:

    python -m helix_tpu desktop-bridge --control-plane http://cp:8080 \
        [--name my-desktop] [--fps 10] [--api-key ...]

or programmatically: ``DesktopBridge(url).start()`` (tests drive a demo
agent desktop through a real control plane this way).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional


class DesktopBridge:
    def __init__(self, control_plane: str, name: str = "bridged-desktop",
                 fps: float = 10.0, api_key: str = "",
                 width: int = 960, height: int = 540,
                 on_command=None):
        self.control_plane = control_plane.rstrip("/")
        self.name = name
        self.fps = fps
        self.api_key = api_key
        self.width = width
        self.height = height
        self.desktop_id: str = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.connected = threading.Event()
        self.frames_sent = 0

        from helix_tpu.desktop.gui import build_agent_desktop

        self.source, self.handles = build_agent_desktop(
            width, height, on_command=on_command
        )

    def _headers(self) -> dict:
        return (
            {"Authorization": f"Bearer {self.api_key}"}
            if self.api_key else {}
        )

    def register(self) -> str:
        """Create the external desktop on the control plane."""
        import requests

        r = requests.post(
            f"{self.control_plane}/api/v1/desktops",
            json={
                "kind": "external", "name": self.name, "fps": self.fps,
                "codec": "video",
            },
            headers=self._headers(), timeout=10,
        )
        r.raise_for_status()
        self.desktop_id = r.json()["id"]
        return self.desktop_id

    def start(self) -> "DesktopBridge":
        if not self.desktop_id:
            self.register()
        self._thread = threading.Thread(
            target=self._run, name="helix-desktop-bridge", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- the provider loop ---------------------------------------------------
    def _run(self) -> None:
        import asyncio

        asyncio.new_event_loop().run_until_complete(self._session())

    async def _session(self) -> None:
        import asyncio

        import aiohttp

        url = (
            self.control_plane.replace("http://", "ws://")
            .replace("https://", "wss://")
            + f"/api/v1/desktops/{self.desktop_id}/ws/provider"
        )
        backoff = 0.5
        while not self._stop.is_set():
            try:
                async with aiohttp.ClientSession() as http:
                    async with http.ws_connect(
                        url, headers=self._headers(), max_msg_size=0
                    ) as ws:
                        self.connected.set()
                        backoff = 0.5
                        await self._pump(ws)
            except Exception:  # noqa: BLE001 — control plane away: retry
                pass
            finally:
                self.connected.clear()
            if self._stop.is_set():
                return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 15.0)

    async def _pump(self, ws) -> None:
        """Encode+send at fps; apply input events as they arrive."""
        import asyncio

        import aiohttp

        from helix_tpu.desktop.video import VideoEncoder

        enc = VideoEncoder(
            self.width, self.height, quality=70, target_kbps=2000,
            fps=self.fps,
        )
        period = 1.0 / self.fps
        force_kf = True
        next_frame = time.monotonic()
        while not self._stop.is_set() and not ws.closed:
            now = time.monotonic()
            if now >= next_frame:
                frame = self.source.get_frame()
                packet = enc.encode(frame, keyframe=force_kf)
                force_kf = False
                await ws.send_bytes(packet)
                self.frames_sent += 1
                next_frame = now + period
            try:
                msg = await asyncio.wait_for(
                    ws.receive(), timeout=max(next_frame - now, 0.005)
                )
            except asyncio.TimeoutError:
                continue
            if msg.type == aiohttp.WSMsgType.TEXT:
                try:
                    event = json.loads(msg.data)
                except ValueError:
                    continue
                if event.get("type") == "refresh":
                    force_kf = True
                else:
                    self.source.input(event)
            elif msg.type in (
                aiohttp.WSMsgType.CLOSED, aiohttp.WSMsgType.CLOSE,
                aiohttp.WSMsgType.ERROR,
            ):
                return


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="helix-tpu desktop-bridge")
    ap.add_argument("--control-plane", required=True)
    ap.add_argument("--name", default="bridged-desktop")
    ap.add_argument("--fps", type=float, default=10.0)
    ap.add_argument("--api-key", default="")
    args = ap.parse_args(argv)
    bridge = DesktopBridge(
        args.control_plane, name=args.name, fps=args.fps,
        api_key=args.api_key,
    ).start()
    print(f"desktop-bridge serving {bridge.desktop_id} "
          f"-> {args.control_plane}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        bridge.stop()
    return 0
