from helix_tpu.desktop.streamcore import StreamDecoder, StreamEncoder

__all__ = ["StreamEncoder", "StreamDecoder"]
