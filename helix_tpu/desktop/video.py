"""ctypes bindings for the native lossy video codec (native/vidcodec).

The software encoder standing where the reference's hardware ladder sits
(``api/pkg/desktop/ws_stream.go:502-530`` nvenc→vaapi→openh264→x264): a
DCT block codec with I/P frames, 4:2:0 chroma, quantizer rate control.
Same FFI pattern as :mod:`helix_tpu.desktop.streamcore`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "vidcodec",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhxvid.so")
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR], check=True, capture_output=True
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.hxv_encoder_create.restype = ctypes.c_void_p
        lib.hxv_encoder_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_float, ctypes.c_int,
            ctypes.c_float, ctypes.c_int,
        ]
        lib.hxv_encoder_destroy.argtypes = [ctypes.c_void_p]
        lib.hxv_encode.restype = ctypes.c_long
        lib.hxv_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.hxv_encoder_stats.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.hxv_encoder_qscale.restype = ctypes.c_float
        lib.hxv_encoder_qscale.argtypes = [ctypes.c_void_p]
        lib.hxv_decoder_create.restype = ctypes.c_void_p
        lib.hxv_decoder_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.hxv_decoder_destroy.argtypes = [ctypes.c_void_p]
        lib.hxv_decode.restype = ctypes.c_int
        lib.hxv_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long
        ]
        lib.hxv_decoder_frame.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.hxv_decoder_frame.argtypes = [ctypes.c_void_p]
        lib.hxv_decoder_frame_id.restype = ctypes.c_uint32
        lib.hxv_decoder_frame_id.argtypes = [ctypes.c_void_p]
        lib.hxv_decoder_frame_type.restype = ctypes.c_int
        lib.hxv_decoder_frame_type.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class VideoEncoder:
    """Lossy I/P-frame encoder. Frames: uint8 [H, W, 4] BGRA.

    Unlike the lossless tile codec, EVERY call yields a packet (P-frames of
    an unchanged screen are a few bytes of skip flags)."""

    def __init__(self, width: int, height: int, quality: float = 70.0,
                 target_kbps: int = 0, fps: float = 10.0,
                 kf_interval: int = 100):
        self._lib = _load()
        self._h = self._lib.hxv_encoder_create(
            width, height, quality, target_kbps, fps, kf_interval
        )
        if not self._h:
            raise ValueError("bad encoder dimensions")
        self.width = width
        self.height = height

    def encode(self, frame: np.ndarray, keyframe: bool = False) -> bytes:
        frame = np.ascontiguousarray(frame, dtype=np.uint8)
        assert frame.shape == (self.height, self.width, 4), frame.shape
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.hxv_encode(
            self._h, frame.tobytes(), 1 if keyframe else 0, ctypes.byref(out)
        )
        if n <= 0:
            raise RuntimeError(f"encode failed: {n}")
        return ctypes.string_at(out, n)

    @property
    def stats(self) -> dict:
        v = [ctypes.c_uint64() for _ in range(4)]
        self._lib.hxv_encoder_stats(self._h, *[ctypes.byref(x) for x in v])
        return {
            "frames": v[0].value, "bytes_out": v[1].value,
            "coded_mbs": v[2].value, "skipped_mbs": v[3].value,
            "qscale": round(self._lib.hxv_encoder_qscale(self._h), 3),
        }

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hxv_encoder_destroy(self._h)
            self._h = None


class VideoDecoder:
    def __init__(self, width: int, height: int):
        self._lib = _load()
        self._h = self._lib.hxv_decoder_create(width, height)
        if not self._h:
            raise ValueError("bad decoder dimensions")
        self.width = width
        self.height = height

    def decode(self, packet: bytes) -> np.ndarray:
        rc = self._lib.hxv_decode(self._h, packet, len(packet))
        if rc != 0:
            raise RuntimeError(f"decode failed: {rc}")
        return self.frame

    @property
    def frame(self) -> np.ndarray:
        ptr = self._lib.hxv_decoder_frame(self._h)
        buf = ctypes.string_at(ptr, self.width * self.height * 4)
        return np.frombuffer(buf, np.uint8).reshape(
            self.height, self.width, 4
        )

    @property
    def frame_id(self) -> int:
        return self._lib.hxv_decoder_frame_id(self._h)

    @property
    def frame_type(self) -> str:
        return "I" if self._lib.hxv_decoder_frame_type(self._h) == 0 else "P"

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hxv_decoder_destroy(self._h)
            self._h = None
