"""ctypes bindings for the native software compositor (native/compositor).

The scene-graph / composition half of the GUI desktop path — standing
where the reference's headless Wayland compositor sits
(``desktop/wayland-display-core/src/lib.rs:28-40``).  Surfaces are BGRA
buffers owned by in-process apps; the compositor z-orders, alpha-blends,
overlays the cursor, and answers hit tests for input routing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "compositor",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhxcomp.so")
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR], check=True, capture_output=True
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.hxc_create.restype = ctypes.c_void_p
        lib.hxc_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.hxc_destroy.argtypes = [ctypes.c_void_p]
        lib.hxc_surface_create.restype = ctypes.c_uint32
        lib.hxc_surface_create.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int
        ]
        for fn in ("hxc_surface_destroy", "hxc_surface_raise"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.hxc_surface_attach.restype = ctypes.c_int
        lib.hxc_surface_attach.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p
        ]
        lib.hxc_surface_move.restype = ctypes.c_int
        lib.hxc_surface_move.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int
        ]
        lib.hxc_surface_set_visible.restype = ctypes.c_int
        lib.hxc_surface_set_visible.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int
        ]
        lib.hxc_set_cursor.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int
        ]
        lib.hxc_composite.restype = ctypes.c_int
        lib.hxc_composite.argtypes = [
            ctypes.c_void_p, ctypes.c_uint8, ctypes.c_uint8, ctypes.c_uint8
        ]
        lib.hxc_framebuffer.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.hxc_framebuffer.argtypes = [ctypes.c_void_p]
        lib.hxc_hit_test.restype = ctypes.c_uint32
        lib.hxc_hit_test.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.hxc_composite_count.restype = ctypes.c_uint64
        lib.hxc_composite_count.argtypes = [ctypes.c_void_p]
        lib.hxc_surface_count.restype = ctypes.c_int
        lib.hxc_surface_count.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class Compositor:
    """Z-ordered alpha-blending surface compositor with cursor + hit test."""

    def __init__(self, width: int, height: int):
        self._lib = _load()
        self._h = self._lib.hxc_create(width, height)
        if not self._h:
            raise ValueError("bad compositor dimensions")
        self.width = width
        self.height = height
        self._sizes: dict[int, Tuple[int, int]] = {}

    def create_surface(self, width: int, height: int) -> int:
        sid = self._lib.hxc_surface_create(self._h, width, height)
        if not sid:
            raise ValueError("bad surface dimensions")
        self._sizes[sid] = (width, height)
        return sid

    def destroy_surface(self, sid: int) -> None:
        self._lib.hxc_surface_destroy(self._h, sid)
        self._sizes.pop(sid, None)

    def attach(self, sid: int, bgra: np.ndarray) -> None:
        w, h = self._sizes[sid]
        bgra = np.ascontiguousarray(bgra, dtype=np.uint8)
        assert bgra.shape == (h, w, 4), (bgra.shape, (h, w))
        rc = self._lib.hxc_surface_attach(self._h, sid, bgra.tobytes())
        if rc != 0:
            raise KeyError(sid)

    def move(self, sid: int, x: int, y: int) -> None:
        self._lib.hxc_surface_move(self._h, sid, x, y)

    def raise_(self, sid: int) -> None:
        self._lib.hxc_surface_raise(self._h, sid)

    def set_visible(self, sid: int, visible: bool) -> None:
        self._lib.hxc_surface_set_visible(self._h, sid, 1 if visible else 0)

    def set_cursor(self, x: int, y: int, visible: bool = True) -> None:
        self._lib.hxc_set_cursor(self._h, x, y, 1 if visible else 0)

    def composite(self, bg=(18, 18, 24)) -> bool:
        """-> True if the framebuffer changed since the last composite."""
        return bool(
            self._lib.hxc_composite(self._h, bg[2], bg[1], bg[0])
        )

    @property
    def framebuffer(self) -> np.ndarray:
        ptr = self._lib.hxc_framebuffer(self._h)
        buf = ctypes.string_at(ptr, self.width * self.height * 4)
        return np.frombuffer(buf, np.uint8).reshape(
            self.height, self.width, 4
        )

    def hit_test(self, x: int, y: int) -> Optional[Tuple[int, int, int]]:
        """-> (surface_id, local_x, local_y), or None on background."""
        lx = ctypes.c_int()
        ly = ctypes.c_int()
        sid = self._lib.hxc_hit_test(
            self._h, x, y, ctypes.byref(lx), ctypes.byref(ly)
        )
        if not sid:
            return None
        return sid, lx.value, ly.value

    @property
    def composite_count(self) -> int:
        return self._lib.hxc_composite_count(self._h)

    @property
    def surface_count(self) -> int:
        return self._lib.hxc_surface_count(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.hxc_destroy(self._h)
            self._h = None
