"""The scheduler: SLO-tiered admission, per-tenant fairness, preemption policy.

Admission used to be improvised inside ``EngineLoop``: a FIFO list with
global depth/token bounds, and three hardcoded newest-first victim picks
(quarantine fallback x2, preempt-for-pressure).  This module factors
every ordering / shedding / preemption *decision* into one policy
object the loop and engine delegate to (ROADMAP item 5):

- **Priority classes** — ``interactive`` / ``batch``, resolved from the
  ``X-Helix-Class`` request header (forwarded by the control plane for
  authenticated callers only) with a per-profile default.  Dispatch is
  strict priority between classes: while interactive work is queued, no
  batch request admits ahead of it.
- **Per-tenant weighted fair queueing within a class** — deficit-style
  round robin keyed on the PR 7 tenant id: each tenant carries a
  virtual-service counter (admitted prompt tokens normalized by its
  declared weight); the tenant with the least normalized attained
  service dispatches first, so under saturation admitted tokens
  converge to the weight ratio.  Weights live in the profile's ``slo:``
  block (``sched: {tenant_weights: {...}}``).  Bounded per-tenant
  queues turn one flooding tenant's overflow into *per-tenant* 429s
  instead of a global ``queue_full`` that starves everyone.
- **Adaptive chunked-prefill admission budget** — a per-step token
  budget for NEW prefill admissions (the APEX idea: budget host-side
  admission work against the latency target).  The budget halves while
  the fast-window TTFT/queue-wait burn rate (PR 7 violation buckets
  over the PR 3 latency observations) exceeds 1.0 and grows back
  multiplicatively once the burn clears, floored so admission always
  makes progress.
- **Policy-driven victims** — ``preempt_order`` / ``pick_shed_victim``
  implement one ladder everywhere: lowest class first (batch before
  interactive), then the most-over-fair-share tenant (highest
  normalized attained service), then newest admission.

The FIFO policy is the default-off baseline: ``make_scheduler(None)``
returns a scheduler whose reorder is a no-op and whose victim pick is
the historical newest-first, so every pre-scheduler ordering semantic
(and test) is preserved bit-for-bit.

Contract 5 (``tools/lint_metrics.py``): ``helix_sched_*`` metric names
and the scheduler-decision audit reasons below may only be minted by
THIS module — the loop and the OpenAI surface import the shared
constants (the SATURATION_KEYS / TENANT_KEYS importer pattern).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional

from helix_tpu.obs.slo import ANON_TENANT

# priority classes, strict dispatch order (first = most urgent)
INTERACTIVE = "interactive"
BATCH = "batch"
SCHED_CLASSES = (INTERACTIVE, BATCH)

# the priority-class request header: set by clients, forwarded by the
# control plane at dispatch for AUTHENTICATED callers (anonymous traffic
# cannot self-select a class — it gets the profile default)
CLASS_HEADER = "X-Helix-Class"

# Scheduler-decision audit reasons (obs.slo.AdmissionAudit ring).  The
# linter fails the build if these literals appear anywhere but here:
# every other module imports the constants, so the audit vocabulary has
# one owner.
TENANT_QUEUE_FULL = "sched_tenant_queue_full"
PREEMPT_VICTIM = "sched_preempt_victim"
SHED_VICTIM = "sched_shed_victim"
SCHED_AUDIT_REASONS = (
    TENANT_QUEUE_FULL,
    PREEMPT_VICTIM,
    SHED_VICTIM,
)


def sanitize_class(raw, default: str = "") -> str:
    """The one class-header sanitiser: a known class name passes
    through, anything else (missing header, garbage) yields
    ``default``.  Mirrors ``obs.slo.sanitize_tenant`` — a hostile
    header must never mint a metric label value."""
    if isinstance(raw, str):
        v = raw.strip().lower()
        if v in SCHED_CLASSES:
            return v
    return default


def _env_str(name: str, default: str = "") -> str:
    v = os.environ.get(name, "")
    return v.strip() if v.strip() else default


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name, "")
    try:
        return int(v) if v else None
    except ValueError:
        return None


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Scheduler policy knobs, declared in the profile's ``slo:`` block
    (``sched: {...}``) with operator env overrides (``HELIX_SCHED_*``
    beat the profile, same contract as HELIX_SPEC_TOKENS)."""

    # "fifo" preserves the pre-scheduler ordering semantics exactly;
    # "wfq" turns on class tiers + per-tenant weighted fair queueing
    policy: str = "fifo"
    # class assumed when a request carries none
    default_class: str = INTERACTIVE
    # per-tenant DRR weights (share of admitted tokens under
    # saturation); tenants not listed get default_weight
    tenant_weights: dict = dataclasses.field(default_factory=dict)
    default_weight: float = 1.0
    # bounded per-tenant queues: one tenant may hold at most this many
    # queued requests before ITS submissions 429 (None = unbounded)
    max_tenant_queue_depth: Optional[int] = None
    # adaptive per-step prefill-admission token budget: the cap/initial
    # value (None = unbudgeted) and the floor the TTFT-burn feedback
    # loop may shrink it to
    prefill_budget_tokens: Optional[int] = None
    prefill_budget_min_tokens: int = 256
    # how often the budget controller re-reads the burn signal
    adapt_interval_seconds: float = 1.0

    @classmethod
    def from_profile(cls, slo_block: Optional[dict]) -> "SchedConfig":
        """Build from the profile's ``slo: {sched: {...}}`` sub-block,
        with ``HELIX_SCHED_*`` env overrides applied on top."""
        d = {}
        if isinstance(slo_block, dict):
            raw = slo_block.get("sched")
            if isinstance(raw, dict):
                d = raw
        policy = str(d.get("policy", "fifo")).strip().lower()
        policy = _env_str("HELIX_SCHED_POLICY", policy).strip().lower()
        if policy not in ("fifo", "wfq"):
            policy = "fifo"
        default_class = sanitize_class(
            _env_str(
                "HELIX_SCHED_DEFAULT_CLASS",
                str(d.get("default_class", INTERACTIVE)),
            ),
            INTERACTIVE,
        )
        weights = {}
        raw_w = d.get("tenant_weights")
        if isinstance(raw_w, dict):
            for t, w in raw_w.items():
                try:
                    f = float(w)
                except (TypeError, ValueError):
                    continue
                if f > 0 and isinstance(t, str):
                    weights[t] = f
        try:
            default_weight = max(
                1e-6, float(d.get("default_weight", 1.0))
            )
        except (TypeError, ValueError):
            default_weight = 1.0

        def _opt_int(key, env):
            v = _env_int(env)
            if v is None:
                raw = d.get(key)
                try:
                    v = int(raw) if raw is not None else None
                except (TypeError, ValueError):
                    v = None
            return v if v is None or v > 0 else None

        budget = _opt_int(
            "prefill_budget_tokens", "HELIX_SCHED_PREFILL_BUDGET"
        )
        budget_min = _opt_int(
            "prefill_budget_min_tokens", "HELIX_SCHED_PREFILL_BUDGET_MIN"
        ) or 256
        depth = _opt_int(
            "max_tenant_queue_depth", "HELIX_SCHED_TENANT_QUEUE_DEPTH"
        )
        return cls(
            policy=policy,
            default_class=default_class,
            tenant_weights=weights,
            default_weight=default_weight,
            max_tenant_queue_depth=depth,
            prefill_budget_tokens=budget,
            prefill_budget_min_tokens=budget_min,
        )


class FifoScheduler:
    """The default-off baseline: every decision matches the
    pre-scheduler behaviour (FIFO order, newest-first victims, no
    per-step budget) so existing ordering semantics — and every test
    that depends on them — are preserved.  Also the shared bookkeeping
    (per-class admission counters, metrics surface) the WFQ subclass
    builds on."""

    name = "fifo"
    #: True when the policy actually reorders/budgets (the loop skips
    #: the per-pass scheduler work entirely for the baseline)
    active = False

    def __init__(self, cfg: Optional[SchedConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or SchedConfig()
        self.clock = clock
        self._lock = threading.Lock()
        # per-class lifetime admission counters (on_admit hook)
        self.admitted_requests = {c: 0 for c in SCHED_CLASSES}
        self.admitted_tokens = {c: 0 for c in SCHED_CLASSES}
        # last-observed queue depth per class (stamped by reorder)
        self._class_depth = {c: 0 for c in SCHED_CLASSES}
        self.reorders = 0
        self.tenant_queue_sheds = 0   # per-tenant-bound 429s
        self.preempt_victims = {c: 0 for c in SCHED_CLASSES}
        self.shed_victims = {c: 0 for c in SCHED_CLASSES}
        # adaptive prefill budget state (None under the FIFO baseline,
        # whose prefill_budget() never applies one — the gauge must not
        # claim a budget the policy will never enforce)
        self._budget = (
            self.cfg.prefill_budget_tokens if self.active else None
        )
        self._budget_checked = 0.0
        self.budget_shrinks = 0
        self.budget_grows = 0

    # -- identity ------------------------------------------------------------

    def request_class(self, req) -> str:
        """The request's effective priority class (its stamped class,
        else the profile default)."""
        return sanitize_class(
            getattr(req, "sched_class", ""), self.cfg.default_class
        )

    def weight(self, tenant: str) -> float:
        return max(
            1e-6,
            float(
                self.cfg.tenant_weights.get(
                    tenant, self.cfg.default_weight
                )
            ),
        )

    # -- admission -----------------------------------------------------------

    def tenant_overflow(self, tenant: str, tenant_depth: int) -> bool:
        """Would admitting one more request from ``tenant`` exceed its
        bounded queue?  (The caller formats the 429 and owns the audit
        record; this only answers the policy question.)"""
        bound = self.cfg.max_tenant_queue_depth
        return bound is not None and tenant_depth >= bound

    def note_tenant_shed(self) -> None:
        self.tenant_queue_sheds += 1

    def note_admitted(self, req) -> None:
        """Admission-confirm hook (``Engine.on_admit``): charges the
        request's prefill cost to its class counters (and, in the WFQ
        subclass, its tenant's fair-share account)."""
        cls = self.request_class(req)
        cost = max(
            1,
            len(req.prompt_tokens) - getattr(req, "cached_tokens", 0),
        )
        with self._lock:
            self.admitted_requests[cls] += 1
            self.admitted_tokens[cls] += cost
            self._charge_locked(cls, getattr(req, "tenant", ANON_TENANT),
                                cost)

    def _charge_locked(self, cls: str, tenant: str, cost: int) -> None:
        pass   # fair-share accounting lives in the WFQ subclass

    # -- ordering ------------------------------------------------------------

    def reorder(self, waiting: list) -> None:
        """FIFO: leave the queue exactly as submitted.  Like the WFQ
        override, safe to call with a device step in flight (the async
        loop's pending-dispatch contract): reads only the wait queue."""

    # -- per-step prefill budget --------------------------------------------

    def prefill_budget(self, slo=None) -> Optional[int]:
        """Token budget for NEW prefill admissions this step (None =
        unbudgeted — the FIFO baseline and unconfigured WFQ)."""
        return None

    # -- victim selection ----------------------------------------------------

    def pick_shed_victim(self, cands: list):
        """The request to sacrifice when the loop must shed one of
        ``cands`` (oldest-admission-first order).  Baseline: newest —
        the historical hardcoded choice."""
        return cands[-1] if cands else None

    def preempt_order(self, cands: list) -> list:
        """Preference-ordered preemption victims for
        ``Engine.preempt_for_pressure``.  The baseline returns [] so
        the engine keeps its builtin newest-admission/largest-footprint
        pick."""
        return []

    def note_preempt_victim(self, req) -> None:
        self.preempt_victims[self.request_class(req)] += 1

    def note_shed_victim(self, req) -> None:
        self.shed_victims[self.request_class(req)] += 1

    # -- observability -------------------------------------------------------

    def collect(self, c, lbl: dict) -> None:
        """Scrape-time ``helix_sched_*`` samples — contract 5: this
        module is the only legal emitter of the family."""
        c.gauge("helix_sched_wfq_enabled", 1 if self.active else 0, lbl)
        c.gauge(
            "helix_sched_prefill_budget_tokens", self._budget or 0, lbl
        )
        c.counter(
            "helix_sched_prefill_budget_shrinks_total",
            self.budget_shrinks, lbl,
        )
        c.counter(
            "helix_sched_prefill_budget_grows_total",
            self.budget_grows, lbl,
        )
        c.counter("helix_sched_reorders_total", self.reorders, lbl)
        c.counter(
            "helix_sched_tenant_queue_sheds_total",
            self.tenant_queue_sheds, lbl,
        )
        for cls in SCHED_CLASSES:
            cl = {**lbl, "class": cls}
            c.counter(
                "helix_sched_admitted_requests_total",
                self.admitted_requests[cls], cl,
            )
            c.counter(
                "helix_sched_admitted_tokens_total",
                self.admitted_tokens[cls], cl,
            )
            c.gauge(
                "helix_sched_queue_depth", self._class_depth[cls], cl
            )
            c.counter(
                "helix_sched_preempt_victims_total",
                self.preempt_victims[cls], cl,
            )
            c.counter(
                "helix_sched_shed_victims_total",
                self.shed_victims[cls], cl,
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "policy": self.name,
                "default_class": self.cfg.default_class,
                "admitted_requests": dict(self.admitted_requests),
                "admitted_tokens": dict(self.admitted_tokens),
                "queue_depth": dict(self._class_depth),
                "tenant_queue_sheds": self.tenant_queue_sheds,
                "preempt_victims": dict(self.preempt_victims),
                "shed_victims": dict(self.shed_victims),
                "prefill_budget_tokens": self._budget,
                "budget_shrinks": self.budget_shrinks,
                "budget_grows": self.budget_grows,
                "reorders": self.reorders,
            }


class WFQScheduler(FifoScheduler):
    """Strict-priority classes + per-tenant deficit-style weighted fair
    queueing.

    Fair-share state is one number per (class, tenant): the tenant's
    *normalized attained service* — admitted prompt tokens divided by
    its weight.  Ordering dispatches the tenant with the LEAST
    normalized service first (ties broken by queue arrival), which is
    the deficit-round-robin invariant expressed as a running account:
    every admission charges ``cost/weight``, so under saturation the
    per-tenant admitted-token ratio converges to the weight ratio.
    Charging happens only on CONFIRMED admissions (the ``Engine.on_admit``
    hook), so a reorder pass that the engine could not act on (resource
    block) leaves no trace and cannot under-serve anyone.

    A per-class *virtual floor* tracks the minimum normalized service
    among recently queued tenants; a newly active tenant starts at the
    floor instead of zero, so returning after an idle hour does not
    grant a monopoly burst, and the state stays prunable (entries at or
    below the floor with nothing queued carry no information).
    """

    name = "wfq"
    active = True

    # bound on the fair-share dict: beyond this, idle entries at the
    # floor are pruned (they are reconstructible as "floor" by
    # definition)
    _MAX_TENANTS = 4096

    def __init__(self, cfg: Optional[SchedConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(cfg, clock)
        # (class) -> tenant -> normalized attained service
        self._vsrv: dict = {c: {} for c in SCHED_CLASSES}
        self._vfloor = {c: 0.0 for c in SCHED_CLASSES}

    # -- fair-share account --------------------------------------------------

    def _charge_locked(self, cls: str, tenant: str, cost: int) -> None:
        vs = self._vsrv[cls]
        base = max(vs.get(tenant, 0.0), self._vfloor[cls])
        vs[tenant] = base + cost / self.weight(tenant)
        if len(vs) > self._MAX_TENANTS:
            floor = self._vfloor[cls]
            for t in [t for t, v in vs.items() if v <= floor]:
                del vs[t]

    def normalized_service(self, cls: str, tenant: str) -> float:
        with self._lock:
            return max(
                self._vsrv[cls].get(tenant, 0.0), self._vfloor[cls]
            )

    # -- ordering ------------------------------------------------------------

    def reorder(self, waiting: list) -> None:
        """Rewrite ``waiting`` in place into dispatch order: interactive
        before batch (strict priority), and within a class the DRR
        interleave — repeatedly take the head of the tenant with the
        least normalized attained service, charging a *simulated* copy
        of the account so one pass emits the whole fair interleave.
        FIFO order within a tenant is preserved.  Runs on the engine
        thread (the list's owner); an in-place slice assignment keeps
        concurrent GIL-atomic ``len()`` / ``list()`` readers safe.

        Pending-dispatch contract (ISSUE 13): the async engine loop may
        invoke this while a device step is still in flight, so a reorder
        pass must read ONLY the wait queue and the burn-rate accounts —
        never slot state, page occupancy or anything else the in-flight
        step's reconcile will rewrite.  The loop reconciles before the
        dispatch that acts on the new order, so the order can never be
        applied against a stale resource picture."""
        if len(waiting) < 2:
            # nothing to reorder, but keep the per-class depth gauges
            # live — a burst's stamp must not outlast the burst
            counts = {c: 0 for c in SCHED_CLASSES}
            for req in waiting:
                if not req.finished:
                    counts[self.request_class(req)] += 1
            with self._lock:
                self._class_depth = counts
            return
        groups: dict = {c: {} for c in SCHED_CLASSES}
        arrival: dict = {c: {} for c in SCHED_CLASSES}
        dropped = 0
        for i, req in enumerate(waiting):
            if req.finished:
                dropped += 1   # purged: a finished request owns no slot
                continue
            cls = self.request_class(req)
            t = getattr(req, "tenant", ANON_TENANT)
            groups[cls].setdefault(t, []).append(req)
            arrival[cls].setdefault(t, i)
        with self._lock:
            sim = {
                c: {
                    t: max(self._vsrv[c].get(t, 0.0), self._vfloor[c])
                    for t in groups[c]
                }
                for c in SCHED_CLASSES
            }
            # advance the virtual floor to the least service among
            # currently queued tenants: future arrivals start here
            for c in SCHED_CLASSES:
                if sim[c]:
                    self._vfloor[c] = max(
                        self._vfloor[c], min(sim[c].values())
                    )
            for c in SCHED_CLASSES:
                self._class_depth[c] = sum(
                    len(q) for q in groups[c].values()
                )
        order = []
        for cls in SCHED_CLASSES:
            queues = groups[cls]
            while queues:
                t = min(
                    queues,
                    key=lambda u: (sim[cls][u], arrival[cls][u]),
                )
                req = queues[t].pop(0)
                order.append(req)
                sim[cls][t] += max(1, len(req.prompt_tokens)) / (
                    self.weight(t)
                )
                if not queues[t]:
                    del queues[t]
        if dropped or any(
            a is not b for a, b in zip(order, waiting)
        ):
            waiting[:] = order
        self.reorders += 1

    # -- adaptive prefill budget --------------------------------------------

    def prefill_budget(self, slo=None) -> Optional[int]:
        """Current per-step prefill-admission token budget, adapted to
        the fast-window latency burn: >1.0 (the error budget is being
        spent faster than it accrues) halves the budget toward the
        floor; a healthy burn (<0.5) grows it back 1.25x toward the
        cap.  Re-evaluated at most once per ``adapt_interval_seconds``;
        with no declared SLO targets the burn reads 0.0 and the budget
        rests at the cap."""
        cap = self.cfg.prefill_budget_tokens
        if cap is None:
            return None
        now = self.clock()
        if (
            self._budget is not None
            and now - self._budget_checked < self.cfg.adapt_interval_seconds
        ):
            return self._budget
        self._budget_checked = now
        burn = 0.0
        if slo is not None:
            try:
                burn = slo.latency_fast_burn()
            except Exception:  # noqa: BLE001 — feedback is advisory
                burn = 0.0
        cur = self._budget if self._budget is not None else cap
        floor = min(cap, max(1, self.cfg.prefill_budget_min_tokens))
        if burn > 1.0:
            nxt = max(floor, cur // 2)
            if nxt < cur:
                self.budget_shrinks += 1
            cur = nxt
        elif burn < 0.5 and cur < cap:
            cur = min(cap, int(cur * 1.25) + 1)
            self.budget_grows += 1
        self._budget = cur
        return cur

    # -- victim selection ----------------------------------------------------

    def _victim_key(self, cands: list):
        """The one victim ladder: lowest class (batch sacrificed before
        interactive), then most-over-fair-share tenant (highest
        normalized attained service), then newest — judged by actual
        admission recency (submit time for never-admitted requests),
        NOT list position: preempt candidates arrive in slot order and
        shed candidates in dispatch order, neither of which says who is
        newest."""
        with self._lock:
            vsrv = {
                c: dict(self._vsrv[c]) for c in SCHED_CLASSES
            }
            floor = dict(self._vfloor)

        def key(pair):
            i, req = pair
            cls = self.request_class(req)
            t = getattr(req, "tenant", ANON_TENANT)
            over = max(vsrv[cls].get(t, 0.0), floor[cls])
            recency = (
                req.admitted_time
                if getattr(req, "admitted_time", None) is not None
                else getattr(req, "submit_time", 0.0)
            )
            # batch ranks above interactive as a victim
            return (1 if cls == BATCH else 0, over, recency, i)

        return key

    def pick_shed_victim(self, cands: list):
        if not cands:
            return None
        key = self._victim_key(cands)
        return max(enumerate(cands), key=key)[1]

    def preempt_order(self, cands: list) -> list:
        key = self._victim_key(cands)
        return [
            req
            for _i, req in sorted(
                enumerate(cands), key=key, reverse=True
            )
        ]


def make_scheduler(cfg=None) -> FifoScheduler:
    """Policy factory: a ``SchedConfig`` (or profile ``slo:`` dict, or
    None) to the scheduler the engine loop delegates to.  Anything
    short of an explicit ``policy: wfq`` yields the FIFO baseline."""
    if cfg is None:
        cfg = SchedConfig.from_profile(None)
    elif isinstance(cfg, dict):
        cfg = SchedConfig.from_profile(cfg)
    if cfg.policy == "wfq":
        return WFQScheduler(cfg)
    return FifoScheduler(cfg)
