"""Engine driver thread: bridges async HTTP handlers to the step loop.

The reference streams SSE chunks from vLLM through hydra and a NATS response
queue back to the waiting HTTP handler (``SURVEY.md`` §3.2).  In-process the
same shape holds with cheaper parts: one dedicated thread owns the Engine
(all JAX dispatch stays single-threaded), handlers submit via a thread-safe
inbox and receive per-request events through callbacks marshalled onto their
asyncio loop.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Optional

from helix_tpu.engine.engine import Engine, FinishReason, Request


@dataclasses.dataclass
class TokenEvent:
    request_id: str
    token_id: int
    finished: bool
    finish_reason: Optional[str] = None
    error: Optional[str] = None


class EngineLoop:
    def __init__(self, engine: Engine, name: str = "engine",
                 max_queue_seconds: float = 600.0):
        self.engine = engine
        self.name = name
        self.max_queue_seconds = max_queue_seconds
        self._inbox: "queue.Queue" = queue.Queue()
        self._subscribers: dict[str, Callable[[TokenEvent], None]] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_reap = time.monotonic()
        # serving metrics (scraped by /metrics)
        self.steps = 0
        self.started_at = time.monotonic()

    # -- called from any thread --------------------------------------------

    def submit(self, req: Request, on_event: Callable[[TokenEvent], None]):
        # reject unservable requests on the caller's thread with a clean
        # event — the engine thread must never die on bad input
        err = self.engine.validate_request(req)
        if err:
            on_event(
                TokenEvent(
                    request_id=req.id, token_id=-1, finished=True,
                    finish_reason="error", error=err,
                )
            )
            return
        self._inbox.put((req, on_event))
        self._wake.set()

    def abort(self, request_id: str):
        self._inbox.put((request_id, None))
        self._wake.set()

    def stats(self) -> dict:
        """Counter snapshot for /metrics (reads of plain ints are atomic
        under the GIL, so no lock against the engine thread is needed)."""
        eng = self.engine
        return {
            "steps": self.steps,
            "prefill_tokens": eng.num_prefill_tokens,
            "decode_tokens": eng.num_decode_tokens,
            "mixed_steps": getattr(eng, "num_mixed_steps", 0),
            "moe_dropped_tokens": getattr(eng, "moe_dropped_tokens", 0),
            "waiting": len(eng.waiting),
            "active_slots": sum(1 for s in eng.slots if s is not None),
            "free_pages": eng.allocator.free_pages,
            "kv_cache_dtype": eng.cache_cfg.dtype,
        }

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name=f"helix-engine-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, join: bool = True):
        self._stop.set()
        self._wake.set()
        if join and self._thread is not None:
            self._thread.join(timeout=30)

    # -- engine thread ------------------------------------------------------

    def _drain_inbox(self):
        while True:
            try:
                item, on_event = self._inbox.get_nowait()
            except queue.Empty:
                return
            if on_event is None:  # abort
                self.engine.abort(item)
                self._subscribers.pop(item, None)
            else:
                try:
                    self.engine.add_request(item)
                    self._subscribers[item.id] = on_event
                except Exception as e:  # noqa: BLE001 — thread must survive
                    on_event(
                        TokenEvent(
                            request_id=item.id, token_id=-1, finished=True,
                            finish_reason="error", error=str(e),
                        )
                    )

    def _run(self):
        while not self._stop.is_set():
            self._drain_inbox()
            if time.monotonic() - self._last_reap > 10.0:
                self._last_reap = time.monotonic()
                for req in self.engine.reap_stuck(self.max_queue_seconds):
                    cb = self._subscribers.pop(req.id, None)
                    if cb:
                        cb(
                            TokenEvent(
                                request_id=req.id, token_id=-1,
                                finished=True, finish_reason="error",
                                error="request timed out in queue",
                            )
                        )
            if not self.engine.has_work():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                emitted = self.engine.step()
            except Exception as e:  # noqa: BLE001 — fail requests, not the loop
                import traceback

                traceback.print_exc()
                for req in list(self.engine.slots) + list(self.engine.waiting):
                    if req is None:
                        continue
                    self.engine.abort(req.id)
                    cb = self._subscribers.pop(req.id, None)
                    if cb:
                        cb(
                            TokenEvent(
                                request_id=req.id, token_id=-1,
                                finished=True, finish_reason="error",
                                error=f"engine step failed: {e}",
                            )
                        )
                continue
            self.steps += 1
            for req, token in emitted:
                cb = self._subscribers.get(req.id)
                if cb is None:
                    continue
                cb(
                    TokenEvent(
                        request_id=req.id,
                        token_id=token,
                        finished=req.finished,
                        finish_reason=(
                            req.finish_reason.value if req.finish_reason else None
                        ),
                    )
                )
                if req.finished:
                    self._subscribers.pop(req.id, None)
