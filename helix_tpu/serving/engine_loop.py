"""Engine driver thread: bridges async HTTP handlers to the step loop.

The reference streams SSE chunks from vLLM through hydra and a NATS response
queue back to the waiting HTTP handler (``SURVEY.md`` §3.2).  In-process the
same shape holds with cheaper parts: one dedicated thread owns the Engine
(all JAX dispatch stays single-threaded), handlers submit via a thread-safe
inbox and receive per-request events through callbacks marshalled onto their
asyncio loop.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Optional

from helix_tpu.engine.engine import Engine, FinishReason, Request


@dataclasses.dataclass
class TokenEvent:
    request_id: str
    token_id: int
    finished: bool
    finish_reason: Optional[str] = None


class EngineLoop:
    def __init__(self, engine: Engine, name: str = "engine"):
        self.engine = engine
        self.name = name
        self._inbox: "queue.Queue" = queue.Queue()
        self._subscribers: dict[str, Callable[[TokenEvent], None]] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serving metrics (scraped by /metrics)
        self.steps = 0
        self.started_at = time.monotonic()

    # -- called from any thread --------------------------------------------

    def submit(self, req: Request, on_event: Callable[[TokenEvent], None]):
        self._inbox.put((req, on_event))
        self._wake.set()

    def abort(self, request_id: str):
        self._inbox.put((request_id, None))
        self._wake.set()

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name=f"helix-engine-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, join: bool = True):
        self._stop.set()
        self._wake.set()
        if join and self._thread is not None:
            self._thread.join(timeout=30)

    # -- engine thread ------------------------------------------------------

    def _drain_inbox(self):
        while True:
            try:
                item, on_event = self._inbox.get_nowait()
            except queue.Empty:
                return
            if on_event is None:  # abort
                self.engine.abort(item)
                self._subscribers.pop(item, None)
            else:
                self._subscribers[item.id] = on_event
                self.engine.add_request(item)

    def _run(self):
        while not self._stop.is_set():
            self._drain_inbox()
            if not self.engine.has_work():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            emitted = self.engine.step()
            self.steps += 1
            for req, token in emitted:
                cb = self._subscribers.get(req.id)
                if cb is None:
                    continue
                cb(
                    TokenEvent(
                        request_id=req.id,
                        token_id=token,
                        finished=req.finished,
                        finish_reason=(
                            req.finish_reason.value if req.finish_reason else None
                        ),
                    )
                )
                if req.finished:
                    self._subscribers.pop(req.id, None)
