"""Engine driver thread: bridges async HTTP handlers to the step loop.

The reference streams SSE chunks from vLLM through hydra and a NATS response
queue back to the waiting HTTP handler (``SURVEY.md`` §3.2).  In-process the
same shape holds with cheaper parts: one dedicated thread owns the Engine
(all JAX dispatch stays single-threaded), handlers submit via a thread-safe
inbox and receive per-request events through callbacks marshalled onto their
asyncio loop.

Robustness (ISSUE 2): a failing ``engine.step()`` no longer aborts every
in-flight request.  The loop retries the step once (transient faults), then
quarantines the not-yet-emitting requests and bisects them back in to find
the poisoned one(s) — only the culprit gets an error event, everything else
keeps generating.  Admission is bounded (queue depth / queued-token budget)
so overload sheds immediately with a clean ``queue_full`` error (HTTP 429)
instead of rotting toward the queue timeout, and ``stop(drain=...)`` drains
in-flight work before the thread exits.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Callable, Optional

from helix_tpu.engine.engine import (
    Engine,
    FinishReason,
    Request,
    SnapshotError,
)
from helix_tpu.obs import EngineLoopObs, FlightRecorder, RateTracker
from helix_tpu.obs import trace as obs_trace
from helix_tpu.obs.flight import SATURATION_KEYS
from helix_tpu.obs.slo import ANON_TENANT, SLOObserver
from helix_tpu.serving.sched import (
    PREEMPT_VICTIM,
    SHED_VICTIM,
    TENANT_QUEUE_FULL,
    make_scheduler,
)
from helix_tpu.testing import faults

log = logging.getLogger("helix.engine")

# error-message prefixes the HTTP layer maps onto statuses (429 / 503);
# keep in sync with openai_api._engine_error_response
QUEUE_FULL = "queue_full"
SHUTTING_DOWN = "shutting_down"
# typed KV-exhaustion shed (ISSUE 6): a request that cannot claim pages
# within the admission deadline — or arrives while admission has already
# been KV-starved longer than the deadline — gets a clean 503 +
# Retry-After instead of silently aging in the queue
KV_EXHAUSTED = "kv_exhausted"


@dataclasses.dataclass
class TokenEvent:
    request_id: str
    token_id: int
    finished: bool
    finish_reason: Optional[str] = None
    error: Optional[str] = None


class _EmissionStage:
    """Bounded, ordered, off-critical-path token emission (ISSUE 13).

    The async loop hands each step's emitted batch to this stage so SSE
    subscriber callbacks and per-tenant SLO accounting never sit between
    a device completion and the next dispatch.  One worker thread keeps
    per-request event order; the bounded queue applies backpressure (a
    full queue blocks the engine thread, so the pipeline never runs more
    than ``depth`` batches ahead of the slowest subscriber).  When not
    started (synchronous loop), ``push`` degrades to a direct call on
    the caller's thread — exactly the pre-pipeline behaviour."""

    def __init__(self, sink: Callable, obs_hist, depth: int = 8):
        self._sink = sink
        self._obs = obs_hist
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._thread: Optional[threading.Thread] = None
        self.started = False
        self.batches = 0

    def start(self, name: str = "emit") -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"helix-emit-{name}", daemon=True
        )
        self.started = True
        self._thread.start()

    def push(self, emitted) -> None:
        if not emitted:
            return
        if not self.started:
            t0 = time.monotonic()
            self._sink(emitted)
            self._obs.observe(time.monotonic() - t0)
            return
        self._q.put(emitted)   # blocks when full: bounded backpressure
        self.batches += 1

    def flush(self) -> None:
        """Block until every pushed batch has been delivered — THE
        ordering barrier the engine thread takes before any terminal
        event (evict/shed/drain/quarantine), so an error frame can never
        overtake that request's queued tokens."""
        if self.started:
            self._q.join()

    def stop(self) -> None:
        if not self.started:
            return
        self._q.put(None)
        self.started = False
        if self._thread is not None:
            self._thread.join(timeout=10)

    def depth(self) -> int:
        return self._q.qsize()

    def _run(self) -> None:
        while True:
            batch = self._q.get()
            try:
                if batch is None:
                    return
                t0 = time.monotonic()
                try:
                    self._sink(batch)
                except Exception:  # noqa: BLE001 — a subscriber bug must not kill emission
                    log.exception("emission stage sink failed")
                self._obs.observe(time.monotonic() - t0)
            finally:
                self._q.task_done()


@dataclasses.dataclass
class _ImportItem:
    """An inbox entry carrying a migrated-in request snapshot (ISSUE 11):
    ``engine.import_request`` must run on the engine thread, so the HTTP
    handler enqueues here like a submit.  ``on_result(err, code)`` fires
    once validation settles (None = accepted) so the import endpoint can
    answer with a typed status instead of a blind 200."""

    snapshot: object
    on_event: Callable[[TokenEvent], None]
    on_result: Optional[Callable] = None


class EngineLoop:
    def __init__(self, engine: Engine, name: str = "engine",
                 max_queue_seconds: float = 600.0,
                 max_queue_depth: Optional[int] = None,
                 max_queued_tokens: Optional[int] = None,
                 admission_timeout: Optional[float] = None,
                 preempt_stall_seconds: Optional[float] = None,
                 slo_targets: Optional[dict] = None,
                 tenant_top_k: Optional[int] = None,
                 burn_windows: Optional[tuple] = None,
                 sched_config=None):
        self.engine = engine
        self.name = name
        self.max_queue_seconds = max_queue_seconds
        # admission bounds: None = unbounded (seed behaviour).  Depth
        # counts requests waiting for a slot (inbox + engine wait queue);
        # tokens bound the queued prefill work so one burst of 32k
        # prompts can't hide behind a small depth bound.
        self.max_queue_depth = max_queue_depth
        self.max_queued_tokens = max_queued_tokens
        # KV-pressure degradation ladder (ISSUE 6), rungs from mildest:
        # spill (engine-internal, always on with a host tier) ->
        # preempt-by-swap after admission has stalled preempt_stall_
        # seconds -> typed kv_exhausted shed once a request has waited
        # admission_timeout (and fast-fail of NEW arrivals while the
        # engine is that starved).  None disables a rung.
        self.admission_timeout = admission_timeout
        self.preempt_stall_seconds = preempt_stall_seconds
        self._stall_since: Optional[float] = None
        self._admit_seen = 0            # num_admitted at last progress
        self._last_preempt_at = 0.0
        self.kv_exhausted_sheds = 0     # typed 503s issued
        self._inbox: "queue.Queue" = queue.Queue()
        self._pending = 0          # submitted, not yet drained to the engine
        self._pending_tokens = 0
        # RLock: submit holds it across check+enqueue so the draining
        # flag flip in stop() can be made atomic against in-flight submits
        self._admission_lock = threading.RLock()
        self._subscribers: dict[str, Callable[[TokenEvent], None]] = {}
        self._admit_order: list[str] = []   # request ids, admission order
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = False
        self._drain_deadline = 0.0
        self._thread: Optional[threading.Thread] = None
        self._last_reap = time.monotonic()
        self._consec_failures = 0
        self._barren_rounds = 0   # quarantine rounds that found no culprit
        # serving metrics (scraped by /metrics)
        self.steps = 0
        self.step_failures = 0
        self.step_retries = 0
        self.quarantine_evictions = 0
        self.shed_requests = 0
        self.started_at = time.monotonic()
        # latency histograms (TTFT / queue wait / inter-token / step) —
        # standalone obs families; the runner's /metrics folds them in
        # with a model label at scrape time
        self.obs = EngineLoopObs()
        # flight recorder: bounded per-step ring + anomaly watchdog
        # (host-side counter deltas only — nothing enters the jitted
        # path), served at GET /v1/debug/flight
        self.flight = FlightRecorder()
        # goodput tokens/s over a trailing window (scraped by /metrics
        # and the heartbeat saturation summary)
        self._tps = RateTracker()
        # per-tenant SLO observability (ISSUE 7): bounded top-K tenant
        # accounting (+ __other__ fold), multi-window burn rates against
        # the profile-declared SLO targets, and the admission audit ring
        # served at GET /v1/debug/admissions
        self.slo = SLOObserver(
            targets=slo_targets, top_k=tenant_top_k, windows=burn_windows
        )
        self._trace = obs_trace.default_store()
        self._first_emit: dict[str, float] = {}   # req id -> first-token t
        self._last_emit: dict[str, float] = {}    # req id -> last-token t
        # the scheduler (ISSUE 9, serving/sched.py): owns every
        # ordering / per-tenant-bound / victim decision.  The FIFO
        # baseline (no sched_config, or policy: fifo) preserves the
        # pre-scheduler semantics exactly.  Multihost leaders run the
        # scheduler like any engine: its decisions (budget, victim
        # order, admission order) replicate as step-plan data.
        self.sched = make_scheduler(sched_config)
        self._sched_active = self.sched.active
        # per-tenant inbox depth (admission lock); the per-tenant bound
        # adds the engine-side wait-queue count on demand
        self._pending_by_tenant: dict[str, int] = {}
        # asynchronous pipelined loop (ISSUE 13): dispatch step N+1
        # against predicted post-step state while step N executes, and
        # emit through the bounded off-thread stage.  Requires the
        # dispatch/complete engine split.  Multihost leaders pipeline
        # too: plan N+1 publishes at dispatch, so the broadcast rides
        # the same overlap and followers apply it while device step N
        # completes.
        self.async_enabled = (
            bool(getattr(
                getattr(engine, "cfg", None), "enable_async_loop", False
            ))
            and hasattr(engine, "step_dispatch")
        )
        self.pipelined_steps = 0    # steps dispatched while one was in flight
        self._emit_stage = _EmissionStage(
            self._deliver, self.obs.emit_seconds
        )
        # host-side device-busy watermark: the last completion's return
        # time.  A dispatch that happens with nothing in flight charges
        # the gap since this watermark as device idle (idle_gap_s).
        self._device_busy_until = 0.0
        # cross-runner migration (ISSUE 11): when set, requests still
        # unfinished at the drain deadline are snapshotted and handed to
        # this callable (wire dict -> accepting peer id; raises on
        # failure) instead of shed — the node agent wires a PeerShipper
        # here during graceful shutdown, tests wire a direct stub
        self.exporter = None
        self.migration_failures = 0   # failed exports/ships/imports
        # disaggregated prefill/decode (ISSUE 14): request ids staged
        # for export-at-prefill-completion -> callback(kind, wire).
        # Written by HTTP handler threads (stage/unstage), consumed on
        # the engine thread (_disagg_tick) — dict ops are GIL-atomic.
        self._disagg_cb: dict = {}
        self.disagg_exports = 0       # prefill snapshots handed to a shipper
        engine.on_admit = self._note_admit
        if self._sched_active:
            engine.victim_policy = self.sched.preempt_order

    # -- called from any thread --------------------------------------------

    def check_admission(
        self, prompt_len: int, count_shed: bool = False,
        tenant: str = ANON_TENANT, trace_id: str = "",
        request_id: str = "",
    ) -> Optional[str]:
        """Would a submit of this size be shed right now?  Returns the
        error string (``queue_full: ...`` / ``shutting_down: ...``) or
        None.  HTTP handlers pre-check so streaming requests get a clean
        429/503 status instead of an SSE error frame; callers that act on
        the verdict (actually shed the request) pass ``count_shed=True``
        so the metric — and the per-tenant accounting + admission audit
        entry — is owned here, in one place."""
        hit = self._check_admission(prompt_len, tenant)
        if hit is None:
            return None
        reason, err = hit
        if count_shed:
            self.shed_requests += 1
            kv = reason == "kv_exhausted"
            if kv:
                self.kv_exhausted_sheds += 1
            if reason == TENANT_QUEUE_FULL:
                # a scheduler decision: the flooding tenant overflowed
                # ITS bounded queue — everyone else keeps admitting
                self.sched.note_tenant_shed()
            self.slo.note_shed(tenant, kv_exhausted=kv)
            self._audit(
                reason, tenant=tenant, trace_id=trace_id,
                request_id=request_id, detail=err,
            )
        return err

    def _audit(self, reason: str, tenant: str = ANON_TENANT,
               trace_id: str = "", request_id: str = "",
               detail: str = "") -> None:
        """One admission-decision audit record, stamped with the queue
        state at the moment of the decision.  O(1) reads only — sheds
        spike exactly when the node is saturated, so the rejection path
        must not walk the wait queue per record."""
        eng = self.engine
        self.slo.audit.record(
            reason, tenant=tenant, trace_id=trace_id,
            request_id=request_id, detail=detail,
            queue_depth=self.queue_depth(),
            kv_pages_free=eng.allocator.free_pages,
            slots_busy=sum(1 for s in eng.slots if s is not None),
            preempted_parked=len(getattr(eng, "preempted", ())),
        )

    def queue_depth(self) -> int:
        """Requests awaiting a slot (inbox + engine wait queue) — THE
        queue-depth formula: the admission bound, audit records,
        saturation summary and flight records all read this one helper
        (the ``queued_tokens()`` treatment).  O(1) GIL-atomic reads,
        safe from any thread."""
        return self._pending + len(self.engine.waiting)

    def queued_tokens(self) -> int:
        """Prompt tokens awaiting admission (inbox + engine wait queue)
        — the quantity ``max_queued_tokens`` bounds and the
        ``helix_queued_tokens`` gauge reports.  Finished (aborted while
        queued) requests no longer hold KV work, so they don't count.
        GIL-atomic reads, safe from any thread."""
        return self._pending_tokens + sum(
            len(r.prompt_tokens)
            for r in list(self.engine.waiting)
            if not r.finished
        )

    def _tenant_depth(self, tenant: str) -> int:
        """Queued requests for ONE tenant (inbox + engine wait queue).
        Only computed when the scheduler's per-tenant bound is
        configured — an O(queue) walk like ``queued_tokens``."""
        return self._pending_by_tenant.get(tenant, 0) + sum(
            1
            for r in list(self.engine.waiting)
            if not r.finished
            and getattr(r, "tenant", ANON_TENANT) == tenant
        )

    def _check_admission(
        self, prompt_len: int, tenant: str = ANON_TENANT,
    ) -> Optional[tuple]:
        """(audit_reason, error_string) when a submit of this size would
        be shed right now, else None."""
        if self._draining or self._stop.is_set():
            return (
                "shutting_down",
                f"{SHUTTING_DOWN}: engine '{self.name}' is draining",
            )
        # KV-starved fast-fail: when admission has already been stalled
        # longer than the deadline, a new arrival would only age out the
        # same way — reject it NOW, before the HTTP layer commits SSE
        # headers, so the client gets a real 503 + Retry-After
        # (_stall_since is written by the engine thread; a float read is
        # GIL-atomic)
        stall_since = self._stall_since
        if (
            self.admission_timeout is not None
            and stall_since is not None
            and time.monotonic() - stall_since > self.admission_timeout
        ):
            return (
                "kv_exhausted",
                f"{KV_EXHAUSTED}: engine '{self.name}' admission has been "
                f"KV-starved for {time.monotonic() - stall_since:.1f}s "
                f"(admission_timeout={self.admission_timeout}s)",
            )
        # the engine-side sums are read without the admission lock (list
        # copies are GIL-atomic; the bound is advisory by one request
        # anyway), so overloaded submitters don't serialize on an O(n)
        # walk of the wait queue
        depth = self.queue_depth()
        if (
            self.max_queue_depth is not None
            and depth >= self.max_queue_depth
        ):
            return (
                "queue_full",
                f"{QUEUE_FULL}: {depth} request(s) already queued "
                f"(max_queue_depth={self.max_queue_depth})",
            )
        # bounded per-tenant queues (scheduler policy): one flooding
        # tenant overflows ITS queue and gets per-tenant 429s instead
        # of filling the global bound and starving the cluster
        if self.sched.cfg.max_tenant_queue_depth is not None:
            td = self._tenant_depth(tenant)
            if self.sched.tenant_overflow(tenant, td):
                return (
                    TENANT_QUEUE_FULL,
                    f"{QUEUE_FULL}: tenant '{tenant}' already has {td} "
                    f"request(s) queued (max_tenant_queue_depth="
                    f"{self.sched.cfg.max_tenant_queue_depth})",
                )
        if self.max_queued_tokens is not None:
            queued = self.queued_tokens()
            if queued + prompt_len > self.max_queued_tokens:
                return (
                    "queue_full",
                    f"{QUEUE_FULL}: {queued} tokens queued + "
                    f"{prompt_len} requested exceeds "
                    f"max_queued_tokens={self.max_queued_tokens}",
                )
        return None

    def submit(self, req: Request, on_event: Callable[[TokenEvent], None]):
        # resolve the priority class once, at the edge: a stamped class
        # passes through, everything else gets the profile default
        if not getattr(req, "sched_class", ""):
            req.sched_class = self.sched.cfg.default_class
        # reject unservable requests on the caller's thread with a clean
        # event — the engine thread must never die on bad input
        err = self.engine.validate_request(req) or self.check_admission(
            len(req.prompt_tokens), count_shed=True,
            tenant=getattr(req, "tenant", ANON_TENANT),
            trace_id=req.trace_id, request_id=req.id,
        )
        if err:
            on_event(
                TokenEvent(
                    request_id=req.id, token_id=-1, finished=True,
                    finish_reason="error", error=err,
                )
            )
            return
        if getattr(req, "adapter", ""):
            # cold-adapter overlap starts NOW, on the submitter's
            # thread: the engine's ONE readiness gate (thread-safe —
            # pool/store take their own locks) kicks the async
            # filestore->host prefetch as a side effect, so the load
            # rides the queue wait and a still-cold adapter defers
            # admission instead of stalling a step
            ready = getattr(self.engine, "_adapter_ready", None)
            if ready is not None:
                ready(req)
        with self._admission_lock:
            # re-check under the lock: stop() flips _draining inside the
            # same lock, so a submit can never slip its request into the
            # inbox after the engine thread's terminal sweep
            if self._draining or self._stop.is_set():
                self.shed_requests += 1
                self.slo.note_shed(getattr(req, "tenant", ANON_TENANT))
                self._audit(
                    "shutting_down",
                    tenant=getattr(req, "tenant", ANON_TENANT),
                    trace_id=req.trace_id, request_id=req.id,
                    detail="draining",
                )
                on_event(
                    TokenEvent(
                        request_id=req.id, token_id=-1, finished=True,
                        finish_reason="error",
                        error=f"{SHUTTING_DOWN}: engine '{self.name}' "
                              "is draining",
                    )
                )
                return
            self._pending += 1
            self._pending_tokens += len(req.prompt_tokens)
            t = getattr(req, "tenant", ANON_TENANT)
            self._pending_by_tenant[t] = (
                self._pending_by_tenant.get(t, 0) + 1
            )
            self._inbox.put((req, on_event))
        self._wake.set()

    def abort(self, request_id: str):
        self._inbox.put((request_id, None))
        self._wake.set()

    @property
    def draining(self) -> bool:
        """Shutdown-ladder state for metrics/heartbeats (GIL-atomic)."""
        return self._draining or self._stop.is_set()

    def submit_import(self, snapshot, on_event, on_result=None):
        """Enqueue a migrated-in request snapshot (any thread).

        Validation and re-admission happen on the engine thread
        (``engine.import_request`` — every checksum checked before any
        allocator mutation); ``on_result(err, code)`` reports the
        outcome.  A KV-carrying snapshot parks on the preempted list and
        re-admits when a slot + pages free up, so an import landing on a
        FULL engine queues behind admission instead of wedging — and the
        ordinary admission deadline sheds it (typed) if capacity never
        comes."""
        with self._admission_lock:
            if self._draining or self._stop.is_set():
                if on_result is not None:
                    on_result(
                        f"{SHUTTING_DOWN}: engine '{self.name}' is "
                        "draining",
                        "shutting_down",
                    )
                return
            self._inbox.put(
                (_ImportItem(snapshot, on_event, on_result), None)
            )
        self._wake.set()

    def _handle_import(self, item: _ImportItem) -> None:
        """Engine-thread half of submit_import."""
        rid = getattr(item.snapshot, "request_id", "")
        t0 = time.monotonic()
        try:
            req = self.engine.import_request(item.snapshot)
        except SnapshotError as e:
            self.migration_failures += 1
            self.flight.note_anomaly(
                "import_rejected", request_id=rid, detail=str(e)[:200]
            )
            log.warning(
                "engine '%s' rejected snapshot import request_id=%s: %s",
                self.name, rid, e,
            )
            if item.on_result is not None:
                item.on_result(str(e), e.code)
            return
        except Exception as e:  # noqa: BLE001 — thread must survive
            self.migration_failures += 1
            log.exception(
                "engine '%s' snapshot import failed request_id=%s",
                self.name, rid,
            )
            if item.on_result is not None:
                item.on_result(str(e), "snapshot_invalid")
            return
        self._subscribers[req.id] = item.on_event
        self._admit_order.append(req.id)
        # the engine-side admit leg of a migrated/disagg timeline
        # (ISSUE 18): checksum-verified page import through admission
        self._trace.record(
            getattr(item.snapshot, "trace_id", ""),
            "engine import admit", t0, time.monotonic(),
            plane="engine", request_id=req.id,
            prior_tokens=len(req.output_tokens),
            pages=len(getattr(item.snapshot, "pages", ())),
        )
        log.info(
            "engine '%s' imported request_id=%s (%d prior token(s), "
            "%d page(s))",
            self.name, req.id, len(req.output_tokens),
            len(getattr(item.snapshot, "pages", ())),
        )
        if item.on_result is not None:
            item.on_result(None, None)

    def stage_disagg_export(self, request_id: str, on_snapshot) -> None:
        """Register a disaggregated prefill export (ISSUE 14): the
        moment ``request_id`` has completed its prefill (first token
        sampled), the engine thread snapshots it via
        ``engine.export_prefill`` and fires ``on_snapshot(kind, wire)``
        exactly once, where kind is:

        - ``"snapshot"`` — wire dict attached; the request KEEPS
          decoding locally until the caller confirms the ship and
          aborts it (a failed ship degrades to local serving);
        - ``"completed"`` — the request finished before the export
          fired (short generation): serve the buffered stream locally;
        - ``"local"`` — export unavailable/failed (VL, lockstep, host
          page lost): the request keeps generating here, colocated;
        - ``"gone"`` — the request vanished (aborted) before export.

        Call BEFORE ``submit`` so the first token cannot race the
        staging."""
        self._disagg_cb[request_id] = on_snapshot

    def unstage_disagg_export(self, request_id: str) -> None:
        """Withdraw a staged export (handler timed out / chose local)."""
        self._disagg_cb.pop(request_id, None)

    def _handoff_work(self) -> bool:
        """True when a staged disagg export is actionable — the gate
        that forces a reconcile before ``_disagg_tick`` runs (export
        gathers pages + syncs sampler state, so no step may be in
        flight).  O(staged), GIL-atomic reads."""
        if not self._disagg_cb:
            return False
        for rid in list(self._disagg_cb):
            req = self.engine.get_request(rid)
            if req is None or req.finished or req.output_tokens:
                return True
        return False

    def _disagg_tick(self) -> None:
        """Engine-thread half of the disaggregated handoff: export every
        staged request whose prefill completed and hand the wire dict to
        its callback (the HTTP handler ships it OFF this thread — a slow
        peer must never stall the engine).  Export mutates nothing; the
        request keeps decoding until the ship is confirmed."""
        from helix_tpu.serving.migration import snapshot_to_wire

        for rid, cb in list(self._disagg_cb.items()):
            req = self.engine.get_request(rid)
            if req is None:
                self._disagg_cb.pop(rid, None)
                cb("gone", None)
                continue
            if req.finished:
                self._disagg_cb.pop(rid, None)
                cb("completed", None)
                continue
            if not req.output_tokens:
                continue   # still queued / prefilling
            self._disagg_cb.pop(rid, None)
            t0 = time.monotonic()
            export = getattr(self.engine, "export_prefill", None)
            snap = None
            if export is not None:
                try:
                    snap = export(rid)
                except Exception:  # noqa: BLE001 — degrade to local serving
                    log.exception(
                        "engine '%s' prefill export failed for "
                        "request_id=%s", self.name, rid,
                    )
            if snap is None:
                cb("local", None)
                continue
            try:
                wire = snapshot_to_wire(snap)
            except Exception:  # noqa: BLE001 — degrade to local serving
                log.exception(
                    "engine '%s' prefill snapshot encode failed for "
                    "request_id=%s", self.name, rid,
                )
                cb("local", None)
                continue
            self.disagg_exports += 1
            # the engine-side export leg (ISSUE 18): prefill snapshot
            # gather + wire encode, before the HTTP handler ships it
            self._trace.record(
                getattr(req, "trace_id", ""), "disagg export",
                t0, time.monotonic(), plane="engine", request_id=rid,
                pages=len(wire.get("pages") or ()),
            )
            cb("snapshot", wire)

    def _export_survivors(self) -> int:
        """Drain-deadline migration: snapshot every still-unfinished
        request and ship it to a peer via ``self.exporter`` instead of
        shedding.  Runs on the engine thread after the last drain step,
        so the captured sampler state is exactly where generation
        stopped.  Requests that cannot export (VL, ship failure) are
        left for the ``_fail_all`` that follows."""
        self._emit_stage.flush()   # no error frame may overtake tokens
        if self.exporter is None:
            return 0
        from helix_tpu.serving.migration import (
            migrated_error,
            snapshot_to_wire,
        )

        shipped = 0
        for req in self._active_by_recency():
            try:
                snap = self.engine.export_request(req.id)
            except Exception:  # noqa: BLE001 — degrade to shed
                log.exception(
                    "engine '%s' export failed for request_id=%s",
                    self.name, req.id,
                )
                snap = None
            if snap is None:
                self.migration_failures += 1
                continue
            t0 = time.monotonic()
            try:
                peer = self.exporter(snapshot_to_wire(snap))
            except Exception as e:  # noqa: BLE001 — degrade to shed
                self.migration_failures += 1
                self._trace.record(
                    getattr(req, "trace_id", ""), "migrate export ship",
                    t0, time.monotonic(), plane="engine",
                    request_id=req.id, outcome="failed",
                )
                log.warning(
                    "engine '%s' could not ship snapshot for "
                    "request_id=%s: %s",
                    self.name, req.id, e,
                )
                continue
            shipped += 1
            # the drain-ladder ship leg (ISSUE 18): snapshot encode +
            # accepted POST to the peer that now owns the request
            self._trace.record(
                getattr(req, "trace_id", ""), "migrate export ship",
                t0, time.monotonic(), plane="engine",
                request_id=req.id, outcome="shipped", peer=peer,
            )
            msg = migrated_error(req.id, peer)
            self.engine.abort(req.id)
            self._forget_request(req.id)
            log.info(
                "engine '%s' migrated request_id=%s to peer %s at "
                "drain deadline",
                self.name, req.id, peer,
            )
            cb = self._subscribers.pop(req.id, None)
            if cb:
                cb(
                    TokenEvent(
                        request_id=req.id, token_id=-1, finished=True,
                        finish_reason="error", error=msg,
                    )
                )
        return shipped

    def stats(self) -> dict:
        """Counter snapshot for /metrics (reads of plain ints are atomic
        under the GIL, so no lock against the engine thread is needed)."""
        eng = self.engine
        return {
            "steps": self.steps,
            "step_failures": self.step_failures,
            "step_retries": self.step_retries,
            "quarantine_evictions": self.quarantine_evictions,
            "shed_requests": self.shed_requests,
            "prefill_tokens": eng.num_prefill_tokens,
            "decode_tokens": eng.num_decode_tokens,
            "generated_tokens": getattr(eng, "num_generated_tokens", 0),
            "prefill_padding_tokens": getattr(
                eng, "num_prefill_padding_tokens", 0
            ),
            # ragged unification (ISSUE 10): distinct compiled device-
            # step entry points + padding over the flight window
            "compiled_step_shapes": getattr(
                eng, "compiled_step_shapes", 0
            ),
            "prefill_padding_ratio": self.padding_ratio(),
            "mixed_steps": getattr(eng, "num_mixed_steps", 0),
            "moe_dropped_tokens": getattr(eng, "moe_dropped_tokens", 0),
            "spec_steps": getattr(eng, "num_spec_steps", 0),
            "spec_drafted_tokens": getattr(
                eng, "num_spec_drafted_tokens", 0
            ),
            "spec_accepted_tokens": getattr(
                eng, "num_spec_accepted_tokens", 0
            ),
            "waiting": len(eng.waiting),
            "active_slots": sum(1 for s in eng.slots if s is not None),
            "free_pages": eng.allocator.free_pages,
            "kv_pages_used": getattr(eng, "kv_pages_used", 0),
            "kv_pages_peak": getattr(eng.allocator, "peak_used", 0),
            "flight_anomalies": self.flight.anomalies_total,
            "kv_cache_dtype": eng.cache_cfg.dtype,
            # KV tiering + preemption-by-swap (ISSUE 6)
            "preemptions": getattr(eng, "num_preemptions", 0),
            "resumes": getattr(eng, "num_resumes", 0),
            "preempted_parked": len(getattr(eng, "preempted", ())),
            "kv_exhausted_sheds": self.kv_exhausted_sheds,
            "host_pool": (
                eng.host_pool.stats()
                if getattr(eng, "host_pool", None) is not None
                else None
            ),
            # cross-runner migration (ISSUE 11): snapshots out/in +
            # ship/import failures + the drain-ladder state
            "migration": {
                "exported": getattr(eng, "num_snapshots_exported", 0),
                "imported": getattr(eng, "num_snapshots_imported", 0),
                "failures": self.migration_failures,
                "draining": self.draining,
                # disaggregated prefill handoffs (ISSUE 14)
                "prefill_exports": getattr(
                    eng, "num_prefill_exports", 0
                ),
                "disagg_exports": self.disagg_exports,
            },
            # persistent filestore KV tier (ISSUE 14): None = tier off
            "filestore": (
                eng.kv_filestore.stats()
                if getattr(eng, "kv_filestore", None) is not None
                else None
            ),
            # per-tenant SLO observability (ISSUE 7): pooled totals +
            # top-K bounding introspection
            "tenants": self.slo.stats(),
            # scheduler policy + per-class admission/victim counters
            # (ISSUE 9)
            "sched": self.sched.stats(),
            # asynchronous pipelined loop (ISSUE 13)
            "async_loop": {
                "enabled": self.async_enabled,
                "pipelined_steps": self.pipelined_steps,
                "device_idle_ratio": round(self.device_idle_ratio(), 4),
                "emit_queue_depth": self._emit_stage.depth(),
            },
            # continuous multi-LoRA serving (ISSUE 15): HBM pool +
            # host/filestore residency ladder; None = pool off
            "adapters": (
                {
                    **eng.adapter_pool.stats(),
                    "store": (
                        eng.adapter_store.stats()
                        if getattr(eng, "adapter_store", None)
                        is not None else None
                    ),
                }
                if getattr(eng, "adapter_pool", None) is not None
                else None
            ),
            # N-follower mesh health + failover accounting (ISSUE 17):
            # None except on a plan-broadcast leader.  multihost-ok:
            # duck-typed stats surfacing, not a feature guard.
            "multihost": (
                eng.mh_stats()
                if callable(getattr(eng, "mh_stats", None))
                else None
            ),
        }

    def device_idle_ratio(self) -> float:
        """Fraction of recent serving wall time the device had NOTHING
        dispatched (flight-window ``idle_gap_s`` / ``wall_s``) — the
        async loop's headline gauge.  Host-side approximation: a gap is
        charged from the previous completion's return to the next
        dispatch whenever no step was in flight in between (pipelined
        dispatches therefore charge zero), so it understates idle only
        when a fetch returned after the device actually finished."""
        return self.flight.window_ratio("idle_gap_s", ("wall_s",))

    def tokens_per_sec(self) -> float:
        """Goodput: generated tokens/s over the trailing rate window."""
        return self._tps.rate(getattr(self.engine, "num_generated_tokens", 0))

    def padding_ratio(self) -> float:
        """Prefill padding / (padding + useful prefill) over the flight
        window — the ragged unification's waste gauge (one formula,
        fed by the engine's single ``_charge_padding`` site)."""
        return self.flight.window_ratio(
            "padding_tokens", ("padding_tokens", "prefill_tokens")
        )

    def saturation(self) -> dict:
        """The compact saturation summary (``obs.flight.SATURATION_KEYS``
        schema) this engine contributes to the node heartbeat and the
        runner's capacity gauges.  Plain GIL-atomic reads, safe from any
        thread."""
        eng = self.engine
        used = getattr(eng, "kv_pages_used", 0)
        cap = getattr(eng, "kv_pages_capacity", 1)
        pc = getattr(eng, "prefix_cache", None)
        hits = getattr(pc, "hits", 0) if pc is not None else 0
        misses = getattr(pc, "misses", 0) if pc is not None else 0
        denom = hits + misses
        hp = getattr(eng, "host_pool", None)
        out = {
            "kv_occupancy": round(used / cap, 4),
            "slots_busy": sum(1 for s in eng.slots if s is not None),
            "slots_total": len(eng.slots),
            "queue_depth": self.queue_depth(),
            "tokens_per_sec": round(self.tokens_per_sec(), 2),
            "prefix_hit_rate": round(hits / denom, 4) if denom else 0.0,
            "spec_acceptance_ratio": round(
                getattr(eng, "spec_acceptance_ratio", 0.0), 4
            ),
            # host KV tier fullness (0 with the tier off) + decoders
            # currently swapped out awaiting resume
            "kv_host_occupancy": round(
                hp.occupancy if hp is not None else 0.0, 4
            ),
            "preempted_requests": len(getattr(eng, "preempted", ())),
            # scheduler prefill-admission budget this engine is running
            # under (0 = unbudgeted — FIFO baseline or no cap declared)
            "prefill_budget_tokens": int(
                getattr(eng, "prefill_budget", None) or 0
            ),
            # multi-LoRA adapters resident in the HBM pool (0 = pool
            # off) — the control plane's adapter-affinity signal
            "adapters_resident": (
                eng.adapter_pool.stats()["resident"]
                if getattr(eng, "adapter_pool", None) is not None
                else 0
            ),
            # tiered KV residency (ISSUE 20): cold-middle pages demoted
            # to host RAM — how much admitted context lives past HBM
            "kv_cold_pages": int(getattr(eng, "kv_cold_pages", 0)),
        }
        # schema lockstep: this summary IS the per-engine instance of the
        # shared heartbeat schema — emit exactly its key set
        return {k: out[k] for k in SATURATION_KEYS}

    def start(self):
        if self.async_enabled:
            self._emit_stage.start(self.name)
        self._thread = threading.Thread(
            target=self._run, name=f"helix-engine-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, join: bool = True, drain: float = 0.0):
        """Stop the engine thread.  With ``drain > 0`` new submissions are
        shed (``shutting_down`` -> 503) while in-flight requests keep
        stepping for up to ``drain`` seconds; anything still unfinished at
        the deadline gets a clean error event before the thread exits.
        ``join=False`` + drain leaves the thread to finish the drain on
        its own (it exits once idle or at the deadline)."""
        if drain > 0 and self._thread is not None and self._thread.is_alive():
            # deadline must be visible before the flag: the engine thread
            # checks the deadline as soon as it sees _draining
            self._drain_deadline = time.monotonic() + drain
            with self._admission_lock:
                self._draining = True
            self._wake.set()
            if not join:
                return   # thread self-terminates when drained
            self._thread.join(timeout=drain + 30)
        self._stop.set()
        self._wake.set()
        if join and self._thread is not None:
            self._thread.join(timeout=30)

    # -- engine thread ------------------------------------------------------

    def _drain_inbox(self):
        while True:
            try:
                item, on_event = self._inbox.get_nowait()
            except queue.Empty:
                return
            if isinstance(item, _ImportItem):  # migrated-in snapshot
                self._handle_import(item)
                continue
            if on_event is None:  # abort
                # barrier: the emission worker may still be delivering
                # this request's queued tokens — its bookkeeping and the
                # forget below must not interleave
                self._emit_stage.flush()
                self.engine.abort(item)
                self._subscribers.pop(item, None)
                self._forget_request(item)
            else:
                with self._admission_lock:
                    self._pending = max(0, self._pending - 1)
                    self._pending_tokens = max(
                        0, self._pending_tokens - len(item.prompt_tokens)
                    )
                    t = getattr(item, "tenant", ANON_TENANT)
                    n = self._pending_by_tenant.get(t, 0) - 1
                    if n > 0:
                        self._pending_by_tenant[t] = n
                    else:
                        self._pending_by_tenant.pop(t, None)
                try:
                    self.engine.add_request(item)
                    self._subscribers[item.id] = on_event
                    self._admit_order.append(item.id)
                except Exception as e:  # noqa: BLE001 — thread must survive
                    on_event(
                        TokenEvent(
                            request_id=item.id, token_id=-1, finished=True,
                            finish_reason="error", error=str(e),
                        )
                    )

    def _note_admit(self, req) -> None:
        """Engine admission-confirm hook (fires on the engine thread
        inside ``_try_claim``): feeds the scheduler's class counters and
        the per-tenant fair-share account — charging only on CONFIRMED
        admissions is what keeps the DRR ledger honest when a reorder
        pass couldn't be acted on (resource block)."""
        try:
            self.sched.note_admitted(req)
        except Exception:  # noqa: BLE001 — bookkeeping must never fail admission
            log.exception("scheduler note_admitted failed")

    def _observe_emit(self, req: Request, finished: bool) -> None:
        """Feed the latency histograms + engine-level spans from one
        emitted token (queue/prefill on the first token, decode span on
        finish).  ``finished`` is the emission-time snapshot — the live
        ``req.finished`` may already reflect a LATER step's reconcile
        when delivery runs on the emission worker."""
        now = time.monotonic()
        rid = req.id
        tenant = getattr(req, "tenant", ANON_TENANT)
        last = self._last_emit.get(rid)
        if rid not in self._first_emit:
            self._first_emit[rid] = now
            admitted = req.admitted_time or now
            self.obs.queue_wait.observe(max(0.0, admitted - req.submit_time))
            self.obs.ttft.observe(max(0.0, now - req.submit_time))
            self.slo.note_first_token(
                tenant,
                max(0.0, now - req.submit_time),
                max(0.0, admitted - req.submit_time),
                len(req.prompt_tokens),
            )
            if req.trace_id:
                self._trace.record(
                    req.trace_id, "queue", req.submit_time, admitted,
                    plane="engine", request_id=rid,
                )
                self._trace.record(
                    req.trace_id, "prefill", admitted, now,
                    plane="engine", request_id=rid,
                    prompt_tokens=len(req.prompt_tokens),
                    cached_tokens=req.cached_tokens,
                )
        elif last is not None:
            self.obs.inter_token.observe(max(0.0, now - last))
        self._last_emit[rid] = now
        if finished:
            t_first = self._first_emit.pop(rid, now)
            self._last_emit.pop(rid, None)
            if req.trace_id:
                self._trace.record(
                    req.trace_id, "decode", t_first, now,
                    plane="engine", request_id=rid,
                    output_tokens=len(req.output_tokens),
                    finish_reason=(
                        req.finish_reason.value if req.finish_reason else None
                    ),
                )

    def _forget_request(self, request_id: str) -> None:
        """Drop per-request emit bookkeeping (abort/evict paths where no
        finished token event flows through _emit)."""
        self._first_emit.pop(request_id, None)
        self._last_emit.pop(request_id, None)

    def _emit(self, emitted) -> None:
        """Snapshot + deliver in one call (synchronous paths: direct
        emission, quarantine bisection).  The async loop snapshots on
        the engine thread at push time and delivers on the emission
        worker."""
        self._deliver(self._snapshot_events(emitted))

    def _snapshot_events(self, emitted) -> list:
        """Render ``[(req, token), ...]`` into delivery-ready events —
        ENGINE-THREAD ONLY, at emission time.  ``req.finished`` keeps
        evolving after the push (the next step's reconcile may finish
        this request before the worker delivers), so a delivery-time
        read would stamp an EARLIER token as terminal, pop the
        subscriber, and drop the real final tokens.  Within one batch
        the finishing token is always a request's LAST entry (the
        engine discards post-finish overruns), so only the last
        occurrence carries the finished flag."""
        # chaos (ISSUE 19): a corrupt_output rule models a host silently
        # computing wrong logits — offset every emitted token id (mod
        # vocab) at emission time.  Requests still complete, latency is
        # untouched; only the canary's bit-identity check can see it.
        offset = 0
        inj = faults.active()
        if inj is not None:
            corrupt = inj.corrupt_output(self.name)
            if corrupt:
                offset = int(corrupt.get("offset", 1))
        vocab = getattr(
            getattr(self.engine, "model_cfg", None), "vocab_size", 0
        )
        last: dict = {}
        for idx, (req, _token) in enumerate(emitted):
            last[req.id] = idx
        events = []
        for idx, (req, token) in enumerate(emitted):
            if offset and token >= 0 and vocab:
                token = (token + offset) % vocab
            fin = req.finished and last[req.id] == idx
            events.append((
                req, fin,
                TokenEvent(
                    request_id=req.id,
                    token_id=token,
                    finished=fin,
                    finish_reason=(
                        req.finish_reason.value
                        if fin and req.finish_reason else None
                    ),
                ),
            ))
        return events

    def _deliver(self, events) -> None:
        # per-tenant token counts batched to ONE accounting call per
        # tenant per step (not per token) — the accounting lock is
        # shared with /metrics scrapes and must stay off the hot path
        tenant_tokens: dict = {}
        for req, fin, ev in events:
            self._observe_emit(req, fin)
            t = getattr(req, "tenant", ANON_TENANT)
            tenant_tokens[t] = tenant_tokens.get(t, 0) + 1
            cb = self._subscribers.get(req.id)
            if cb is None:
                continue
            cb(ev)
            if fin:
                self._subscribers.pop(req.id, None)
        for t, n in tenant_tokens.items():
            self.slo.note_tokens(t, n)

    def _shed_kv_exhausted(self, req, waited: float) -> None:
        """Terminal typed shed for one request that outwaited the
        admission deadline (queued or parked-preempted)."""
        self._emit_stage.flush()   # no error frame may overtake tokens
        msg = (
            f"{KV_EXHAUSTED}: request waited {waited:.1f}s for KV pages "
            f"(admission_timeout={self.admission_timeout}s) — the engine "
            "is out of KV capacity; retry later"
        )
        self.engine.abort(req.id)
        self.kv_exhausted_sheds += 1
        self.shed_requests += 1
        tenant = getattr(req, "tenant", ANON_TENANT)
        self.slo.note_shed(tenant, kv_exhausted=True)
        self._audit(
            "kv_exhausted", tenant=tenant, trace_id=req.trace_id,
            request_id=req.id, detail=msg,
        )
        log.warning(
            "engine '%s' shedding request_id=%s trace_id=%s: %s",
            self.name, req.id, req.trace_id or "-", msg,
            extra={"trace_id": req.trace_id or "", "request_id": req.id},
        )
        self._forget_request(req.id)
        cb = self._subscribers.pop(req.id, None)
        if cb:
            cb(
                TokenEvent(
                    request_id=req.id, token_id=-1, finished=True,
                    finish_reason="error", error=msg,
                )
            )

    def _memory_pressure_tick(self) -> None:
        """The graceful-degradation ladder, walked once per loop pass.

        Tracks how long admission has been KV-starved (queue non-empty
        with no admissions or resumes landing).  Past
        ``preempt_stall_seconds``, swap out the newest/largest decoder
        (``Engine.preempt_for_pressure``) so the starved queue gets its
        pages — bounded to one preemption per stall window.  Past
        ``admission_timeout``, requests stop aging silently: queued and
        parked requests over the deadline get the typed ``kv_exhausted``
        shed."""
        eng = self.engine
        now = time.monotonic()
        progress = eng.num_admitted + getattr(eng, "num_resumes", 0)
        waiting = list(eng.waiting)
        if progress != self._admit_seen:
            self._admit_seen = progress
            self._stall_since = now if waiting else None
        elif not waiting:
            self._stall_since = None
        elif self._stall_since is None:
            self._stall_since = now
        if self.admission_timeout is not None:
            # queued sheds require the STALL ITSELF to have outlived the
            # deadline (same criterion as the fast-fail path): a request
            # aging in a merely throughput-bound queue — admissions still
            # landing, so the stall clock keeps resetting — is ordinary
            # latency, not KV exhaustion, and labelling it kv_exhausted
            # would misdirect both the client's retry and the operator's
            # capacity read
            if (
                self._stall_since is not None
                and now - self._stall_since > self.admission_timeout
            ):
                over = [
                    r for r in waiting
                    if not r.finished
                    and now - r.submit_time > self.admission_timeout
                ]
                if self._sched_active and len(over) > 1:
                    # every over-deadline request sheds, but in the
                    # policy's victim order (lowest class first) so the
                    # audit trail reflects the ladder
                    over = self.sched.preempt_order(over)
                for r in over:
                    self._shed_kv_exhausted(r, now - r.submit_time)
            # a parked decoder that cannot re-acquire pages IS KV
            # pressure by construction (resume is retried every step),
            # so its deadline is unconditional
            for st in list(getattr(eng, "preempted", ())):
                waited = now - st.preempted_at
                if not st.req.finished and waited > self.admission_timeout:
                    self._shed_kv_exhausted(st.req, waited)
        if (
            self.preempt_stall_seconds is not None
            and self._stall_since is not None
            and now - self._stall_since > self.preempt_stall_seconds
            and now - self._last_preempt_at > self.preempt_stall_seconds
        ):
            victim = self.engine.preempt_for_pressure()
            if victim is not None:
                self._last_preempt_at = now
                vreq = self.engine.get_request(victim)
                tenant = getattr(vreq, "tenant", ANON_TENANT)
                self.slo.note_preemption(tenant)
                if self._sched_active and vreq is not None:
                    # a scheduler decision: the victim came from the
                    # policy ladder (lowest class, most-over-fair-share
                    # tenant, newest) — audited under its own reason
                    self.sched.note_preempt_victim(vreq)
                    preempt_reason = PREEMPT_VICTIM
                else:
                    preempt_reason = "preempt_by_swap"
                self._audit(
                    preempt_reason, tenant=tenant,
                    trace_id=getattr(vreq, "trace_id", ""),
                    request_id=victim,
                    detail=f"admission KV-starved "
                           f"{now - self._stall_since:.1f}s",
                )
                log.warning(
                    "engine '%s' admission KV-starved for %.1fs: "
                    "preempted request_id=%s (swap-to-host)",
                    self.name, now - self._stall_since, victim,
                )

    def _deliver_resume_failures(self) -> None:
        """Typed error events for parked requests whose swap-in failed
        verification (corrupt host copy) — detected inside the engine,
        surfaced to the subscriber here."""
        drain = getattr(self.engine, "drain_resume_failures", None)
        if drain is None:
            return
        if self._resume_failures_pending():
            self._emit_stage.flush()
        for req, msg in drain():
            log.warning(
                "engine '%s' resume failed for request_id=%s: %s",
                self.name, req.id, msg,
                extra={"trace_id": req.trace_id or "",
                       "request_id": req.id},
            )
            self.flight.note_anomaly(
                "resume_corrupt", request_id=req.id, detail=msg[:200]
            )
            self._forget_request(req.id)
            cb = self._subscribers.pop(req.id, None)
            if cb:
                cb(
                    TokenEvent(
                        request_id=req.id, token_id=-1, finished=True,
                        finish_reason="error", error=msg,
                    )
                )

    def _fault_gate(self) -> None:
        """The (normally disabled) fault-injection hook so chaos tests
        can poison specific requests — shared by the synchronous step
        and the async dispatch."""
        from helix_tpu.testing import faults

        inj = faults.active()
        if inj is not None:
            ids = [r.id for r in self.engine.slots if r is not None] + [
                r.id for r in self.engine.waiting
            ]
            inj.maybe_fail_step(self.name, self.steps, ids)

    def _step_once(self):
        """One full synchronous engine step (quarantine bisection uses
        this — no pipelining)."""
        self._fault_gate()
        return self.engine.step()

    def _dispatch_once(self):
        """Host phase of one engine step.  An engine without the
        dispatch/complete split runs its monolithic ``step()`` and
        returns no pending, so the loop behaves exactly synchronously.
        Multihost leaders implement the split themselves (publishing the
        step plan at dispatch), so they pipeline like any engine."""
        self._fault_gate()
        if not hasattr(self.engine, "step_dispatch"):
            return self.engine.step(), None
        return self.engine.step_dispatch()

    def _handle_step_failure(
        self, e: Exception, dt_step: float, flight_pre: tuple,
    ) -> None:
        """The step-failure ladder (shared by the sync and async paths):
        record, retry once on the exact same state, then quarantine."""
        self._emit_stage.flush()
        self.obs.step_seconds.observe(dt_step)
        self._flight_record(
            dt_step, flight_pre, generated=0, failed=str(e)
        )
        self.step_failures += 1
        self._consec_failures += 1
        scheduled = [
            r.id for r in self.engine.slots if r is not None
        ]
        log.warning(
            "engine '%s' step %d failed (consecutive=%d, "
            "scheduled request_ids=%s): %s",
            self.name, self.steps, self._consec_failures,
            scheduled, e,
        )
        if self._consec_failures == 1:
            # transient faults (preemption, relay hiccup) clear on
            # an immediate retry of the exact same state
            self.step_retries += 1
            return
        import traceback

        traceback.print_exc()
        self._quarantine(e)
        self._consec_failures = 0

    # -- flight recorder (host-side counter deltas only) --------------------

    def _flight_pre(self) -> tuple:
        """Counter snapshot taken just before a step so the per-step
        record carries deltas, not lifetime totals."""
        eng = self.engine
        hp = getattr(eng, "host_pool", None)
        return (
            eng.num_prefill_tokens,
            getattr(eng, "num_prefill_padding_tokens", 0),
            eng.num_decode_tokens,
            getattr(eng, "num_admitted", 0),
            self.quarantine_evictions,
            getattr(eng, "num_spec_drafted_tokens", 0),
            getattr(eng, "num_spec_accepted_tokens", 0),
            hp.spilled_pages if hp is not None else 0,
            hp.restored_pages if hp is not None else 0,
            getattr(eng, "num_preemptions", 0),
            getattr(eng, "num_resumes", 0),
            getattr(eng, "num_ctx_stream_chunks", 0),
        )

    def _resume_failures_pending(self) -> bool:
        return bool(getattr(self.engine, "_resume_failures", None))

    def _flight_record(
        self, duration: float, pre: tuple, generated: int,
        failed: Optional[str] = None, timing: Optional[dict] = None,
    ) -> None:
        eng = self.engine
        (p0, pad0, d0, a0, q0, sd0, sa0, sp0, rs0, pe0, re0,
         cs0) = pre
        hp = getattr(eng, "host_pool", None)
        prefill = eng.num_prefill_tokens - p0
        decode = eng.num_decode_tokens - d0
        if failed is not None:
            kind = "failed"
        elif prefill and decode:
            kind = "mixed"
        elif prefill:
            kind = "prefill"
        elif decode:
            kind = "decode"
        else:
            kind = "idle"
        rec = {
            "step": self.steps,
            "ts": time.time(),
            "duration": duration,
            "kind": kind,
            "slots_busy": sum(1 for s in eng.slots if s is not None),
            "slots_total": len(eng.slots),
            "queue_depth": self.queue_depth(),
            "kv_pages_used": getattr(eng, "kv_pages_used", 0),
            "kv_pages_free": eng.allocator.free_pages,
            "prefill_tokens": prefill,
            "padding_tokens": (
                getattr(eng, "num_prefill_padding_tokens", 0) - pad0
            ),
            # distinct compiled device-step entry points live for this
            # model at step time: flat after warmup = the shape ladder
            # is doing its job; climbing under traffic = a caller is
            # minting new trace shapes (the pre-unification zoo smell)
            "compiled_shapes": getattr(eng, "compiled_step_shapes", 0),
            "decode_tokens": decode,
            "generated_tokens": generated,
            "admissions": getattr(eng, "num_admitted", 0) - a0,
            "evictions": self.quarantine_evictions - q0,
            # speculative decoding gains: drafts proposed/accepted this
            # step (0/0 on non-speculative steps)
            "spec_drafted": (
                getattr(eng, "num_spec_drafted_tokens", 0) - sd0
            ),
            "spec_accepted": (
                getattr(eng, "num_spec_accepted_tokens", 0) - sa0
            ),
            # KV tiering this step: pages demoted/promoted across the
            # host tier, decoders swapped out/in, host-pool fullness
            "spilled_pages": (
                (hp.spilled_pages - sp0) if hp is not None else 0
            ),
            "restored_pages": (
                (hp.restored_pages - rs0) if hp is not None else 0
            ),
            "preemptions": getattr(eng, "num_preemptions", 0) - pe0,
            "resumes": getattr(eng, "num_resumes", 0) - re0,
            "host_pool_pages": hp.pages if hp is not None else 0,
            # tiered KV residency (ISSUE 20): cold chunks streamed
            # through attention this step, and the cold-page gauge
            "ctx_stream_chunks": (
                getattr(eng, "num_ctx_stream_chunks", 0) - cs0
            ),
            "kv_cold_pages": int(getattr(eng, "kv_cold_pages", 0)),
            # the scheduler's prefill-admission budget in force this
            # step (0 = unbudgeted)
            "prefill_budget_tokens": int(
                getattr(eng, "prefill_budget", None) or 0
            ),
            # distinct tenants sharing this step's decode batch: the
            # noisy-neighbour axis (1 = single-tenant step, >1 = a slow
            # step taxed every tenant listed)
            "distinct_tenants": len({
                getattr(s, "tenant", ANON_TENANT)
                for s in eng.slots if s is not None
            }),
            # distinct multi-LoRA adapters sharing this step's batch
            # (ISSUE 15): >1 = a genuinely mixed-adapter device call —
            # the batched gather-matmul packing the wave that merged
            # per-tenant model copies never could
            "distinct_adapters": len({
                getattr(s, "adapter", "")
                for s in eng.slots
                if s is not None and getattr(s, "adapter", "")
            }),
        }
        if timing:
            # per-step time split (ISSUE 13): host build / device wait /
            # emit, plus the device-idle gap this step charged — the
            # numerators of helix_device_idle_ratio and the bench's
            # host_overlap block
            rec.update(timing)
        if failed is not None:
            rec["anomaly"] = "step_failure"
            rec["error"] = failed[:200]
        self.flight.record_step(rec)
        # bank a goodput sample while the engine works (throttled inside
        # the tracker): keeps the rate anchor within ~one window of now,
        # so sparse external scrapes can't understate a recent burst
        self._tps.rate(getattr(eng, "num_generated_tokens", 0))

    def _run(self):
        # the at-most-one dispatched-but-not-reconciled step (async
        # pipeline, ISSUE 13); always None under the synchronous loop
        inflight = None

        def complete(pend):
            """One step's reconcile: the fetch + every host-visible
            effect, stamping the device-busy watermark the idle-gap
            accounting reads."""
            emitted = self.engine.step_complete(pend)
            self._device_busy_until = time.monotonic()
            return emitted

        def reconcile_or_fail() -> bool:
            """Reconcile point outside the main step path (inbox
            arrivals, drain, idle, preemption): complete the in-flight
            step and drain the emission stage.  False = the completion
            failed and the failure ladder ran — restart the loop pass."""
            nonlocal inflight
            if inflight is None:
                self._emit_stage.flush()
                return True
            pend, inflight = inflight, None
            pre = self._flight_pre()
            t0 = time.monotonic()
            try:
                emitted = complete(pend)
            except Exception as e:  # noqa: BLE001 — fail requests, not the loop
                self.engine.discard_pending(pend)
                self._handle_step_failure(e, time.monotonic() - t0, pre)
                return False
            dt_wait = time.monotonic() - t0
            t_emit = time.monotonic()
            self._emit_stage.push(self._snapshot_events(emitted))
            dt_emit = time.monotonic() - t_emit
            # the step was dispatched by an earlier pass that skipped
            # its record ("numbers land with its completion"): record
            # it here or the burst's last step vanishes from the flight
            # window (tokens, device wait, the idle-ratio denominator)
            self._flight_record(
                dt_wait, pre, generated=len(emitted),
                timing={
                    "host_build_s": 0.0,
                    "device_wait_s": round(dt_wait, 6),
                    "emit_s": round(dt_emit, 6),
                    "idle_gap_s": 0.0,
                    "wall_s": round(time.monotonic() - t0, 6),
                    "pipelined": 1,
                },
            )
            self._emit_stage.flush()
            return True

        while not self._stop.is_set():
            if inflight is not None and not self._inbox.empty():
                # inbox items (submit/abort/import) mutate state the
                # in-flight prediction did not see — reconcile first
                if not reconcile_or_fail():
                    continue
            self._drain_inbox()
            if self._draining:
                if not reconcile_or_fail():
                    continue
                if not self.engine.has_work():
                    break
                if time.monotonic() > self._drain_deadline:
                    # migrate instead of shed (ISSUE 11): with an
                    # exporter wired, the drain ladder is
                    # finish -> snapshot+ship -> shed — _fail_all only
                    # sees what could not be exported
                    shipped = self._export_survivors()
                    if shipped:
                        log.info(
                            "engine '%s' exported %d request(s) at the "
                            "drain deadline", self.name, shipped,
                        )
                    self._fail_all("drain deadline exceeded at shutdown")
                    break
            if time.monotonic() - self._last_reap > 10.0:
                self._last_reap = time.monotonic()
                reaped = self.engine.reap_stuck(self.max_queue_seconds)
                if reaped:
                    self._emit_stage.flush()
                for req in reaped:
                    cb = self._subscribers.pop(req.id, None)
                    if cb:
                        cb(
                            TokenEvent(
                                request_id=req.id, token_id=-1,
                                finished=True, finish_reason="error",
                                error="request timed out in queue",
                            )
                        )
            self._memory_pressure_tick()
            if self._handoff_work():
                # disaggregated prefill export (ISSUE 14): the export
                # gathers pages + syncs device sampler state, so the
                # in-flight pipelined step (if any) reconciles first
                if not reconcile_or_fail():
                    continue
                self._disagg_tick()
            ctick = getattr(self.engine, "checkpoint_tick", None)
            if ctick is not None and self.engine.checkpoint_due():
                # leader-state checkpoint (ISSUE 17): capture is a pure
                # host-side read of queue/digest bookkeeping (the blob
                # write happens off-thread), but the snapshot must not
                # straddle an in-flight pipelined step
                if not reconcile_or_fail():
                    continue
                ctick(sched=self.sched)
            if not self.engine.has_work():
                if not reconcile_or_fail():
                    continue
                if self.engine.has_work():
                    continue   # the reconcile freed/advanced work
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            if self._sched_active:
                # scheduler pass (engine thread — the wait queue's
                # owner): rewrite the queue into dispatch order (strict
                # classes + per-tenant DRR) and refresh the per-step
                # prefill-admission budget from the live TTFT burn.
                # With a step in flight this still only touches the wait
                # queue and burn-rate reads (the sched.reorder contract)
                # — and a non-empty queue forces the reconcile below
                # before the dispatch acts on the new order anyway.
                self.sched.reorder(self.engine.waiting)
                self.engine.prefill_budget = self.sched.prefill_budget(
                    self.slo
                )
            # pipeline gate, decided BEFORE the dispatch: plain
            # fused-decode steady state only — anything else (admission
            # waves, chunked prefill, speculation, parked preemptions,
            # dirty slot state, draining) reconciles first and runs the
            # synchronous dispatch -> complete ordering
            can_pipe = (
                self.async_enabled
                and not self._draining
                and self.engine.pipeline_ready()
            )
            if inflight is not None and not can_pipe:
                if not reconcile_or_fail():
                    continue
            t_step = time.monotonic()
            flight_pre = self._flight_pre()
            overlapped = inflight is not None
            try:
                emitted, pend = self._dispatch_once()
            except Exception as e:  # noqa: BLE001 — fail requests, not the loop
                # the in-flight step is healthy already-dispatched work:
                # reconcile it first so its tokens are not lost — and
                # flight-record it (its fill pass skipped the record on
                # the promise the completion would land it)
                if inflight is not None:
                    prev, inflight = inflight, None
                    pre_prev = self._flight_pre()
                    t0_prev = time.monotonic()
                    try:
                        prev_emitted = complete(prev)
                    except Exception:  # noqa: BLE001 — poisoned chain
                        self.engine.discard_pending(prev)
                    else:
                        self._emit_stage.push(
                            self._snapshot_events(prev_emitted)
                        )
                        dt_prev = time.monotonic() - t0_prev
                        self._flight_record(
                            dt_prev, pre_prev,
                            generated=len(prev_emitted),
                            timing={
                                "host_build_s": 0.0,
                                "device_wait_s": round(dt_prev, 6),
                                "emit_s": 0.0,
                                "idle_gap_s": 0.0,
                                "wall_s": round(dt_prev, 6),
                                "pipelined": 1,
                            },
                        )
                self._handle_step_failure(
                    e, time.monotonic() - t_step, flight_pre
                )
                continue
            t_build_end = time.monotonic()
            dt_build = t_build_end - t_step
            idle_gap = 0.0
            if not overlapped and self._device_busy_until:
                # nothing was in flight while this step's metadata was
                # built: the device sat idle from the last completion's
                # return until this dispatch landed
                idle_gap = max(
                    0.0, t_build_end - self._device_busy_until
                )
            prev, inflight = inflight, None
            dt_wait = 0.0
            try:
                if prev is not None:
                    # step N+1 is now queued on the device: fetch step
                    # N's results — the block covers only the device
                    # time the host build did not already overlap
                    t_w = time.monotonic()
                    prev_emitted = complete(prev)
                    prev = None
                    dt_wait += time.monotonic() - t_w
                    emitted = prev_emitted + emitted
                if pend is not None and can_pipe and pend.kind == "decode":
                    inflight, pend = pend, None
                    self.pipelined_steps += 1
                elif pend is not None:
                    t_w = time.monotonic()
                    if hasattr(self.engine, "prefetch_cold"):
                        # stage the NEXT step's cold-middle KV chunks
                        # while the dispatched step still runs on the
                        # device — the gathers queue behind the step on
                        # the device stream, so this is free overlap
                        self.engine.prefetch_cold()
                    self.engine.step_complete(pend, emitted)
                    pend = None
                    dt_wait += time.monotonic() - t_w
                    self._device_busy_until = time.monotonic()
            except Exception as e:  # noqa: BLE001 — fail requests, not the loop
                for p in (prev, pend):
                    if p is not None:
                        self.engine.discard_pending(p)
                inflight = None
                self._handle_step_failure(
                    e, time.monotonic() - t_step, flight_pre
                )
                continue
            dt_step = time.monotonic() - t_step
            self.obs.step_seconds.observe(dt_step)
            self.obs.host_build.observe(dt_build)
            self._consec_failures = 0
            self._barren_rounds = 0
            self.steps += 1
            if inflight is not None and not emitted:
                # pipeline-fill pass: dispatched with nothing reconciled
                # yet — no flight record (a dispatch-only pass would read
                # as zero_progress to the watchdog); the step's numbers
                # land with its completion next pass
                continue
            t_emit = time.monotonic()
            self._emit_stage.push(self._snapshot_events(emitted))
            dt_emit = time.monotonic() - t_emit
            self._deliver_resume_failures()
            self._flight_record(
                dt_step, flight_pre, generated=len(emitted),
                timing={
                    "host_build_s": round(dt_build, 6),
                    "device_wait_s": round(dt_wait, 6),
                    "emit_s": round(dt_emit, 6),
                    "idle_gap_s": round(idle_gap, 6),
                    "wall_s": round(time.monotonic() - t_step, 6),
                    "pipelined": 1 if overlapped else 0,
                },
            )
        # a step still in flight at shutdown: reconcile so its tokens
        # reach subscribers before the terminal sweep
        if inflight is not None:
            try:
                self._emit_stage.push(
                    self._snapshot_events(complete(inflight))
                )
            except Exception:  # noqa: BLE001 — best-effort at shutdown
                self.engine.discard_pending(inflight)
            inflight = None
        self._emit_stage.stop()
        # terminal sweep: anything still in the inbox (raced a shutdown)
        # gets a clean error event instead of a 300s client hang
        while True:
            try:
                item, on_event = self._inbox.get_nowait()
            except queue.Empty:
                break
            if on_event is not None:
                on_event(
                    TokenEvent(
                        request_id=item.id, token_id=-1, finished=True,
                        finish_reason="error",
                        error=f"{SHUTTING_DOWN}: engine '{self.name}' "
                              "stopped",
                    )
                )

    # -- poisoned-request quarantine ----------------------------------------

    def _active_by_recency(self) -> list:
        """Unfinished submitted requests, oldest admission first."""
        out = []
        for rid in self._admit_order:
            req = self.engine.get_request(rid)
            if req is not None and not req.finished:
                out.append(req)
        # prune finished ids so the order list doesn't grow unboundedly
        self._admit_order = [r.id for r in out]
        return out

    def _evict_victim(self, cands: list, msg: str) -> None:
        """Shed ONE of ``cands`` (oldest-admission-first): the scheduler
        picks the victim — the policy ladder (lowest class, then
        most-over-fair-share tenant, then newest) under WFQ, the
        historical newest-first under the FIFO baseline — and the
        decision is recorded in the admission audit ring."""
        victim = self.sched.pick_shed_victim(cands)
        if victim is None:
            return
        if self._sched_active:
            self.sched.note_shed_victim(victim)
            self._audit(
                SHED_VICTIM,
                tenant=getattr(victim, "tenant", ANON_TENANT),
                trace_id=victim.trace_id or "",
                request_id=victim.id,
                detail=f"policy victim among {len(cands)} candidate(s)",
            )
        self._evict(victim, msg)

    def _evict(self, req, msg: str) -> None:
        self._emit_stage.flush()   # no error frame may overtake tokens
        self.engine.abort(req.id)
        self.quarantine_evictions += 1
        self.flight.note_anomaly(
            "quarantine", request_id=req.id, detail=msg[:200]
        )
        self._audit(
            "quarantine", tenant=getattr(req, "tenant", ANON_TENANT),
            trace_id=req.trace_id or "", request_id=req.id, detail=msg,
        )
        log.warning(
            "engine '%s' evicting request_id=%s trace_id=%s: %s",
            self.name, req.id, req.trace_id or "-", msg,
            extra={"trace_id": req.trace_id or "", "request_id": req.id},
        )
        if req.trace_id:
            now = time.monotonic()
            self._trace.record(
                req.trace_id, "quarantine", self._first_emit.get(req.id, now),
                now, plane="engine", request_id=req.id, reason=msg,
            )
        self._forget_request(req.id)
        cb = self._subscribers.pop(req.id, None)
        if cb:
            cb(
                TokenEvent(
                    request_id=req.id, token_id=-1, finished=True,
                    finish_reason="error", error=msg,
                )
            )

    @staticmethod
    def _clone_for_readmit(req) -> Request:
        """A fresh Request (same id — subscribers stay valid) for a
        quarantined request that never emitted a token, so it can be
        re-prefilled from scratch during bisection."""
        return Request(
            id=req.id,
            prompt_tokens=list(req.prompt_tokens),
            sampling=req.sampling,
            stop_token_ids=req.stop_token_ids,
            image_embeds=req.image_embeds,
            image_positions=req.image_positions,
            positions3=req.positions3,
            mrope_delta=req.mrope_delta,
            trace_id=req.trace_id,
            tenant=getattr(req, "tenant", ANON_TENANT),
            sched_class=getattr(req, "sched_class", ""),
            adapter=getattr(req, "adapter", ""),
        )

    def _trial(self, group: list) -> bool:
        """Re-admit ``group`` (clones) and step until each member emits or
        finishes.  True = group is clean (members left running); False =
        a step failed, members re-aborted (subscribers kept)."""
        clones = []
        for req in group:
            clone = self._clone_for_readmit(req)
            try:
                self.engine.add_request(clone)
            except Exception as e:  # noqa: BLE001 — validation changed?
                self._evict(clone, f"engine rejected request: {e}")
                continue
            clones.append(clone)
        if not clones:
            return True
        # budget: admission + every prefill chunk + slack; prevents an
        # unbounded spin if a clone can never reach its first token
        chunk = max(1, self.engine.cfg.max_prefill_len)
        budget = 8 + sum(
            len(c.prompt_tokens) // chunk + 1 for c in clones
        )
        for _ in range(budget):
            try:
                emitted = self._step_once()
            except Exception:  # noqa: BLE001 — the culprit is in this group
                for c in clones:
                    self.engine.abort(c.id)
                return False
            self.steps += 1
            self._emit(emitted)
            if all(c.finished or c.output_tokens for c in clones):
                return True
        return True   # budget exhausted without a failure: call it clean

    def _quarantine(self, err: Exception) -> None:
        """The step failed twice on the same state: blame the most
        recently admitted request(s) instead of aborting the world.

        Requests that have not emitted a token yet (just-admitted — the
        usual poison: a prompt whose prefill trips the fault) can be
        safely re-prefilled, so they are pulled out and bisected back in;
        only the subset whose re-admission still fails the step is
        evicted.  A control step with the suspects removed guards the
        other direction: if the fault persists without them, it lives in
        an already-emitting request — the suspects are re-admitted
        untouched and requests are shed newest-first instead (bounded
        collateral, never abort-all)."""
        self._emit_stage.flush()   # bisection emits directly from here
        active = self._active_by_recency()
        suspects = [r for r in active if not r.output_tokens]
        emitting = [r for r in active if r.output_tokens]
        if suspects:
            for r in suspects:
                self.engine.abort(r.id)   # keep subscribers: clones re-emit
            if emitting and self.engine.has_work():
                # control step: suspects quarantined, only the emitting
                # set runs.  A failure here exonerates the suspects.
                try:
                    emitted = self._step_once()
                    self.steps += 1
                    self._emit(emitted)
                except Exception:  # noqa: BLE001 — fault is in the batch
                    for r in suspects:
                        try:
                            self.engine.add_request(
                                self._clone_for_readmit(r)
                            )
                        except Exception as e:  # noqa: BLE001
                            self._evict(r, f"engine rejected request: {e}")
                    self._evict_victim(
                        emitting,
                        f"evicted after repeated engine step failures "
                        f"({err})",
                    )
                    return
            culprits: list = []
            stack = [suspects]
            while stack:
                group = stack.pop()
                if self._trial(group):
                    continue
                if len(group) == 1:
                    culprits.append(group[0])
                    continue
                mid = len(group) // 2
                stack.append(group[:mid])    # older half
                stack.append(group[mid:])    # newer half tested first
            for r in culprits:
                self.quarantine_evictions += 1
                msg = (
                    f"request quarantined: engine step failed while "
                    f"scheduled ({err})"
                )
                self.flight.note_anomaly(
                    "quarantine", request_id=r.id, detail=msg[:200]
                )
                self._audit(
                    "quarantine",
                    tenant=getattr(r, "tenant", ANON_TENANT),
                    trace_id=r.trace_id or "", request_id=r.id,
                    detail=msg,
                )
                log.warning(
                    "engine '%s' quarantined request_id=%s trace_id=%s: %s",
                    self.name, r.id, r.trace_id or "-", msg,
                    extra={"trace_id": r.trace_id or "", "request_id": r.id},
                )
                if r.trace_id:
                    now = time.monotonic()
                    self._trace.record(
                        r.trace_id, "quarantine", now, now,
                        plane="engine", request_id=r.id, reason=msg,
                    )
                self._forget_request(r.id)
                cb = self._subscribers.pop(r.id, None)
                if cb:
                    cb(
                        TokenEvent(
                            request_id=r.id, token_id=-1, finished=True,
                            finish_reason="error", error=msg,
                        )
                    )
            if culprits:
                self._barren_rounds = 0
                return
            # all suspects came back clean: either the fault was
            # transient (give the loop one more chance) or it lives in an
            # already-emitting request (shed newest-first next round)
            self._barren_rounds += 1
            if self._barren_rounds < 2:
                return
        # no fresh suspect to blame — shed the policy's pick (baseline:
        # the most recently admitted active request) and let the loop
        # retry with the remainder
        if active:
            self._evict_victim(
                active,
                f"evicted after repeated engine step failures ({err})",
            )

    def _fail_all(self, msg: str) -> None:
        self._emit_stage.flush()   # no error frame may overtake tokens
        for req in self._active_by_recency():
            self.engine.abort(req.id)
            self._forget_request(req.id)
            cb = self._subscribers.pop(req.id, None)
            if cb:
                cb(
                    TokenEvent(
                        request_id=req.id, token_id=-1, finished=True,
                        finish_reason="error", error=msg,
                    )
                )
