"""Persistent filestore tier for the KV residency ladder (ISSUE 14).

The ladder so far: HBM (PageAllocator + PrefixCache) -> host RAM
(``HostPagePool``, PR 6) -> peer runner (request snapshots, PR 11).
This module adds the bottom rung: a **persistent, content-addressed
blob store** for full prefix-cache pages, backed by the same rooted
local-FS ``control.filestore.Filestore`` the control plane serves user
files from (a shared filesystem in production, a local dir in dev).

Why it exists: agent fleets replay the same system prompts for days.
The HBM prefix cache dies with the process and the host tier dies with
the host; the filestore tier survives restarts, so a rolling deploy (or
a brand-new decode-pool runner) serves a warm prefix without
recomputing it.

Contract (the degrade-to-local discipline):

- blobs are **content-addressed** by the engine's prefix-chain digest
  (``PrefixCache.page_hashes``) namespaced by model + KV geometry, so a
  blob can only ever be adopted by an engine whose pool it is
  bit-compatible with;
- every blob carries the same ``page_checksum`` digest the host tier
  and request snapshots use, verified on EVERY read BEFORE any engine
  state is touched — a corrupt or truncated blob is dropped, counted
  (``helix_filestore_kv_corrupt_total``) and treated as a miss: the
  prompt recomputes, it never errors and never attends wrong KV;
- writes are **quota'd per tenant** (PR 7 identity): the adopting
  request's tenant is charged; past ``HELIX_FILESTORE_KV_QUOTA_BYTES``
  new writes are rejected with a typed counter, reads are never gated.

The ``helix_filestore_kv_*`` metric family is minted ONLY here
(``tools/lint_metrics.py`` contract 10); the runner's /metrics calls
``collect_filestore_kv``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("helix.kv_filestore")

# ---------------------------------------------------------------------------
# metric vocabulary (lint_metrics contract 10: minted only in this module)
# ---------------------------------------------------------------------------

FILESTORE_KV_HITS = "helix_filestore_kv_hits_total"
FILESTORE_KV_MISSES = "helix_filestore_kv_misses_total"
FILESTORE_KV_CORRUPT = "helix_filestore_kv_corrupt_total"
FILESTORE_KV_STORES = "helix_filestore_kv_stores_total"
FILESTORE_KV_QUOTA_REJECTS = "helix_filestore_kv_quota_rejects_total"
FILESTORE_KV_STORE_DROPS = "helix_filestore_kv_store_drops_total"
FILESTORE_KV_BYTES = "helix_filestore_kv_bytes"

_PAGE_FIELDS = ("k", "v", "k_scale", "v_scale")


def kv_filestore_dir() -> str:
    """HELIX_FILESTORE_KV_DIR: root of the persistent KV blob store
    ('' = tier off)."""
    return os.environ.get("HELIX_FILESTORE_KV_DIR", "")


def kv_filestore_quota_bytes() -> int:
    """HELIX_FILESTORE_KV_QUOTA_BYTES: per-tenant write quota (0 =
    unlimited)."""
    try:
        return int(os.environ.get("HELIX_FILESTORE_KV_QUOTA_BYTES", "0")
                   or 0)
    except (TypeError, ValueError):
        return 0


def _encode_array(a) -> Optional[dict]:
    if a is None:
        return None
    import base64

    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(doc) -> Optional[np.ndarray]:
    if doc is None:
        return None
    import base64

    raw = base64.b64decode(doc["b64"])
    a = np.frombuffer(raw, dtype=np.dtype(doc["dtype"]))
    return a.reshape([int(d) for d in doc["shape"]]).copy()


class KVFilestore:
    """Content-addressed page-blob store over ``control.filestore``.

    Thread contract: ``contains``/``get``/``put`` run on the engine
    thread; the /metrics collector reads the counter snapshot from the
    scrape thread (plain GIL-atomic int reads)."""

    # blobs live under one reserved owner prefix in the backing store —
    # user file traffic and KV blobs can share a filestore root without
    # colliding (Filestore._resolve keeps owners disjoint)
    OWNER = "kv-pages"

    def __init__(self, root: str, namespace: str,
                 quota_bytes: Optional[int] = None):
        from helix_tpu.control.filestore import Filestore

        self.store = Filestore(root)
        # geometry namespace: blobs are only visible to bit-compatible
        # pools (model + page_size + layers + heads + head_dim + dtype)
        self.namespace = namespace
        self.quota_bytes = (
            quota_bytes if quota_bytes is not None
            else kv_filestore_quota_bytes()
        )
        self._lock = threading.Lock()
        # typed counters (the degrade ladder's observability)
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.stores = 0
        self.quota_rejects = 0
        self.store_drops = 0
        # single background writer for put_async (lazily started): the
        # engine thread must not pay D2H fetch + encode + disk latency
        # at adoption time
        self._writeq = None
        self._writer = None
        # positive-presence cache: contains() is called per page per
        # admission retry; misses fall through to the filesystem so
        # blobs written by a PEER process (shared filesystem) are found
        self._known: set = set()
        # per-tenant usage ledger, persisted next to the blobs so the
        # quota survives restarts (advisory across processes)
        self._usage: dict = self._load_usage()

    @staticmethod
    def namespace_for(model: str, page_size: int, num_layers: int,
                      kv_heads: int, head_dim: int, kv_dtype: str) -> str:
        h = hashlib.blake2b(digest_size=8)
        h.update(
            f"{model}|{page_size}|{num_layers}|{kv_heads}|{head_dim}|"
            f"{kv_dtype}".encode()
        )
        return h.hexdigest()

    # -- paths / ledger ----------------------------------------------------
    def _path(self, digest) -> str:
        d = digest.hex() if isinstance(digest, bytes) else str(digest)
        return f"{self.namespace}/{d[:2]}/{d}.json"

    def _usage_path(self) -> str:
        return f"{self.namespace}/usage.json"

    def _load_usage(self) -> dict:
        try:
            doc = json.loads(
                self.store.read(self.OWNER, self._usage_path())
            )
            return {str(k): int(v) for k, v in doc.items()}
        except FileNotFoundError:
            return {}
        except Exception:  # noqa: BLE001 — a mangled ledger resets, never errors
            return {}

    def _save_usage(self) -> None:
        try:
            self.store.write(
                self.OWNER, self._usage_path(),
                json.dumps(self._usage).encode(),
            )
        except OSError:
            log.warning("could not persist KV filestore usage ledger")

    def usage(self, tenant: str) -> int:
        with self._lock:
            return int(self._usage.get(tenant, 0))

    # -- blob operations ---------------------------------------------------
    def contains(self, digest) -> bool:
        d = digest.hex() if isinstance(digest, bytes) else str(digest)
        if d in self._known:
            return True
        try:
            self.store.stat(self.OWNER, self._path(d))
        except (FileNotFoundError, PermissionError, OSError):
            return False
        self._known.add(d)
        return True

    def get(self, digest) -> Optional[dict]:
        """The stored page entry for ``digest`` (the ``gather_pages``
        field layout, checksum-verified), or None on miss/corruption.
        A corrupt blob is DELETED and counted — the caller recomputes;
        the next writer re-stores a good copy."""
        from helix_tpu.engine.kv_cache import page_checksum

        d = digest.hex() if isinstance(digest, bytes) else str(digest)
        try:
            raw = self.store.read(self.OWNER, self._path(d))
        except (FileNotFoundError, PermissionError, OSError):
            self.misses += 1
            self._known.discard(d)
            return None
        try:
            doc = json.loads(raw)
            entry = {
                f: _decode_array((doc.get("page") or {}).get(f))
                for f in _PAGE_FIELDS
            }
            claimed = str(doc.get("checksum", ""))
            if entry["k"] is None or entry["v"] is None:
                raise ValueError("page missing k/v buffers")
            if page_checksum(entry).hex() != claimed:
                raise ValueError("page checksum mismatch")
        except Exception as e:  # noqa: BLE001 — corrupt blob = typed miss
            self.corrupt += 1
            self._known.discard(d)
            log.warning(
                "dropping corrupt KV filestore blob %s: %s", d, e
            )
            try:
                self.store.delete(self.OWNER, self._path(d))
            except (PermissionError, OSError):
                pass
            return None
        self.hits += 1
        self._known.add(d)
        return entry

    def put(self, digest, entry: dict, tenant: str = "") -> bool:
        """Store one page blob, charged to ``tenant``'s quota.  False =
        not stored (already present is True, quota reject is False with
        a typed counter).  Never raises into the engine."""
        from helix_tpu.engine.kv_cache import page_checksum

        d = digest.hex() if isinstance(digest, bytes) else str(digest)
        if self.contains(d):
            return True
        charged = 0
        try:
            host = {
                f: None if entry.get(f) is None
                else np.asarray(entry[f])
                for f in _PAGE_FIELDS
            }
            doc = {
                "namespace": self.namespace,
                "tenant": tenant,
                "checksum": page_checksum(host).hex(),
                "page": {
                    f: _encode_array(host[f]) for f in _PAGE_FIELDS
                },
            }
            raw = json.dumps(doc).encode()
            with self._lock:
                if self.quota_bytes and (
                    self._usage.get(tenant, 0) + len(raw)
                    > self.quota_bytes
                ):
                    self.quota_rejects += 1
                    return False
                self._usage[tenant] = (
                    self._usage.get(tenant, 0) + len(raw)
                )
                charged = len(raw)
            self.store.write(self.OWNER, self._path(d), raw)
            self._save_usage()
        except Exception:  # noqa: BLE001 — the tier degrades, never errors
            if charged:
                # the blob never landed: un-charge the tenant, or
                # repeated write failures would eat the quota with
                # nothing stored against it
                with self._lock:
                    self._usage[tenant] = max(
                        0, self._usage.get(tenant, 0) - charged
                    )
            log.exception("KV filestore store failed for %s", d)
            return False
        self.stores += 1
        self._known.add(d)
        return True

    def put_async(self, digest, entry: dict, tenant: str = "") -> None:
        """Queue ``put`` on the store's single writer thread.  The
        engine calls this at adoption time with still-on-device arrays;
        the worker pays the D2H fetch (``np.asarray`` inside ``put``),
        the encode, and the disk write so the serving hot path never
        stalls on the persistent tier.  Bounded queue: under sustained
        pressure writes DROP with a typed counter — the tier degrades
        (a dropped page is just a future miss), serving never blocks."""
        import queue as _queue

        with self._lock:
            if self._writer is None:
                self._writeq = _queue.Queue(maxsize=256)
                self._writer = threading.Thread(
                    target=self._write_loop, daemon=True,
                    name="kv-filestore-writer",
                )
                self._writer.start()
        try:
            self._writeq.put_nowait((digest, entry, tenant))
        except _queue.Full:
            self.store_drops += 1

    def _write_loop(self) -> None:
        while True:
            digest, entry, tenant = self._writeq.get()
            try:
                self.put(digest, entry, tenant=tenant)
            except Exception:  # noqa: BLE001 — the tier degrades, never dies
                log.exception(
                    "async KV filestore store failed for %s", digest
                )
            finally:
                self._writeq.task_done()

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every queued async write has landed (tests and
        graceful shutdown — NOT the serving path)."""
        import time as _time

        q = self._writeq
        if q is None:
            return
        deadline = _time.monotonic() + timeout
        while q.unfinished_tasks and _time.monotonic() < deadline:
            _time.sleep(0.005)

    # -- observability -----------------------------------------------------
    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._usage.values())

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "corrupt": self.corrupt,
            "stores": self.stores,
            "quota_rejects": self.quota_rejects,
            "store_drops": self.store_drops,
            "bytes": self.total_bytes(),
            "quota_bytes": self.quota_bytes,
            "namespace": self.namespace,
        }


def filestore_for_engine(root: str, model_cfg, cache_cfg,
                         quota_bytes: Optional[int] = None) -> KVFilestore:
    """Bind a store to one engine's KV geometry (the namespace that
    makes content addressing safe across mixed fleets)."""
    ns = KVFilestore.namespace_for(
        model_cfg.name, cache_cfg.page_size, model_cfg.num_layers,
        model_cfg.num_kv_heads, model_cfg.head_dim, cache_cfg.dtype,
    )
    return KVFilestore(root, ns, quota_bytes=quota_bytes)


def collect_filestore_kv(c, loop, labels: dict) -> None:
    """Runner-side filestore-tier series for one engine loop (called
    from the OpenAI server's scrape-time collector; no-op when the tier
    is off)."""
    fs = getattr(loop.engine, "kv_filestore", None)
    if fs is None:
        return
    c.counter(
        FILESTORE_KV_HITS, fs.hits, labels,
        help="Prefix pages restored from the persistent filestore tier",
    )
    c.counter(
        FILESTORE_KV_MISSES, fs.misses, labels,
        help="Filestore lookups that found no blob (prompt recomputed)",
    )
    c.counter(
        FILESTORE_KV_CORRUPT, fs.corrupt, labels,
        help="Corrupt/truncated blobs dropped pre-adoption "
             "(recompute, never an error)",
    )
    c.counter(
        FILESTORE_KV_STORES, fs.stores, labels,
        help="Full prefix pages persisted to the filestore tier",
    )
    c.counter(
        FILESTORE_KV_QUOTA_REJECTS, fs.quota_rejects, labels,
        help="Writes rejected by the per-tenant filestore quota",
    )
    c.counter(
        FILESTORE_KV_STORE_DROPS, fs.store_drops, labels,
        help="Async write-throughs dropped at the bounded writer queue",
    )
    c.gauge(
        FILESTORE_KV_BYTES, fs.total_bytes(), labels,
        help="Bytes of KV blobs this engine's namespace holds",
    )
