"""OpenAI- and Anthropic-compatible HTTP surface over the engine.

Mirrors the reference's public inference surface exactly (the routes its
inference-proxy forwards: ``/v1/chat/completions``, ``/v1/completions``,
``/v1/embeddings``, ``/v1/models`` — ``api/pkg/inferenceproxy/proxy.go:
94-120`` — plus the native Anthropic ``/v1/messages`` proxy surface,
``api/pkg/anthropic/anthropic_proxy.go:32-40``), so a reference control
plane can point at this server the way it points at a vLLM container.

SSE framing follows OpenAI: ``data: {json}\n\n`` chunks, closing
``data: [DONE]``; Anthropic streaming emits the event-typed frames
(message_start / content_block_delta / message_stop).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from typing import Optional

from aiohttp import web

from helix_tpu import obs
from helix_tpu.engine.engine import Request, SnapshotError
from helix_tpu.engine.sampling import SamplingParams
from helix_tpu.obs.canary import collect_canary_metrics, default_prober
from helix_tpu.obs.slo import ANON_TENANT, TENANT_HEADER, sanitize_tenant
from helix_tpu.engine.adapters import (
    ADAPTER_SEP,
    MAX_LISTED_ADAPTERS,
    collect_adapter_metrics,
    sanitize_adapter_id,
    split_model_adapter,
)
from helix_tpu.serving.sched import CLASS_HEADER, sanitize_class
from helix_tpu.obs.trace import (
    TRACE_HEADER,
    adopt_trace_id,
    collect_trace_metrics,
    is_trace_id,
)
from helix_tpu.serving.engine_loop import (
    KV_EXHAUSTED,
    QUEUE_FULL,
    SHUTTING_DOWN,
)
from helix_tpu.serving.context_cache import (
    collect_ctx_metrics,
    context_cache_for,
)
from helix_tpu.serving.kv_filestore import collect_filestore_kv, kv_filestore_dir
from helix_tpu.serving.multihost_serving import collect_mh_metrics
from helix_tpu.serving.migration import (
    DISAGG_HEADER,
    DISAGG_PEER_ADDR_HEADER,
    DISAGG_PEER_ID_HEADER,
    MIGRATED,
    ImportedStream,
    ImportedStreams,
    XferConfig,
    collect_runner_migration,
    collect_xfer,
    make_chunk,
    migrated_error,
    migration_timeout,
    wire_to_snapshot,
)
from helix_tpu.serving.registry import ModelRegistry
from helix_tpu.serving.tokenizer import IncrementalDetokenizer, _content_text


def _now() -> int:
    return int(time.time())


_LONGPOLL_POOL = None


def _longpoll_pool():
    """Dedicated pool for multi-host journal long-polls (they park a
    thread for tens of seconds each)."""
    global _LONGPOLL_POOL
    if _LONGPOLL_POOL is None:
        import concurrent.futures

        _LONGPOLL_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="mh-longpoll"
        )
    return _LONGPOLL_POOL


def _error(status: int, message: str, etype: str = "invalid_request_error",
           headers: Optional[dict] = None, trace_id: str = "",
           request_id: str = "", code: str = ""):
    """Structured error body.  When a trace id is known it rides both the
    body and the response header, so a failing request can be correlated
    from the client straight to runner logs and /v1/debug/traces."""
    err: dict = {"message": message, "type": etype}
    if code:
        err["code"] = code
    if trace_id:
        err["trace_id"] = trace_id
        headers = {**(headers or {}), TRACE_HEADER: trace_id}
    if request_id:
        err["request_id"] = request_id
    return web.json_response({"error": err}, status=status, headers=headers)


class EngineRequestError(Exception):
    """A request the engine rejected or failed mid-flight; surfaces as a
    structured 4xx/5xx instead of a dead stream."""

    def __init__(self, message: str, request_id: str = ""):
        super().__init__(message)
        self.request_id = request_id


def _engine_error_response(e: Exception, trace_id: str = ""):
    """Map an engine error onto its HTTP shape: shed load is a clean 429
    with Retry-After, drain is 503, engine timeouts are 504, everything
    else stays the seed's 400."""
    msg = str(e)
    rid = getattr(e, "request_id", "")
    if msg.startswith(QUEUE_FULL):
        return _error(429, msg, "overloaded_error",
                      headers={"Retry-After": "1"}, trace_id=trace_id,
                      request_id=rid)
    if msg.startswith(KV_EXHAUSTED):
        # typed KV-exhaustion shed (ISSUE 6): the engine is out of KV
        # pages and the request outwaited (or would outwait) the
        # admission deadline — clean 503 + Retry-After, code kv_exhausted
        return _error(503, msg, "overloaded_error",
                      headers={"Retry-After": "2"}, trace_id=trace_id,
                      request_id=rid, code="kv_exhausted")
    if msg.startswith(SHUTTING_DOWN):
        return _error(503, msg, "overloaded_error",
                      headers={"Retry-After": "5"}, trace_id=trace_id,
                      request_id=rid)
    if msg.startswith(MIGRATED):
        # the request was exported to a peer at the drain deadline
        # (ISSUE 11): the control plane's mid-stream failover resumes
        # SSE streams in place; non-stream callers get a typed retry
        return _error(503, msg, "overloaded_error",
                      headers={"Retry-After": "1"}, trace_id=trace_id,
                      request_id=rid, code="migrated")
    if msg.startswith("inter_token_timeout"):
        return _error(504, msg, "timeout_error", trace_id=trace_id,
                      request_id=rid)
    return _error(400, msg, trace_id=trace_id, request_id=rid)


def _sse_error_frame(e: Exception, trace_id: str = "") -> dict:
    """In-band SSE error payload with correlation ids (a quarantined
    request's client error names the trace/request the runner logged)."""
    err: dict = {"message": str(e)}
    if trace_id:
        err["trace_id"] = trace_id
    rid = getattr(e, "request_id", "")
    if rid:
        err["request_id"] = rid
    return {"error": err}


class OpenAIServer:
    def __init__(self, registry: ModelRegistry, metrics=None,
                 inter_token_timeout: Optional[float] = None,
                 obs_registry: Optional[obs.Registry] = None,
                 trace_store: Optional[obs.TraceStore] = None):
        import os
        from helix_tpu.serving.logbuf import install as install_logbuf

        self.registry = registry
        self.metrics = metrics
        self.started = time.monotonic()
        self.logbuf = install_logbuf()
        # shared metrics registry (obs): every runner-side series renders
        # through it — engine counters/gauges attach per model at scrape
        # time, latency histograms come from each EngineLoop's obs bundle
        self.obs = obs_registry or obs.Registry()
        self.obs.register_callback(self._collect_metrics)
        # identity check, not truthiness: an EMPTY TraceStore is falsy
        # (__len__ == 0) but still the caller's store
        self.traces = (trace_store if trace_store is not None
                       else obs.default_store())
        self._profiler_lock = threading.Lock()
        # migrated-in requests awaiting their resumed stream (ISSUE 11):
        # the peer engine may start generating before the control plane
        # attaches, so token events buffer here until /v1/migrate/resume
        # claims them (or the migration timeout aborts the orphan)
        self._imported = ImportedStreams()
        # context-caching registry (ISSUE 20): shared with the node
        # agent's heartbeat block via the per-root singleton; persisted
        # through the PR 14 filestore root when one is armed
        self.ctx_cache = context_cache_for(kv_filestore_dir())
        # max seconds between consecutive engine events for one request
        # before the server gives up on it (wedged engine watchdog)
        self.inter_token_timeout = (
            inter_token_timeout
            if inter_token_timeout is not None
            else float(os.environ.get("HELIX_INTER_TOKEN_TIMEOUT", "300"))
        )

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/healthz", self.healthz)
        app.router.add_get("/metrics", self.prometheus_metrics)
        app.router.add_get("/logs", self.tail_logs)
        app.router.add_post("/admin/prefetch", self.prefetch_model)
        app.router.add_get("/v1/models", self.list_models)
        # multi-LoRA registry surface (ISSUE 15): publish a trained
        # LoRA checkpoint for `model@adapter` serving — no restart, no
        # hot-swap, no recompile (the pool shape compiled at warmup)
        app.router.add_post("/v1/adapters", self.publish_adapter)
        # context-caching API (ISSUE 20): persist a prompt prefix once
        # (prefilled + adopted into the residency ladder), reference it
        # from chat/completions via context_id — the cached span's
        # prefill is skipped on every reuse
        app.router.add_post("/v1/context", self.create_context)
        app.router.add_get("/v1/context", self.list_contexts)
        app.router.add_post("/v1/chat/completions", self.chat_completions)
        app.router.add_post("/v1/completions", self.completions)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/messages", self.anthropic_messages)
        # request tracing + on-demand device profiling (obs)
        app.router.add_get("/v1/debug/traces", self.debug_traces_list)
        app.router.add_get(
            "/v1/debug/traces/{trace_id}", self.debug_trace
        )
        # engine flight recorder: per-step saturation ring + frozen
        # anomaly snapshots (ISSUE 4)
        app.router.add_get("/v1/debug/flight", self.debug_flight)
        # admission-decision audit trail: every shed / quarantine /
        # preemption with its tenant + trace id (ISSUE 7)
        app.router.add_get("/v1/debug/admissions", self.debug_admissions)
        # cross-runner migration (ISSUE 11): a peer ships a request
        # snapshot in; the control plane re-attaches the client stream
        app.router.add_post("/v1/migrate/import", self.migrate_import)
        app.router.add_post("/v1/migrate/resume", self.migrate_resume)
        app.router.add_post("/admin/profiler", self.profiler_capture)
        # multi-host step-plan feed (followers long-poll over DCN;
        # see serving/multihost_serving.py).  The route keeps its
        # historical name — followers of either wire version find it,
        # and the version field inside each record does the rejecting.
        app.router.add_get("/multihost/commands", self.multihost_commands)
        return app

    async def multihost_commands(self, request):
        """Leader-side plan feed for follower hosts."""
        import asyncio as _asyncio

        from helix_tpu.serving.multihost_serving import LagError

        model = request.query.get("model", "")
        served = self.registry.get(model)
        if served is None or served.loop is None:
            return _error(404, f"model '{model}' is not served here")
        # multihost-ok: transport plumbing (serving the PlanLeader's
        # ring), not a feature guard
        journal = getattr(served.loop.engine, "journal", None)
        if journal is None:
            return _error(
                400, f"model '{model}' is not running as a multihost "
                "leader"
            )
        since = int(request.query.get("since", 0))
        timeout = min(float(request.query.get("timeout", 25)), 55.0)
        # per-follower registration + health (ISSUE 17): HTTPFeed sends
        # the follower's identity and applied position as query params;
        # the leader's bounded registry drives the lag ladder and the
        # helix_mh_follower_* family.  multihost-ok: transport plumbing.
        note = getattr(served.loop.engine, "note_poll", None)
        fid = request.query.get("follower_id", "")
        if note is not None and fid:
            def _qint(key):
                v = request.query.get(key)
                try:
                    return int(v) if v is not None else None
                except ValueError:
                    return None

            try:
                apply_ms = float(request.query.get("apply_ms", ""))
            except ValueError:
                apply_ms = None
            note(
                fid[:128], since,
                applied_step=_qint("applied_step"),
                apply_ms=apply_ms,
                digest_checks=_qint("digest_checks"),
                digest_mismatches=_qint("digest_mismatches"),
                standby=request.query.get("standby", "0")
                in ("1", "true"),
            )
        try:
            # long-polls park a thread for up to ``timeout`` — keep them
            # out of the shared default executor or a few followers
            # would starve every other run_in_executor call
            records = await _asyncio.get_running_loop().run_in_executor(
                _longpoll_pool(), journal.read_since, since, timeout
            )
        except LagError as e:
            return web.json_response({"lagged": True, "error": str(e)})
        return web.json_response({"records": records})

    # ------------------------------------------------------------------
    async def healthz(self, request):
        return web.json_response(
            {"status": "ok", "models": self.registry.names()}
        )

    async def prometheus_metrics(self, request):
        """Prometheus text surface, rendered by the shared obs registry.
        Runs in an executor: scrape-time collectors take live locks (the
        residency manager's stats() lock is held across whole model
        builds) and must never block the event loop."""
        text = await asyncio.get_running_loop().run_in_executor(
            None, self.obs.render
        )
        return web.Response(text=text)

    def _collect_metrics(self, c: "obs.Collector") -> None:
        """Scrape-time collection from every live engine (per-model
        labels) + the residency manager.  Counter/gauge values are plain
        GIL-atomic int reads off the engine thread's state."""
        c.gauge(
            "helix_uptime_seconds", time.monotonic() - self.started,
            help="Runner process uptime",
        )
        # KV-transfer outcomes (ISSUE 14): process-wide (drain shippers
        # and disagg handoffs share one ledger), minted ONLY by
        # serving/migration.py (lint contract 10)
        collect_xfer(c)
        # trace-loss series (ISSUE 18): spans lost to the per-trace cap
        # or the federation export ring, minted ONLY by obs/trace.py
        # (lint contract 13)
        collect_trace_metrics(c, self.traces)
        # correctness-canary series (ISSUE 19): health rung + probe /
        # mismatch counters from the node agent's prober, minted ONLY
        # by obs/canary.py (lint contract 14); no-op until one starts
        collect_canary_metrics(c, default_prober())
        # context-caching registry (ISSUE 20): handle/token gauges and
        # create/hit/miss/quota counters, minted ONLY by
        # serving/context_cache.py (lint contract 15)
        collect_ctx_metrics(c, self.ctx_cache)
        for m in self.registry.list():
            if m.loop is None:
                continue
            eng = m.loop.engine
            lbl = {"model": m.name}
            c.counter("helix_engine_steps", m.loop.steps, lbl)
            c.counter(
                "helix_prefill_tokens_total", eng.num_prefill_tokens, lbl
            )
            c.counter(
                "helix_decode_tokens_total", eng.num_decode_tokens, lbl
            )
            # ragged mixed steps: chunk prefill + decode in ONE call
            c.counter(
                "helix_mixed_steps_total",
                getattr(eng, "num_mixed_steps", 0), lbl,
            )
            # MoE prefill routing assignments dropped to expert-capacity
            # overflow (rode the residual stream instead)
            c.counter(
                "helix_moe_dropped_tokens_total",
                getattr(eng, "moe_dropped_tokens", 0), lbl,
            )
            # speculative decoding (ISSUE 5): host-drafted tokens, the
            # subset the verify pass accepted, lifetime acceptance, and
            # slots the per-request EMA currently benches
            c.counter(
                "helix_spec_drafted_tokens_total",
                getattr(eng, "num_spec_drafted_tokens", 0), lbl,
            )
            c.counter(
                "helix_spec_accepted_tokens_total",
                getattr(eng, "num_spec_accepted_tokens", 0), lbl,
            )
            c.gauge(
                "helix_spec_acceptance_ratio",
                getattr(eng, "spec_acceptance_ratio", 0.0), lbl,
            )
            spec_disabled = getattr(eng, "spec_disabled_slots", None)
            c.gauge(
                "helix_spec_disabled_slots",
                spec_disabled() if callable(spec_disabled) else 0, lbl,
            )
            c.gauge("helix_waiting_requests", len(eng.waiting), lbl)
            c.gauge(
                "helix_active_slots",
                sum(1 for s in eng.slots if s is not None), lbl,
            )
            c.gauge("helix_free_pages", eng.allocator.free_pages, lbl)
            # robustness spine: step failure/retry/quarantine/shed
            # accounting (ISSUE 2)
            c.counter(
                "helix_step_failures_total",
                getattr(m.loop, "step_failures", 0), lbl,
            )
            c.counter(
                "helix_step_retries_total",
                getattr(m.loop, "step_retries", 0), lbl,
            )
            c.counter(
                "helix_quarantine_evictions_total",
                getattr(m.loop, "quarantine_evictions", 0), lbl,
            )
            c.counter(
                "helix_shed_requests_total",
                getattr(m.loop, "shed_requests", 0), lbl,
            )
            # asynchronous pipelined loop (ISSUE 13): how often the loop
            # dispatched step N+1 while step N was still executing, and
            # the flight-window fraction of serving time the device had
            # nothing dispatched (the pipeline's headline gauge — the
            # sync loop's build+emit shadow shows up here)
            c.counter(
                "helix_pipelined_steps_total",
                getattr(m.loop, "pipelined_steps", 0), lbl,
            )
            if hasattr(m.loop, "device_idle_ratio"):
                c.gauge(
                    "helix_device_idle_ratio",
                    round(m.loop.device_idle_ratio(), 4), lbl,
                )
            # latency histograms (TTFT / queue wait / inter-token / step
            # duration) observed by the engine loop itself
            loop_obs = getattr(m.loop, "obs", None)
            if loop_obs is not None:
                loop_obs.collect(c, lbl)
            # saturation / capacity-efficiency gauges (ISSUE 4): how full
            # the machine is and where the capacity goes
            self._collect_saturation(c, m, eng, lbl)
            # per-tenant SLO series (ISSUE 7): bounded top-K + __other__
            # accounting and burn-rate gauges — obs/slo.py is the ONLY
            # legal emitter of tenant-labelled samples (lint contract 4)
            slo = getattr(m.loop, "slo", None)
            if slo is not None:
                slo.collect(c, lbl)
            # scheduler policy series (ISSUE 9): helix_sched_* samples
            # are minted ONLY by serving/sched.py (lint contract 5)
            sched = getattr(m.loop, "sched", None)
            if sched is not None:
                sched.collect(c, lbl)
            # cross-runner migration series (ISSUE 11): minted ONLY by
            # serving/migration.py (lint contract 6)
            collect_runner_migration(c, m.loop, lbl)
            # persistent filestore KV tier (ISSUE 14): minted ONLY by
            # serving/kv_filestore.py (lint contract 10)
            collect_filestore_kv(c, m.loop, lbl)
            # continuous multi-LoRA serving (ISSUE 15): helix_adapter_*
            # series are minted ONLY by engine/adapters.py (lint
            # contract 11)
            collect_adapter_metrics(c, m.loop, lbl)
            # N-follower mesh health + failover accounting (ISSUE 17):
            # helix_mh_* series are minted ONLY by
            # serving/multihost_serving.py (lint contract 12)
            collect_mh_metrics(c, m.loop, lbl)
            pc = getattr(eng, "prefix_cache", None)
            if pc is not None:
                st = pc.stats
                c.gauge("helix_prefix_cache_pages", st["pages"], lbl)
                c.counter(
                    "helix_prefix_cache_hit_pages_total", st["hits"], lbl
                )
                c.counter(
                    "helix_prefix_cache_miss_pages_total", st["misses"], lbl
                )
                # request-level hit/miss + eviction pressure (ISSUE 4)
                c.counter(
                    "helix_prefix_cache_hits_total",
                    getattr(eng, "prefix_cache_hits", 0), lbl,
                )
                c.counter(
                    "helix_prefix_cache_misses_total",
                    getattr(eng, "prefix_cache_misses", 0), lbl,
                )
                c.counter(
                    "helix_prefix_cache_evicted_pages_total",
                    st.get("evicted_pages", 0), lbl,
                )
            ttfts = getattr(eng, "recent_ttfts", None)
            if ttfts:
                # rolling-window percentiles kept for dashboard
                # continuity (the histogram is the durable surface).
                # The engine thread appends concurrently; a mutation
                # during iteration raises — retry on a fresh snapshot
                s = []
                for _ in range(3):
                    try:
                        s = sorted(ttfts)
                        break
                    except RuntimeError:
                        continue
                if s:
                    c.gauge(
                        "helix_ttft_p50_seconds", s[len(s) // 2], lbl
                    )
                    c.gauge(
                        "helix_ttft_p95_seconds",
                        s[min(len(s) - 1, int(len(s) * 0.95))], lbl,
                    )
        mgr = self._residency_manager()
        if mgr is not None:
            st = mgr.stats()
            c.counter("helix_residency_loads_total", st["loads"])
            c.counter("helix_residency_evictions_total", st["evictions"])
            c.gauge("helix_residency_used_bytes", st["used_bytes"])
            c.gauge(
                "helix_residency_budget_bytes", st.get("budget_bytes", 0)
            )
            for name, secs in sorted(st["swap_seconds"].items()):
                c.gauge(
                    "helix_model_swap_seconds", secs, {"model": name}
                )
            for name, secs in sorted(st["load_seconds"].items()):
                c.gauge(
                    "helix_model_load_seconds", secs, {"model": name}
                )

    def _collect_saturation(self, c, m, eng, lbl: dict) -> None:
        """Per-model capacity gauges: KV occupancy + high-water mark,
        decode-slot utilization, queue depth/queued tokens, goodput
        tokens/s, padding waste, and an MFU estimate when a peak-FLOPs
        figure is known.  All values are GIL-atomic host reads."""
        sat = m.loop.saturation()
        used = getattr(eng, "kv_pages_used", 0)
        cap = getattr(eng, "kv_pages_capacity", 1)
        c.gauge("helix_kv_pages_used", used, lbl)
        c.gauge("helix_kv_pages_capacity", cap, lbl)
        c.gauge(
            "helix_kv_pages_used_peak",
            getattr(eng.allocator, "peak_used", 0), lbl,
        )
        c.gauge("helix_kv_occupancy_ratio", sat["kv_occupancy"], lbl)
        c.gauge("helix_decode_slots_busy", sat["slots_busy"], lbl)
        c.gauge("helix_decode_slots_capacity", sat["slots_total"], lbl)
        c.gauge(
            "helix_decode_slot_utilization",
            sat["slots_busy"] / max(1, sat["slots_total"]), lbl,
        )
        c.gauge("helix_queue_depth", sat["queue_depth"], lbl)
        c.gauge("helix_queued_tokens", m.loop.queued_tokens(), lbl)
        c.counter(
            "helix_generated_tokens_total",
            getattr(eng, "num_generated_tokens", 0), lbl,
        )
        c.counter(
            "helix_prefill_padding_tokens_total",
            getattr(eng, "num_prefill_padding_tokens", 0), lbl,
        )
        # ragged unification (ISSUE 10): the shape-zoo collapse made
        # observable — distinct compiled device-step entry points per
        # model, and padding / (padding + useful prefill) over the
        # flight-recorder window
        c.gauge(
            "helix_compiled_step_shapes",
            getattr(eng, "compiled_step_shapes", 0), lbl,
        )
        c.gauge(
            "helix_prefill_padding_ratio", m.loop.padding_ratio(), lbl
        )
        c.gauge(
            "helix_goodput_tokens_per_second", sat["tokens_per_sec"], lbl
        )
        c.gauge(
            "helix_prefix_cache_hit_ratio", sat["prefix_hit_rate"], lbl
        )
        c.counter(
            "helix_flight_anomalies_total",
            m.loop.flight.anomalies_total, lbl,
        )
        # KV tiering + preemption-by-swap (ISSUE 6): host-tier traffic
        # and fullness, swap-out/swap-in counts, parked decoders, typed
        # kv_exhausted sheds, cumulative restore time
        hp = getattr(eng, "host_pool", None)
        if hp is not None:
            c.counter("helix_kv_spilled_pages_total", hp.spilled_pages, lbl)
            c.counter(
                "helix_kv_restored_pages_total", hp.restored_pages, lbl
            )
            c.counter(
                "helix_kv_host_evicted_pages_total", hp.evicted_pages, lbl
            )
            c.counter(
                "helix_kv_host_corrupt_pages_total", hp.corrupt_pages, lbl
            )
            c.counter(
                "helix_kv_host_alloc_failures_total", hp.alloc_failures,
                lbl,
            )
            c.gauge("helix_kv_host_pool_pages", hp.pages, lbl)
            c.gauge("helix_kv_host_pool_used_bytes", hp.used_bytes, lbl)
            c.gauge(
                "helix_kv_host_pool_budget_bytes", hp.budget_bytes, lbl
            )
            c.gauge("helix_kv_host_occupancy_ratio", hp.occupancy, lbl)
            c.counter(
                "helix_kv_restore_seconds_total",
                getattr(eng, "restore_seconds", 0.0), lbl,
            )
        c.counter(
            "helix_preemptions_total",
            getattr(eng, "num_preemptions", 0), lbl,
        )
        c.counter(
            "helix_resumes_total", getattr(eng, "num_resumes", 0), lbl
        )
        c.gauge(
            "helix_preempted_requests",
            len(getattr(eng, "preempted", ())), lbl,
        )
        c.counter(
            "helix_kv_exhausted_sheds_total",
            getattr(m.loop, "kv_exhausted_sheds", 0), lbl,
        )
        peak = self._peak_flops()
        if peak > 0:
            from helix_tpu.engine.residency import model_param_count

            # decode-side MFU estimate: each generated token moves ~2
            # FLOPs per active parameter through the MXU
            c.gauge(
                "helix_mfu_estimate",
                sat["tokens_per_sec"] * 2 * model_param_count(eng.model_cfg)
                / peak,
                lbl,
            )

    @staticmethod
    def _peak_flops() -> float:
        """Peak accelerator FLOP/s for the MFU denominator:
        ``HELIX_PEAK_FLOPS`` when the operator sets it, else the v5e
        bf16 peak on TPU backends, else 0 (gauge omitted)."""
        import os

        v = os.environ.get("HELIX_PEAK_FLOPS", "")
        if v:
            try:
                return float(v)
            except ValueError:
                return 0.0
        try:
            import jax

            if jax.default_backend() in ("tpu", "axon"):
                return 197e12   # v5e bf16 peak; override for other gens
        except Exception:  # noqa: BLE001 — metrics must never raise
            pass
        return 0.0

    # -- tracing + profiling ---------------------------------------------
    @staticmethod
    def _require_runner_token(request):
        """Debug surfaces carry request metadata / cost serving latency:
        when the node has a shared runner token configured, callers must
        present it (``X-Runner-Token``).  Without one (dev, unix-socket,
        behind-the-tunnel deployments) they stay open like /logs."""
        import hmac
        import os

        token = os.environ.get("HELIX_RUNNER_TOKEN", "")
        if token and not hmac.compare_digest(
            request.headers.get("X-Runner-Token", ""), token
        ):
            return _error(403, "requires the runner token")
        return None

    async def debug_traces_list(self, request):
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        return web.json_response({"traces": self.traces.ids()[-100:]})

    async def debug_trace(self, request):
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        tid = request.match_info["trace_id"]
        if request.query.get("format") == "chrome":
            doc = self.traces.chrome_trace(tid)
        else:
            doc = self.traces.get(tid)
        if doc is None:
            return _error(404, f"unknown trace {tid!r}")
        return web.json_response(doc)

    async def debug_flight(self, request):
        """Engine flight recorder: the per-step saturation ring (batch
        composition, KV occupancy, padding waste, step wall time) plus
        the frozen snapshots of the last N anomalies (slow step,
        zero-progress step, step failure, quarantine).  Runner-token
        gated like the other debug surfaces; ``?model=`` filters to one
        engine, ``?recent=`` bounds the live-ring tail returned."""
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        want = request.query.get("model", "")
        try:
            recent = max(1, min(int(request.query.get("recent", 64)), 512))
        except ValueError:
            return _error(400, "recent must be an integer")
        def collect():
            # off the event loop: registry.list() on a residency-backed
            # runner blocks on the build-holding ResidencyManager lock
            # (same rule as the /metrics render above)
            snap = {}
            for m in self.registry.list():
                if m.loop is None or (want and m.name != want):
                    continue
                fl = getattr(m.loop, "flight", None)
                if fl is None:
                    continue
                snap[m.name] = fl.snapshot(recent=recent)
            return snap

        out = await asyncio.get_running_loop().run_in_executor(
            None, collect
        )
        if want and not out:
            return _error(
                404, f"model {want!r} has no engine flight recorder"
            )
        return web.json_response({"models": out})

    async def debug_admissions(self, request):
        """The admission-decision audit trail: a bounded ring per model
        of every 429 shed, typed kv_exhausted shed, quarantine eviction
        and preemption-by-swap — ``(tenant, trace_id, reason, queue
        state)`` at the moment of the decision.  Runner-token gated like
        ``/v1/debug/flight``; ``?model=`` filters, ``?recent=`` bounds
        the tail returned."""
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        want = request.query.get("model", "")
        try:
            recent = max(1, min(int(request.query.get("recent", 64)), 256))
        except ValueError:
            return _error(400, "recent must be an integer")

        def collect():
            # off the event loop: registry.list() on a residency-backed
            # runner blocks on the build-holding lock (debug_flight rule)
            snap = {}
            for m in self.registry.list():
                if m.loop is None or (want and m.name != want):
                    continue
                slo = getattr(m.loop, "slo", None)
                if slo is None:
                    continue
                snap[m.name] = slo.audit.snapshot(recent=recent)
            return snap

        out = await asyncio.get_running_loop().run_in_executor(
            None, collect
        )
        if want and not out:
            return _error(404, f"model {want!r} has no admission audit")
        return web.json_response({"models": out})

    # -- cross-runner migration (ISSUE 11) --------------------------------

    def _sweep_imports(self) -> None:
        """Abort imported requests whose stream was never claimed within
        the migration timeout — a peer must not generate into the void
        because the control plane that planned to resume went away."""
        for stream in self._imported.sweep():
            served = self.registry.get(stream.model)
            if served is not None and served.loop is not None:
                served.loop.abort(stream.request_id)
                served.loop.migration_failures += 1

    async def migrate_import(self, request):
        """Accept one request snapshot from a peer runner (the drain
        ladder's ship step).  Runner-token gated — migration is
        cluster-internal traffic.  The snapshot is decoded, then
        re-admitted on the engine thread where EVERY page checksum is
        verified before any allocator mutation; a corrupt or
        incompatible snapshot fails typed (422) and touches nothing.
        On success the request parks until resources free (a full
        engine queues it behind admission) and its token events buffer
        until ``/v1/migrate/resume`` attaches."""
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        self._sweep_imports()
        t0 = time.monotonic()
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — client error
            return _error(400, "invalid JSON body")
        try:
            snap = wire_to_snapshot(body)
        except SnapshotError as e:
            return _error(422, str(e), "invalid_request_error",
                          code=e.code)
        # adopt the CALLER's trace (ISSUE 18): the shipping peer
        # forwards X-Helix-Trace-Id (PeerShipper bugfix) and the wire
        # snapshot carries trace_id — prefer the header, fall back to
        # the snapshot, never mint (an untraced import stays untraced)
        hdr_tid = request.headers.get(TRACE_HEADER)
        trace_id = hdr_tid if is_trace_id(hdr_tid) else (
            snap.trace_id if is_trace_id(snap.trace_id) else ""
        )

        def _span(outcome: str) -> None:
            self.traces.record(
                trace_id, "migrate import", t0, time.monotonic(),
                plane="runner", request_id=snap.request_id,
                model=snap.model, outcome=outcome,
                prior_tokens=len(snap.output_tokens),
            )
        served, err = await self._lookup(snap.model)
        if err is not None:
            return err
        err = self._require_loop(served, snap.model)
        if err is not None:
            return err
        stream = ImportedStream(
            snap.request_id, snap.model, snap.output_tokens,
            stop=tuple(snap.sampling.get("stop") or ()),
            trace_id=trace_id,
        )
        if not self._imported.register(stream):
            return _error(
                429, "too many unclaimed imported requests",
                "overloaded_error", headers={"Retry-After": "2"},
            )
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_result(err_msg, code):
            def settle():
                if not fut.done():
                    fut.set_result((err_msg, code))

            loop.call_soon_threadsafe(settle)

        served.loop.submit_import(snap, stream.on_event,
                                  on_result=on_result)
        try:
            err_msg, code = await asyncio.wait_for(
                fut, timeout=migration_timeout()
            )
        except asyncio.TimeoutError:
            # the source treats 504 as a failed ship and may re-ship
            # elsewhere — abort the (possibly later-admitted) request so
            # an unregistered orphan can never keep generating here
            self._imported.discard(snap.request_id)
            served.loop.abort(snap.request_id)
            _span("timeout")
            return _error(
                504, "import was not admitted in time", "timeout_error"
            )
        if err_msg is not None:
            self._imported.discard(snap.request_id)
            status = 503 if code == "shutting_down" else 422
            _span(code or "snapshot_invalid")
            return _error(
                status, err_msg, "invalid_request_error",
                code=code or "snapshot_invalid",
            )
        _span("admitted")
        return web.json_response(
            {
                "ok": True,
                "request_id": snap.request_id,
                "model": snap.model,
                "prior_tokens": len(snap.output_tokens),
            }
        )

    async def migrate_resume(self, request):
        """Attach the client stream to a migrated-in request.

        The control plane calls this after a clean source drain: the
        body names the engine request id and how many characters of
        generated text the CLIENT has already received.  The response
        is a neutral SSE delta stream — first the catch-up slice (text
        the source engine emitted but the client never saw), then live
        deltas — which the control plane re-wraps in the client's
        original chunk shape.  Exactly-once: the snapshot's prior
        tokens seed the detokenizer, so character arithmetic against
        ``emitted_chars`` is exact."""
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        self._sweep_imports()
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — client error
            return _error(400, "invalid JSON body")
        rid = str(body.get("request_id", ""))
        try:
            emitted_chars = max(0, int(body.get("emitted_chars", 0) or 0))
        except (TypeError, ValueError):
            return _error(400, "'emitted_chars' must be an integer")
        stream = self._imported.get(rid)
        if stream is None:
            return _error(
                404, f"no imported request {rid!r} awaiting resume"
            )
        served, err = await self._lookup(stream.model)
        if err is not None:
            return err
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        if not stream.attach(loop, q):
            return _error(409, f"request {rid!r} was already resumed")
        self._imported.discard(rid)
        # the resume leg of the migrated timeline (ISSUE 18): stream
        # attach through catch-up-slice sent, under the trace id the
        # import adopted from the shipping peer
        resume_tid = getattr(stream, "trace_id", "")
        t_resume = time.monotonic()
        detok = IncrementalDetokenizer(served.tokenizer)
        prior = ""
        for t in stream.prior_tokens:
            if t not in served.tokenizer.eos_ids:
                prior += detok.push(t)
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)

        async def send(obj):
            await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

        finished = False
        stops = stream.stop
        full = prior                      # everything generated so far
        sent = min(emitted_chars, len(prior))   # chars the client has

        def stop_hit(scan_from: int):
            """Earliest stop-string index at/after ``scan_from`` (a
            stop may SPAN the migration point, so matches straddle the
            prior/resumed boundary)."""
            hit = None
            for s in stops:
                idx = full.find(s, max(0, scan_from - len(s)))
                if idx >= 0:
                    hit = idx if hit is None else min(hit, idx)
            return hit

        try:
            # stop already completed in the prior text (defensive: the
            # source's HTTP handler normally catches this pre-export)
            hit = stop_hit(0)
            if hit is not None:
                finished = True
                served.loop.abort(rid)
                await send(
                    {"request_id": rid, "delta": full[sent:hit],
                     "finish_reason": "stop"}
                )
            elif len(full) > sent:
                # catch-up: text the source engine emitted that the
                # client never saw
                await send(
                    {"request_id": rid, "delta": full[sent:],
                     "catchup": True, "finish_reason": None}
                )
                sent = len(full)
            self.traces.record(
                resume_tid, "migrate resume", t_resume,
                time.monotonic(), plane="runner", request_id=rid,
                catchup_chars=max(0, sent - emitted_chars),
            )
            while not finished:
                try:
                    ev = await asyncio.wait_for(
                        q.get(), timeout=self.inter_token_timeout
                    )
                except asyncio.TimeoutError:
                    await send(
                        {"request_id": rid,
                         "error": {"message": "inter_token_timeout on "
                                              "resumed stream"}}
                    )
                    break
                if ev.error:
                    finished = True
                    await send(
                        {"request_id": rid,
                         "error": {"message": ev.error}}
                    )
                    break
                is_eos = ev.token_id in served.tokenizer.eos_ids
                prev = len(full)
                delta = "" if is_eos else detok.push(ev.token_id)
                full += delta
                hit = stop_hit(prev)
                if hit is not None:
                    # serving-level stop string: truncate exactly like
                    # the ordinary stream handler would have
                    finished = True
                    served.loop.abort(rid)
                    await send(
                        {"request_id": rid,
                         "delta": full[min(sent, hit):hit],
                         "finish_reason": "stop"}
                    )
                    break
                await send(
                    {
                        "request_id": rid,
                        "delta": full[sent:],
                        "finish_reason": (
                            ev.finish_reason if ev.finished else None
                        ),
                    }
                )
                sent = len(full)
                if ev.finished:
                    finished = True
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        finally:
            if not finished and served.loop is not None:
                served.loop.abort(rid)
        return resp

    async def profiler_capture(self, request):
        """On-demand ``jax.profiler`` capture against the live runner:
        POST {"seconds": 2} starts a device+host trace and returns the
        directory to feed TensorBoard/XProf.  One capture at a time; the
        capture runs in an executor so serving traffic keeps flowing
        while it records.

        Trust model: captures are expensive (real serving-latency cost)
        and write to disk, so when ``HELIX_RUNNER_TOKEN`` is set the
        caller must present it (``X-Runner-Token``) — the same shared
        secret the node uses on the control loop.  Capture directories
        are always minted under the system temp dir (or the operator's
        ``HELIX_PROFILER_DIR``); clients never choose the path."""
        import os

        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:  # noqa: BLE001 — client error
            return _error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        try:
            seconds = min(max(float(body.get("seconds", 2.0)), 0.01), 60.0)
        except (TypeError, ValueError):
            return _error(400, "'seconds' must be a number")
        if not self._profiler_lock.acquire(blocking=False):
            return _error(
                409, "a profiler capture is already running",
                "overloaded_error",
            )

        def capture():
            # the CAPTURE THREAD owns the lock release: if the client
            # disconnects and the awaiting handler is cancelled, the
            # capture still runs to completion — releasing in the
            # handler would let a retry call start_trace concurrently
            try:
                import tempfile
                import jax

                base = os.environ.get("HELIX_PROFILER_DIR") or None
                d = tempfile.mkdtemp(prefix="helix-jax-profile-", dir=base)
                jax.profiler.start_trace(d)
                try:
                    time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
                return d
            finally:
                self._profiler_lock.release()

        try:
            fut = asyncio.get_running_loop().run_in_executor(None, capture)
        except Exception:   # submission failed: the thread never runs
            self._profiler_lock.release()
            raise
        try:
            d = await fut
        except asyncio.CancelledError:
            raise   # capture thread finishes + releases on its own
        except Exception as e:  # noqa: BLE001 — profiler not available
            return _error(501, f"jax profiler capture failed: {e}")
        return web.json_response({"log_dir": d, "seconds": seconds})

    def _residency_manager(self):
        """The ResidencyManager behind the registry, if hot-swap is on."""
        for cand in (self.registry, getattr(self.registry, "inner", None)):
            if cand is not None and hasattr(cand, "prefetch"):
                return cand
        return None

    async def prefetch_model(self, request):
        """Stage a model's weights in the background ahead of traffic (the
        async half of hot-swap; helix_model_swap_seconds in /metrics
        shows the payoff)."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — client error, not server fault
            return _error(400, "invalid JSON body")
        name = body.get("model", "")
        mgr = self._residency_manager()
        if mgr is None:
            return _error(
                409, "no residency manager: profile has no residency block"
            )
        if name not in mgr.names():
            return _error(404, f"unknown model {name!r}")
        # executor: prefetch() takes the manager lock (see /metrics note)
        started = await asyncio.get_running_loop().run_in_executor(
            None, lambda: bool(mgr.prefetch(name))
        )
        return web.json_response(
            {"model": name, "prefetch": "started" if started else "declined"}
        )

    async def tail_logs(self, request):
        """Node log tail for the admin UI (hydra logbuf analogue)."""
        try:
            n = max(1, min(int(request.query.get("tail", 200)), 2000))
        except ValueError:
            return _error(400, "tail must be an integer")
        return web.json_response({"logs": self.logbuf.tail(n)})

    async def list_models(self, request):
        def build():
            # runs in an executor: AdapterStore.ids walks the
            # filestore directory, which may be a slow/remote mount —
            # never on the event loop
            data = []
            for m in self.registry.list():
                data.append(
                    {
                        "id": m.name,
                        "object": "model",
                        "created": m.created,
                        "owned_by": m.owned_by,
                        **(
                            {"context_length": m.context_length}
                            if m.context_length
                            else {}
                        ),
                    }
                )
                # published multi-LoRA adapters (ISSUE 15): bounded
                # `base@adapter` entries, addressable through the same
                # chat/completions surface
                store = getattr(
                    getattr(getattr(m, "loop", None), "engine", None),
                    "adapter_store", None,
                )
                if store is not None:
                    for aid in store.ids(MAX_LISTED_ADAPTERS):
                        data.append(
                            {
                                "id": f"{m.name}{ADAPTER_SEP}{aid}",
                                "object": "model",
                                "created": m.created,
                                "owned_by": m.owned_by,
                                "parent": m.name,
                            }
                        )
            return data

        data = await asyncio.get_running_loop().run_in_executor(
            None, build
        )
        return web.json_response({"object": "list", "data": data})

    async def publish_adapter(self, request):
        """POST /v1/adapters (runner-token gated): publish a LoRA SFT
        checkpoint for ``model@name`` serving.  Body: ``{"model":
        base, "name": adapter_id, "checkpoint": dir[, "scale": f]}``.
        The checkpoint restores off the event loop, is validated
        against the base model's geometry, and lands on the residency
        ladder (host tier + filestore write-through) — servable
        immediately, warmup already covered the pool shape."""
        denied = self._require_runner_token(request)
        if denied is not None:
            return denied
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        base = body.get("model", "")
        adapter_id = sanitize_adapter_id(body.get("name", ""))
        ckpt = body.get("checkpoint", "")
        if not adapter_id:
            return _error(
                400,
                "'name' must be a bounded [A-Za-z0-9._-] adapter id",
            )
        if not ckpt or not isinstance(ckpt, str):
            return _error(400, "'checkpoint' directory is required")
        served, err = await self._lookup(base)
        if err is not None:
            return err
        eng = getattr(served.loop, "engine", None)
        store = getattr(eng, "adapter_store", None)
        if store is None:
            return _error(
                409,
                f"model '{base}' serves without an adapter pool "
                "(engine.adapter_pool_slots)",
            )
        scale = body.get("scale")
        try:
            spec = await asyncio.get_running_loop().run_in_executor(
                None, store.publish_checkpoint, adapter_id, ckpt,
                float(scale) if scale is not None else None,
            )
        except FileNotFoundError as e:
            return _error(404, str(e))
        except (ValueError, TypeError, KeyError) as e:
            # KeyError: a valid orbax checkpoint that is not a LoRA
            # checkpoint (no lora_params tree) — a caller error, not a
            # server fault
            return _error(400, f"adapter rejected: {e}")
        return web.json_response(
            {
                "id": f"{base}{ADAPTER_SEP}{adapter_id}",
                "object": "model",
                "parent": base,
                "rank": spec.rank,
                "scale": spec.scale,
                "bytes": spec.nbytes,
            }
        )

    # ------------------------------------------------------------------
    async def _lookup(self, model: str):
        """Resolve a model, faulting it in off the event loop (the registry
        may be a ResidencyManager that loads weights on demand).  Returns
        (served, error_response)."""
        try:
            served = await asyncio.get_running_loop().run_in_executor(
                None, self.registry.get, model
            )
        except MemoryError as e:
            return None, _error(503, str(e), "overloaded_error")
        if served is None:
            return None, _error(
                404,
                f"model '{model}' not found; available: {self.registry.names()}",
                "model_not_found",
            )
        return served, None

    async def _lookup_generation(self, model: str):
        """Resolve a generation target, including ``base@adapter``
        multi-LoRA addressing (ISSUE 15): the base model faults in
        through the ordinary registry path, the adapter id is sanitised
        and must be published on the engine's residency ladder (404
        otherwise — a hostile id never reaches a metrics label or a
        filestore path), and its filestore->host prefetch is kicked so
        a cold adapter overlaps loading with everything that follows.
        Returns ``(served, adapter_id, error_response)``."""
        base, adapter, ok = split_model_adapter(model)
        if ADAPTER_SEP in (model or "") and model:
            # a model whose LITERAL registered name contains '@' keeps
            # resolving by exact name — adapter addressing never breaks
            # a pre-existing registration
            lit = await asyncio.get_running_loop().run_in_executor(
                None, self.registry.get, model
            )
            if lit is not None:
                return lit, "", None
        if not ok:
            return None, "", _error(
                404, f"model '{model}' not found (invalid adapter id)",
                "model_not_found",
            )
        served, err = await self._lookup(base)
        if err is not None:
            return None, "", err
        if adapter:
            loop = served.loop
            eng = getattr(loop, "engine", None)
            pool = getattr(eng, "adapter_pool", None)
            store = getattr(eng, "adapter_store", None)
            if pool is None or store is None:
                return None, "", _error(
                    404,
                    f"model '{base}' does not serve adapters "
                    "(engine.adapter_pool_slots is off)",
                    "model_not_found",
                )
            # contains and the 404's listing both touch the filestore
            # directory — off the event loop (the mount may be remote);
            # prefetch itself does no caller-thread I/O by contract
            aio = asyncio.get_running_loop()
            known = pool.resident(adapter) or await aio.run_in_executor(
                None, store.contains, adapter
            )
            if not known:
                available = await aio.run_in_executor(
                    None, store.ids, MAX_LISTED_ADAPTERS
                )
                return None, "", _error(
                    404,
                    f"adapter '{adapter}' is not published for model "
                    f"'{base}'; available: {available}",
                    "model_not_found",
                )
            if not pool.resident(adapter):
                store.prefetch(adapter)
        return served, adapter, None

    @staticmethod
    def _require_loop(served, model: str):
        """Generation needs a live engine loop; embedding-only workers
        and multi-host FOLLOWERS (journal replay, no local traffic) have
        loop=None and must answer with a clean error, not a 500."""
        if served.loop is not None:
            return None
        if served.follower is not None:
            return _error(
                409,
                f"'{model}' is a multi-host follower replica on this "
                "host; send traffic to the leader",
            )
        return _error(
            404, f"'{model}' does not serve generation", "model_not_found"
        )

    @staticmethod
    def _precheck_admission(served, prompt_ids, trace_id: str = "",
                            tenant: str = ANON_TENANT):
        """Shed before committing response headers: streaming handlers
        prepare() the SSE response before the first engine event, so a
        queue_full discovered after submit can only surface as an in-band
        error frame — this pre-check turns it into a real 429/503.  The
        tenant rides along so the shed lands in that tenant's accounting
        and the admission audit ring."""
        check = getattr(served.loop, "check_admission", None)
        if check is None:
            return None
        err = check(
            len(prompt_ids), count_shed=True, tenant=tenant,
            trace_id=trace_id,
        )
        if err is None:
            return None
        return _engine_error_response(
            EngineRequestError(err), trace_id=trace_id
        )

    def _trace_id(self, request) -> str:
        """The request's end-to-end trace identity: adopt the control
        plane's (header, shape-validated) or mint one at this endpoint."""
        return adopt_trace_id(request.headers.get(TRACE_HEADER))

    @staticmethod
    def _tenant(request) -> str:
        """The request's tenant identity: the control plane resolves it
        at dispatch and forwards ``X-Helix-Tenant``.  The runner is an
        internal surface (same trust model as /logs and /metrics), so a
        direct caller's header is trusted like its prompts; the
        sanitiser bounds the SHAPE — malformed values and claims on the
        ``__other__`` fold bucket land under ``anonymous`` — and the
        top-K accounting bounds the series count."""
        return sanitize_tenant(request.headers.get(TENANT_HEADER, ""))

    @staticmethod
    def _sched_class(request) -> str:
        """The request's priority class (``X-Helix-Class``): forwarded
        by the control plane for authenticated callers, sanitised to
        the known class names; "" defers to the serving profile's
        default class (stamped by the engine loop at submit)."""
        return sanitize_class(request.headers.get(CLASS_HEADER, ""))

    def _sampling_from_body(self, body: dict) -> SamplingParams:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            max_tokens=int(
                body.get("max_tokens")
                or body.get("max_completion_tokens")
                or 256
            ),
            stop=tuple(stop),
            seed=body.get("seed"),
        )

    async def _generate(self, served, prompt_ids, sampling, extra=None,
                        trace_id: str = "", tenant: str = ANON_TENANT,
                        sched_class: str = ""):
        """Submit to the engine; yields (delta_text, token_id, finished,
        finish_reason).  ``extra`` carries multimodal Request fields;
        ``trace_id`` and ``tenant`` ride the Request into engine-level
        spans and the per-tenant accounting; ``sched_class`` is the
        scheduler priority class ("" = profile default)."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_event(ev):
            loop.call_soon_threadsafe(q.put_nowait, ev)

        req = Request(
            id=f"req-{uuid.uuid4().hex[:12]}",
            prompt_tokens=list(prompt_ids),
            sampling=sampling,
            stop_token_ids=tuple(served.tokenizer.eos_ids),
            trace_id=trace_id,
            tenant=tenant,
            sched_class=sched_class,
            **(extra or {}),
        )
        served.loop.submit(req, on_event)
        detok = IncrementalDetokenizer(served.tokenizer)
        emitted_len = 0
        try:
            while True:
                try:
                    ev = await asyncio.wait_for(
                        q.get(), timeout=self.inter_token_timeout
                    )
                except asyncio.TimeoutError:
                    # never leak a raw TimeoutError (dead stream / bare
                    # 500): abort the engine request and surface a typed
                    # error the handlers map to 504 / an SSE error event
                    served.loop.abort(req.id)
                    raise EngineRequestError(
                        f"inter_token_timeout: no engine event for "
                        f"{self.inter_token_timeout:.0f}s; request "
                        f"{req.id} aborted", request_id=req.id,
                    ) from None
                if ev.error:
                    raise EngineRequestError(ev.error, request_id=req.id)
                is_eos = ev.token_id in served.tokenizer.eos_ids
                delta = "" if is_eos else detok.push(ev.token_id)
                # serving-level stop strings
                hit_stop = None
                for s in sampling.stop:
                    idx = detok._emitted.find(s, max(0, emitted_len - len(s)))
                    if idx >= 0:
                        hit_stop = idx
                        break
                if hit_stop is not None:
                    keep = detok._emitted[:hit_stop]
                    final_delta = keep[emitted_len:]
                    served.loop.abort(req.id)
                    yield final_delta, ev.token_id, True, "stop"
                    return
                emitted_len = len(detok._emitted)
                yield delta, ev.token_id, ev.finished, ev.finish_reason
                if ev.finished:
                    return
        finally:
            if not req.finished:
                served.loop.abort(req.id)

    # ------------------------------------------------------------------
    async def _disagg_prefill(self, request, served, model, prompt_ids,
                              sampling, kind, http_id, created,
                              trace_id, tenant, sched_class,
                              adapter: str = ""):
        """Disaggregated prefill/decode handoff (ISSUE 14), runner side.

        Submits the request like an ordinary stream, but stages an
        export-at-prefill-completion with the engine loop: the moment
        the first token exists, the engine thread snapshots the request
        (pages + device-evolved sampler state) and hands the wire dict
        back HERE, where the ship to the control-plane-named decode
        peer runs off the engine thread with the full
        ``HELIX_XFER_*`` retry/backoff/deadline discipline.

        Degrade-to-local by design — every rung falls back one step and
        none can produce a stuck or wrong-token stream:

        - ship CONFIRMED: the local request aborts and the response is
          a single ``migrated ... peer=<id>`` SSE frame the control
          plane resumes on the peer (the PR 11 clean-drain contract,
          exactly-once via prior-token catch-up);
        - ship FAILED (peer unreachable / corrupt-rejected / slow past
          the deadline): the local request never stopped decoding —
          the stream serves from HERE, colocated, bit-identical;
        - export unavailable or prefill deadline exceeded: same local
          path;
        - the request finished before the export fired (short
          generation): the buffered events replay as a normal stream.
        """
        import os

        from helix_tpu.serving.migration import PeerShipper

        peer_id = request.headers.get(DISAGG_PEER_ID_HEADER, "")
        peer_addr = request.headers.get(DISAGG_PEER_ADDR_HEADER, "")
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_event(ev):
            loop.call_soon_threadsafe(q.put_nowait, ("ev", ev))

        def on_export(kind2, wire):
            loop.call_soon_threadsafe(
                q.put_nowait, ("export", kind2, wire)
            )

        req = Request(
            id=f"req-{uuid.uuid4().hex[:12]}",
            prompt_tokens=list(prompt_ids),
            sampling=sampling,
            stop_token_ids=tuple(served.tokenizer.eos_ids),
            trace_id=trace_id,
            tenant=tenant,
            sched_class=sched_class,
            adapter=adapter,
        )
        t_plan = time.monotonic()
        if peer_addr:
            served.loop.stage_disagg_export(req.id, on_export)
        served.loop.submit(req, on_event)
        # the handoff-plan leg of the federated timeline (ISSUE 18):
        # which decode peer the control plane named, staged or not
        self.traces.record(
            trace_id, "disagg handoff plan", t_plan, time.monotonic(),
            plane="runner", request_id=req.id,
            peer=peer_id or peer_addr or "(none)",
            staged=bool(peer_addr),
        )
        xfer = XferConfig()
        deadline = loop.time() + xfer.deadline
        last_event = loop.time()
        buffered: list = []
        t_wait = time.monotonic()
        outcome = ("local", None) if not peer_addr else None
        try:
            while outcome is None:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    # prefill did not complete inside the transfer
                    # deadline (engine under load): serve locally —
                    # never a stuck handoff
                    served.loop.unstage_disagg_export(req.id)
                    outcome = ("local", None)
                    break
                try:
                    item = await asyncio.wait_for(
                        q.get(),
                        timeout=min(remaining, self.inter_token_timeout),
                    )
                except asyncio.TimeoutError:
                    if loop.time() - last_event < self.inter_token_timeout:
                        # the TRANSFER deadline cut this wait short, not
                        # a wedged engine: a slow prefill that colocated
                        # serving would have tolerated must not become
                        # an error just because disagg was attempted —
                        # withdraw the handoff and serve locally (the
                        # colocated tail keeps its own inter-token
                        # discipline)
                        served.loop.unstage_disagg_export(req.id)
                        outcome = ("local", None)
                        break
                    served.loop.unstage_disagg_export(req.id)
                    served.loop.abort(req.id)
                    raise EngineRequestError(
                        f"inter_token_timeout: no engine event for "
                        f"{self.inter_token_timeout:.0f}s; request "
                        f"{req.id} aborted", request_id=req.id,
                    ) from None
                last_event = loop.time()
                if item[0] == "export":
                    _tag, k2, wire = item
                    if k2 == "snapshot":
                        outcome = ("snapshot", wire)
                    elif k2 == "completed":
                        outcome = ("completed", None)
                    elif k2 == "gone":
                        return _error(
                            502,
                            f"request {req.id} vanished before the "
                            "prefill handoff",
                            "overloaded_error", code="disagg_failed",
                            trace_id=trace_id,
                        )
                    else:   # "local": export unavailable — serve here
                        outcome = ("local", None)
                    continue
                ev = item[1]
                if ev.error:
                    served.loop.unstage_disagg_export(req.id)
                    raise EngineRequestError(
                        ev.error, request_id=req.id
                    )
                buffered.append(ev)
                if ev.finished:
                    served.loop.unstage_disagg_export(req.id)
                    outcome = ("completed", None)
        except EngineRequestError as e:
            return _engine_error_response(e, trace_id=trace_id)
        except asyncio.CancelledError:
            served.loop.unstage_disagg_export(req.id)
            served.loop.abort(req.id)
            raise
        if peer_addr:
            self.traces.record(
                trace_id, "disagg prefill wait", t_wait,
                time.monotonic(), plane="runner", request_id=req.id,
                outcome=outcome[0],
            )

        if outcome[0] == "snapshot":
            # the ship spends only what is LEFT of the one transfer
            # deadline (HELIX_XFER_DEADLINE covers prefill wait + all
            # ship attempts + backoffs, as config_reference documents);
            # an exhausted budget fails the first remaining-time check
            # inside the shipper and degrades to local serving
            shipper = PeerShipper(
                runner_token=os.environ.get("HELIX_RUNNER_TOKEN", ""),
                targets=[{
                    "id": peer_id or peer_addr,
                    "address": peer_addr,
                    "models": [model],
                }],
                config=XferConfig(
                    attempt_timeout=xfer.attempt_timeout,
                    max_attempts=xfer.max_attempts,
                    backoff_base=xfer.backoff_base,
                    backoff_cap=xfer.backoff_cap,
                    deadline=max(0.0, deadline - loop.time()),
                ),
                prefill=True,
            )
            peer = None
            ship_err = ""
            t_ship = time.monotonic()
            try:
                peer = await loop.run_in_executor(
                    None, shipper, outcome[1]
                )
            except Exception as e:  # noqa: BLE001 — degrade to local serving
                ship_err = str(e)
            self.traces.record(
                trace_id, "disagg ship", t_ship, time.monotonic(),
                plane="runner", request_id=req.id,
                peer=peer or peer_id or peer_addr,
                outcome="confirmed" if peer is not None else "failed",
            )
            if peer is not None:
                # handoff confirmed: tear the local request down and
                # hand the stream to the control plane's resume path
                served.loop.abort(req.id)
                self.traces.record(
                    trace_id, "disagg migrated frame",
                    time.monotonic(), time.monotonic(),
                    plane="runner", request_id=req.id, peer=peer,
                )
                resp = web.StreamResponse(
                    headers={
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                        TRACE_HEADER: trace_id,
                    }
                )
                await resp.prepare(request)
                err: dict = {
                    "message": migrated_error(req.id, peer),
                    "request_id": req.id,
                }
                if trace_id:
                    err["trace_id"] = trace_id
                await resp.write(
                    f"data: {json.dumps({'error': err})}\n\n".encode()
                )
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
                return resp
            # ship failed: the local request never stopped decoding —
            # degrade to colocated serving (strictly never worse than
            # not having attempted the handoff)
            import logging as _logging

            _logging.getLogger(__name__).warning(
                "disagg ship for request %s to %s failed (%s): "
                "serving locally", req.id, peer_id or peer_addr,
                ship_err[:200],
            )

        if peer_addr and outcome[0] != "completed":
            # a fallback rung was taken: the handoff was attempted but
            # this request is now serving colocated — name the rung so
            # the timeline explains WHY the decode peer never appears
            self.traces.record(
                trace_id, "disagg fallback rung", time.monotonic(),
                time.monotonic(), plane="runner", request_id=req.id,
                rung=(
                    "ship_failed" if outcome[0] == "snapshot"
                    else "prefill_local"
                ),
            )

        # -- colocated tail: stream buffered + live events ----------------
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                TRACE_HEADER: trace_id,
            }
        )
        await resp.prepare(request)
        detok = IncrementalDetokenizer(served.tokenizer)
        template = {"id": http_id, "model": model, "created": created}
        emitted_len = 0
        first = True
        idx = 0
        finished = False
        try:
            while not finished:
                if idx < len(buffered):
                    ev = buffered[idx]
                    idx += 1
                elif outcome[0] == "completed":
                    break   # defensive: finish event should be last
                else:
                    try:
                        item = await asyncio.wait_for(
                            q.get(), timeout=self.inter_token_timeout
                        )
                    except asyncio.TimeoutError:
                        served.loop.abort(req.id)
                        await resp.write(
                            f"data: {json.dumps(_sse_error_frame(EngineRequestError('inter_token_timeout on disagg-local stream', req.id), trace_id))}\n\n"
                            .encode()
                        )
                        break
                    if item[0] == "export":
                        continue   # stale sentinel: we already chose local
                    ev = item[1]
                if ev.error:
                    await resp.write(
                        f"data: {json.dumps(_sse_error_frame(EngineRequestError(ev.error, req.id), trace_id))}\n\n"
                        .encode()
                    )
                    break
                is_eos = ev.token_id in served.tokenizer.eos_ids
                delta = "" if is_eos else detok.push(ev.token_id)
                hit_stop = None
                for s in sampling.stop:
                    j = detok._emitted.find(
                        s, max(0, emitted_len - len(s))
                    )
                    if j >= 0:
                        hit_stop = j
                        break
                if hit_stop is not None:
                    keep = detok._emitted[:hit_stop]
                    served.loop.abort(req.id)
                    await resp.write(
                        f"data: {json.dumps(make_chunk(template, kind, keep[emitted_len:], 'stop', first=first))}\n\n"
                        .encode()
                    )
                    finished = True
                    break
                emitted_len = len(detok._emitted)
                fr = ev.finish_reason if ev.finished else None
                if delta or fr or first:
                    await resp.write(
                        f"data: {json.dumps(make_chunk(template, kind, delta, fr, first=first))}\n\n"
                        .encode()
                    )
                    first = False
                if ev.finished:
                    finished = True
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        finally:
            if not req.finished:
                served.loop.abort(req.id)
        return resp

    # ------------------------------------------------------------------
    # -- context-caching API (ISSUE 20) --------------------------------
    def _resolve_context(self, body: dict, trace_id: str = ""):
        """Resolve a request's ``context_id`` to its cached token span.
        Returns ``(prefix_ids, error_response)`` — ``([], None)`` when
        the request references no context.  An unknown or unreadable
        handle is a clean 404 (typed miss), never silent recompute of a
        prefix the caller believes is pinned."""
        ctx_id = body.get("context_id", "")
        if not ctx_id:
            return [], None
        if not isinstance(ctx_id, str):
            return [], _error(
                400, "'context_id' must be a string", trace_id=trace_id
            )
        cached = self.ctx_cache.get(ctx_id)
        if cached is None:
            return [], _error(
                404, f"context '{ctx_id}' not found (expired, evicted, "
                "or never created on this runner)",
                "invalid_request_error", code="context_not_found",
                trace_id=trace_id,
            )
        return cached, None

    async def create_context(self, request):
        """``POST /v1/context``: prefill a prompt prefix once and pin it
        behind a content-addressed handle.  The prefix runs through the
        engine as an ordinary one-token request with ``ctx_pin`` set —
        fully resident even on a tiered engine, so the prefix-cache
        adoption and the filestore write-through fire exactly as for any
        resident prompt — then the handle registers in the (tenant-
        quota'd, filestore-persisted) registry.  Requests that later
        carry ``context_id`` prepend the span and the residency ladder
        serves its pages without recomputing prefill."""
        from helix_tpu.serving.context_cache import context_handle

        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        tid = self._trace_id(request)
        tenant = self._tenant(request)
        model = body.get("model", "")
        served, adapter, err = await self._lookup_generation(model)
        if err is not None:
            return err
        if served.kind == "embedding":
            return _error(404, f"model '{model}' is an embedding model",
                          "model_not_found", trace_id=tid)
        err = self._require_loop(served, model)
        if err is not None:
            return err
        messages = body.get("messages")
        prompt = body.get("prompt")
        if messages:
            # no generation prompt: this span is a PREFIX later
            # requests extend, not a turn awaiting an answer
            prompt_ids = served.tokenizer.apply_chat_template(
                messages, add_generation_prompt=False
            )
        elif isinstance(prompt, list) and all(
            isinstance(t, int) for t in prompt
        ):
            prompt_ids = list(prompt)
        elif isinstance(prompt, str) and prompt:
            prompt_ids = served.tokenizer.encode(prompt)
        else:
            return _error(
                400, "'messages' or 'prompt' is required", trace_id=tid
            )
        if not prompt_ids:
            return _error(400, "context prefix is empty", trace_id=tid)
        handle = context_handle(prompt_ids)
        if self.ctx_cache.contains(handle):
            # content-addressed: the prefix is already pinned — answer
            # without paying another prefill and without a new charge
            return web.json_response({
                "id": handle, "object": "context", "created": _now(),
                "model": model, "tokens": len(prompt_ids),
                "cached": True,
            }, headers={TRACE_HEADER: tid})
        if not self.ctx_cache.admit(tenant, len(prompt_ids)):
            return _error(
                429,
                f"tenant '{tenant}' is over its context-cache token "
                f"quota ({self.ctx_cache.tenant_token_cap} tokens)",
                "overloaded_error", code="context_quota_exceeded",
                trace_id=tid,
            )
        shed = self._precheck_admission(
            served, prompt_ids, trace_id=tid, tenant=tenant
        )
        if shed is not None:
            return shed
        # prefill-once: one greedy token forces the full prefix through
        # the engine; ctx_pin keeps it fully device-resident so every
        # page adopts into the prefix cache (and writes through to the
        # filestore tier when armed)
        sampling = SamplingParams(temperature=0.0, max_tokens=1)
        extra = {"ctx_pin": True}
        if adapter:
            extra["adapter"] = adapter
        t0 = time.monotonic()
        try:
            async for _delta, _tok, finished, _reason in self._generate(
                served, prompt_ids, sampling, extra, trace_id=tid,
                tenant=tenant,
            ):
                if finished:
                    break
        except EngineRequestError as e:
            return _engine_error_response(e, trace_id=tid)
        handle = self.ctx_cache.put(prompt_ids, tenant=tenant)
        self.traces.record(
            tid, "context create", t0, time.monotonic(),
            plane="runner", model=model, prompt_tokens=len(prompt_ids),
            handle=handle, tenant=tenant,
        )
        return web.json_response({
            "id": handle, "object": "context", "created": _now(),
            "model": model, "tokens": len(prompt_ids),
            "cached": False,
        }, headers={TRACE_HEADER: tid})

    async def list_contexts(self, request):
        return web.json_response({
            "object": "list", "data": self.ctx_cache.entries(),
        })

    async def chat_completions(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        tid = self._trace_id(request)
        tenant = self._tenant(request)
        sclass = self._sched_class(request)
        t_req = time.monotonic()
        model = body.get("model", "")
        served, adapter, err = await self._lookup_generation(model)
        if err is not None:
            return err
        if served.kind == "embedding":
            return _error(404, f"model '{model}' is an embedding model",
                          "model_not_found", trace_id=tid)
        err = self._require_loop(served, model)
        if err is not None:
            return err
        messages = body.get("messages")
        if not messages:
            return _error(400, "'messages' is required", trace_id=tid)
        sampling = self._sampling_from_body(body)
        t_admit = time.monotonic()
        has_images = any(
            isinstance(m.get("content"), list)
            and any(
                p.get("type") in ("image_url", "image")
                for p in m["content"]
            )
            for m in messages
        )
        extra = None
        if has_images:
            if served.vision is None:
                return _error(
                    400, f"model '{model}' does not accept image input",
                    trace_id=tid,
                )
            try:
                extra = await asyncio.get_running_loop().run_in_executor(
                    None, served.vision.prepare, messages, served.tokenizer
                )
            except Exception as e:  # noqa: BLE001 — bad image data etc.
                return _error(
                    400, f"image processing failed: {e}", trace_id=tid
                )
            prompt_ids = extra.pop("prompt_tokens")
        else:
            prompt_ids = served.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True
            )
        if adapter:
            # `model@adapter` requests ride the batched multi-LoRA
            # path: the engine resolves the id to an HBM pool slot at
            # admission (ISSUE 15)
            extra = {**(extra or {}), "adapter": adapter}
        # context-cache reference (ISSUE 20): prepend the pinned span —
        # the prefix-cache ladder serves its pages, so prefill covers
        # only the NEW tokens
        ctx_prefix, ctx_err = self._resolve_context(body, trace_id=tid)
        if ctx_err is not None:
            return ctx_err
        if ctx_prefix:
            prompt_ids = list(ctx_prefix) + list(prompt_ids)
        shed = self._precheck_admission(
            served, prompt_ids, trace_id=tid, tenant=tenant
        )
        self.traces.record(
            tid, "admit", t_admit, time.monotonic(), plane="runner",
            model=model, prompt_tokens=len(prompt_ids),
            shed=shed is not None, tenant=tenant,
        )
        if shed is not None:
            return shed
        rid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = _now()

        # disaggregated prefill handoff (ISSUE 14): the control plane
        # marked this dispatch prefill-only and named a decode peer.
        # VL requests (device-resident image state) and non-stream
        # bodies ignore the header and serve colocated — the control
        # plane handles an ordinary stream transparently.
        if (
            request.headers.get(DISAGG_HEADER)
            and body.get("stream")
            and not has_images
            and self._require_runner_token(request) is None
            and hasattr(served.loop, "stage_disagg_export")
        ):
            # adapter requests hand off too: the snapshot carries the
            # adapter id and the decode peer re-resolves it against ITS
            # residency ladder (an unpublished adapter there is a typed
            # import rejection -> the ordinary colocated fallback)
            return await self._disagg_prefill(
                request, served, model, prompt_ids, sampling,
                kind="chat", http_id=rid, created=created,
                trace_id=tid, tenant=tenant, sched_class=sclass,
                adapter=adapter,
            )

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    TRACE_HEADER: tid,
                }
            )
            await resp.prepare(request)

            async def send(obj):
                await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

            first = True
            finish_reason = None
            ntokens = 0
            t_emit = None
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling, extra, trace_id=tid,
                tenant=tenant, sched_class=sclass,
              ):
                if t_emit is None:
                    t_emit = time.monotonic()
                ntokens += 1
                chunk_delta = {}
                if first:
                    chunk_delta["role"] = "assistant"
                    first = False
                if delta:
                    chunk_delta["content"] = delta
                finish_reason = reason if finished else None
                await send(
                    {
                        "id": rid,
                        "object": "chat.completion.chunk",
                        "created": created,
                        "model": model,
                        "choices": [
                            {
                                "index": 0,
                                "delta": chunk_delta,
                                "finish_reason": finish_reason,
                            }
                        ],
                    }
                )
                if finished:
                    break
            except EngineRequestError as e:
                await send(_sse_error_frame(e, tid))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            end = time.monotonic()
            self.traces.record(
                tid, "emit", t_emit if t_emit is not None else end, end,
                plane="runner", tokens=ntokens, stream=True,
            )
            self.traces.record(
                tid, "request", t_req, end, plane="runner",
                endpoint=request.path, model=model, http_id=rid,
            )
            return resp

        text_parts = []
        finish_reason = "stop"
        ntokens = 0
        t_emit = None
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling, extra, trace_id=tid,
            tenant=tenant, sched_class=sclass,
          ):
            if t_emit is None:
                t_emit = time.monotonic()
            text_parts.append(delta)
            ntokens += 1
            if finished:
                finish_reason = reason or "stop"
                break
        except EngineRequestError as e:
            return _engine_error_response(e, trace_id=tid)
        end = time.monotonic()
        self.traces.record(
            tid, "emit", t_emit if t_emit is not None else end, end,
            plane="runner", tokens=ntokens, stream=False,
        )
        self.traces.record(
            tid, "request", t_req, end, plane="runner",
            endpoint=request.path, model=model, http_id=rid,
        )
        return web.json_response(
            {
                "id": rid,
                "object": "chat.completion",
                "created": created,
                "model": model,
                "choices": [
                    {
                        "index": 0,
                        "message": {
                            "role": "assistant",
                            "content": "".join(text_parts),
                        },
                        "finish_reason": finish_reason,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(prompt_ids),
                    "completion_tokens": ntokens,
                    "total_tokens": len(prompt_ids) + ntokens,
                },
            },
            headers={TRACE_HEADER: tid},
        )

    # ------------------------------------------------------------------
    async def completions(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        tid = self._trace_id(request)
        tenant = self._tenant(request)
        sclass = self._sched_class(request)
        t_req = time.monotonic()
        model = body.get("model", "")
        served, adapter, err = await self._lookup_generation(model)
        if err is not None:
            return err
        err = self._require_loop(served, model)
        if err is not None:
            return err
        extra = {"adapter": adapter} if adapter else None
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        sampling = self._sampling_from_body(body)
        t_admit = time.monotonic()
        prompt_ids = served.tokenizer.encode(prompt)
        # context-cache reference (ISSUE 20) — see chat_completions
        ctx_prefix, ctx_err = self._resolve_context(body, trace_id=tid)
        if ctx_err is not None:
            return ctx_err
        if ctx_prefix:
            prompt_ids = list(ctx_prefix) + list(prompt_ids)
        shed = self._precheck_admission(
            served, prompt_ids, trace_id=tid, tenant=tenant
        )
        self.traces.record(
            tid, "admit", t_admit, time.monotonic(), plane="runner",
            model=model, prompt_tokens=len(prompt_ids),
            shed=shed is not None, tenant=tenant,
        )
        if shed is not None:
            return shed
        rid = f"cmpl-{uuid.uuid4().hex[:16]}"
        created = _now()

        # disaggregated prefill handoff (ISSUE 14) — see chat_completions
        if (
            request.headers.get(DISAGG_HEADER)
            and body.get("stream")
            and self._require_runner_token(request) is None
            and hasattr(served.loop, "stage_disagg_export")
        ):
            return await self._disagg_prefill(
                request, served, model, prompt_ids, sampling,
                kind="completions", http_id=rid, created=created,
                trace_id=tid, tenant=tenant, sched_class=sclass,
                adapter=adapter,
            )

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    TRACE_HEADER: tid,
                }
            )
            await resp.prepare(request)
            n = 0
            t_emit = None
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling, extra, trace_id=tid,
                tenant=tenant, sched_class=sclass,
              ):
                if t_emit is None:
                    t_emit = time.monotonic()
                n += 1
                await resp.write(
                    f"data: {json.dumps({'id': rid, 'object': 'text_completion', 'created': created, 'model': model, 'choices': [{'index': 0, 'text': delta, 'finish_reason': reason if finished else None}]})}\n\n".encode()
                )
                if finished:
                    break
            except EngineRequestError as e:
                await resp.write(
                    f"data: {json.dumps(_sse_error_frame(e, tid))}\n\n"
                    .encode()
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            end = time.monotonic()
            self.traces.record(
                tid, "emit", t_emit if t_emit is not None else end, end,
                plane="runner", tokens=n, stream=True,
            )
            self.traces.record(
                tid, "request", t_req, end, plane="runner",
                endpoint=request.path, model=model, http_id=rid,
            )
            return resp

        parts = []
        finish_reason = "stop"
        n = 0
        t_emit = None
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling, extra, trace_id=tid,
            tenant=tenant, sched_class=sclass,
          ):
            if t_emit is None:
                t_emit = time.monotonic()
            parts.append(delta)
            n += 1
            if finished:
                finish_reason = reason or "stop"
                break
        except EngineRequestError as e:
            return _engine_error_response(e, trace_id=tid)
        end = time.monotonic()
        self.traces.record(
            tid, "emit", t_emit if t_emit is not None else end, end,
            plane="runner", tokens=n, stream=False,
        )
        self.traces.record(
            tid, "request", t_req, end, plane="runner",
            endpoint=request.path, model=model, http_id=rid,
        )
        return web.json_response(
            {
                "id": rid,
                "object": "text_completion",
                "created": created,
                "model": model,
                "choices": [
                    {
                        "index": 0,
                        "text": "".join(parts),
                        "finish_reason": finish_reason,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(prompt_ids),
                    "completion_tokens": n,
                    "total_tokens": len(prompt_ids) + n,
                },
            },
            headers={TRACE_HEADER: tid},
        )

    # ------------------------------------------------------------------
    async def embeddings(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model", "")
        served, err = await self._lookup(model)
        if err is not None:
            return err
        if served.kind not in ("embedding", "vision-embedding"):
            return _error(
                404, f"'{model}' is not an embedding model", "model_not_found"
            )
        inputs = body.get("input", [])
        if isinstance(inputs, (str, dict)):
            inputs = [inputs]
        bad = [
            x for x in inputs if isinstance(x, dict) and "image" not in x
        ]
        if bad:
            return _error(
                400,
                "dict inputs must be {\"image\": <url/base64>}; got keys "
                f"{sorted(bad[0])}",
            )
        has_images = any(
            isinstance(x, dict) and "image" in x for x in inputs
        )
        if has_images:
            # vision-RAG: image entries ({"image": url/b64}) pool through
            # the vision tower into the same space as text (reference:
            # Qwen3-VL-Embedding pooling runner)
            if served.kind != "vision-embedding":
                return _error(
                    400,
                    f"'{model}' cannot embed images; serve a "
                    "vision-embedding model",
                )
            embed = served.embedder.embed_mixed
        else:
            embed = served.embedder.embed_texts
        vectors = await asyncio.get_running_loop().run_in_executor(
            None, embed, inputs
        )
        text_tokens = sum(
            len(served.tokenizer.encode(t))
            for t in inputs
            if isinstance(t, str)
        )
        return web.json_response(
            {
                "object": "list",
                "model": model,
                "data": [
                    {"object": "embedding", "index": i, "embedding": list(map(float, v))}
                    for i, v in enumerate(vectors)
                ],
                "usage": {
                    "prompt_tokens": text_tokens,
                    "total_tokens": text_tokens,
                },
            }
        )

    # ------------------------------------------------------------------
    async def anthropic_messages(self, request):
        """Native Anthropic /v1/messages surface (reference:
        ``api/pkg/anthropic/anthropic_proxy.go``)."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        tid = self._trace_id(request)
        tenant = self._tenant(request)
        sclass = self._sched_class(request)
        t_req = time.monotonic()
        model = body.get("model", "")
        served, adapter, err = await self._lookup_generation(model)
        if err is not None:
            return err
        err = self._require_loop(served, model)
        if err is not None:
            return err
        extra = {"adapter": adapter} if adapter else None
        messages = list(body.get("messages", []))
        if body.get("system"):
            messages = [{"role": "system", "content": body["system"]}] + messages
        sampling = SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            max_tokens=int(body.get("max_tokens", 256)),
            stop=tuple(body.get("stop_sequences", []) or []),
        )
        t_admit = time.monotonic()
        prompt_ids = served.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True
        )
        shed = self._precheck_admission(
            served, prompt_ids, trace_id=tid, tenant=tenant
        )
        self.traces.record(
            tid, "admit", t_admit, time.monotonic(), plane="runner",
            model=model, prompt_tokens=len(prompt_ids),
            shed=shed is not None, tenant=tenant,
        )
        if shed is not None:
            return shed
        rid = f"msg_{uuid.uuid4().hex[:20]}"

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    TRACE_HEADER: tid,
                }
            )
            await resp.prepare(request)

            async def ev(name, obj):
                await resp.write(
                    f"event: {name}\ndata: {json.dumps(obj)}\n\n".encode()
                )

            await ev(
                "message_start",
                {
                    "type": "message_start",
                    "message": {
                        "id": rid,
                        "type": "message",
                        "role": "assistant",
                        "model": model,
                        "content": [],
                        "usage": {"input_tokens": len(prompt_ids), "output_tokens": 0},
                    },
                },
            )
            await ev(
                "content_block_start",
                {
                    "type": "content_block_start",
                    "index": 0,
                    "content_block": {"type": "text", "text": ""},
                },
            )
            n = 0
            stop_reason = "end_turn"
            t_emit = None
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling, extra, trace_id=tid,
                tenant=tenant, sched_class=sclass,
              ):
                if t_emit is None:
                    t_emit = time.monotonic()
                n += 1
                if delta:
                    await ev(
                        "content_block_delta",
                        {
                            "type": "content_block_delta",
                            "index": 0,
                            "delta": {"type": "text_delta", "text": delta},
                        },
                    )
                if finished:
                    stop_reason = (
                        "max_tokens" if reason == "length" else "end_turn"
                    )
                    break
            except EngineRequestError as e:
                await ev("error", {"type": "error",
                                   "error": _sse_error_frame(e, tid)["error"]})
            await ev(
                "content_block_stop", {"type": "content_block_stop", "index": 0}
            )
            await ev(
                "message_delta",
                {
                    "type": "message_delta",
                    "delta": {"stop_reason": stop_reason},
                    "usage": {"output_tokens": n},
                },
            )
            await ev("message_stop", {"type": "message_stop"})
            await resp.write_eof()
            end = time.monotonic()
            self.traces.record(
                tid, "emit", t_emit if t_emit is not None else end, end,
                plane="runner", tokens=n, stream=True,
            )
            self.traces.record(
                tid, "request", t_req, end, plane="runner",
                endpoint=request.path, model=model, http_id=rid,
            )
            return resp

        parts = []
        n = 0
        stop_reason = "end_turn"
        t_emit = None
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling, extra, trace_id=tid,
            tenant=tenant, sched_class=sclass,
          ):
            if t_emit is None:
                t_emit = time.monotonic()
            parts.append(delta)
            n += 1
            if finished:
                stop_reason = "max_tokens" if reason == "length" else "end_turn"
                break
        except EngineRequestError as e:
            return _engine_error_response(e, trace_id=tid)
        end = time.monotonic()
        self.traces.record(
            tid, "emit", t_emit if t_emit is not None else end, end,
            plane="runner", tokens=n, stream=False,
        )
        self.traces.record(
            tid, "request", t_req, end, plane="runner",
            endpoint=request.path, model=model, http_id=rid,
        )
        return web.json_response(
            {
                "id": rid,
                "type": "message",
                "role": "assistant",
                "model": model,
                "content": [{"type": "text", "text": "".join(parts)}],
                "stop_reason": stop_reason,
                "usage": {
                    "input_tokens": len(prompt_ids),
                    "output_tokens": n,
                },
            },
            headers={TRACE_HEADER: tid},
        )


def run_server(registry: ModelRegistry, host="0.0.0.0", port=8000):
    server = OpenAIServer(registry)
    web.run_app(server.build_app(), host=host, port=port, print=None)
