"""OpenAI- and Anthropic-compatible HTTP surface over the engine.

Mirrors the reference's public inference surface exactly (the routes its
inference-proxy forwards: ``/v1/chat/completions``, ``/v1/completions``,
``/v1/embeddings``, ``/v1/models`` — ``api/pkg/inferenceproxy/proxy.go:
94-120`` — plus the native Anthropic ``/v1/messages`` proxy surface,
``api/pkg/anthropic/anthropic_proxy.go:32-40``), so a reference control
plane can point at this server the way it points at a vLLM container.

SSE framing follows OpenAI: ``data: {json}\n\n`` chunks, closing
``data: [DONE]``; Anthropic streaming emits the event-typed frames
(message_start / content_block_delta / message_stop).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Optional

from aiohttp import web

from helix_tpu.engine.engine import Request
from helix_tpu.engine.sampling import SamplingParams
from helix_tpu.serving.engine_loop import QUEUE_FULL, SHUTTING_DOWN
from helix_tpu.serving.registry import ModelRegistry
from helix_tpu.serving.tokenizer import IncrementalDetokenizer, _content_text


def _now() -> int:
    return int(time.time())


_LONGPOLL_POOL = None


def _longpoll_pool():
    """Dedicated pool for multi-host journal long-polls (they park a
    thread for tens of seconds each)."""
    global _LONGPOLL_POOL
    if _LONGPOLL_POOL is None:
        import concurrent.futures

        _LONGPOLL_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="mh-longpoll"
        )
    return _LONGPOLL_POOL


def _error(status: int, message: str, etype: str = "invalid_request_error",
           headers: Optional[dict] = None):
    return web.json_response(
        {"error": {"message": message, "type": etype}}, status=status,
        headers=headers,
    )


class EngineRequestError(Exception):
    """A request the engine rejected or failed mid-flight; surfaces as a
    structured 4xx/5xx instead of a dead stream."""


def _engine_error_response(e: Exception):
    """Map an engine error onto its HTTP shape: shed load is a clean 429
    with Retry-After, drain is 503, engine timeouts are 504, everything
    else stays the seed's 400."""
    msg = str(e)
    if msg.startswith(QUEUE_FULL):
        return _error(429, msg, "overloaded_error",
                      headers={"Retry-After": "1"})
    if msg.startswith(SHUTTING_DOWN):
        return _error(503, msg, "overloaded_error",
                      headers={"Retry-After": "5"})
    if msg.startswith("inter_token_timeout"):
        return _error(504, msg, "timeout_error")
    return _error(400, msg)


class OpenAIServer:
    def __init__(self, registry: ModelRegistry, metrics=None,
                 inter_token_timeout: Optional[float] = None):
        import os
        from helix_tpu.serving.logbuf import install as install_logbuf

        self.registry = registry
        self.metrics = metrics
        self.started = time.monotonic()
        self.logbuf = install_logbuf()
        # max seconds between consecutive engine events for one request
        # before the server gives up on it (wedged engine watchdog)
        self.inter_token_timeout = (
            inter_token_timeout
            if inter_token_timeout is not None
            else float(os.environ.get("HELIX_INTER_TOKEN_TIMEOUT", "300"))
        )

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/healthz", self.healthz)
        app.router.add_get("/metrics", self.prometheus_metrics)
        app.router.add_get("/logs", self.tail_logs)
        app.router.add_post("/admin/prefetch", self.prefetch_model)
        app.router.add_get("/v1/models", self.list_models)
        app.router.add_post("/v1/chat/completions", self.chat_completions)
        app.router.add_post("/v1/completions", self.completions)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/messages", self.anthropic_messages)
        # multi-host lockstep journal (followers long-poll over DCN;
        # see serving/multihost_serving.py)
        app.router.add_get("/multihost/commands", self.multihost_commands)
        return app

    async def multihost_commands(self, request):
        """Leader-side journal feed for follower hosts."""
        import asyncio as _asyncio

        from helix_tpu.serving.multihost_serving import LagError

        model = request.query.get("model", "")
        served = self.registry.get(model)
        if served is None or served.loop is None:
            return _error(404, f"model '{model}' is not served here")
        journal = getattr(served.loop.engine, "journal", None)
        if journal is None:
            return _error(
                400, f"model '{model}' is not running in lockstep mode"
            )
        since = int(request.query.get("since", 0))
        timeout = min(float(request.query.get("timeout", 25)), 55.0)
        try:
            # long-polls park a thread for up to ``timeout`` — keep them
            # out of the shared default executor or a few followers
            # would starve every other run_in_executor call
            records = await _asyncio.get_running_loop().run_in_executor(
                _longpoll_pool(), journal.read_since, since, timeout
            )
        except LagError as e:
            return web.json_response({"lagged": True, "error": str(e)})
        return web.json_response({"records": records})

    # ------------------------------------------------------------------
    async def healthz(self, request):
        return web.json_response(
            {"status": "ok", "models": self.registry.names()}
        )

    async def prometheus_metrics(self, request):
        lines = [
            "# TYPE helix_uptime_seconds gauge",
            f"helix_uptime_seconds {time.monotonic() - self.started:.1f}",
        ]
        for m in self.registry.list():
            if m.loop is None:
                continue
            eng = m.loop.engine
            tag = f'{{model="{m.name}"}}'
            lines += [
                f"helix_engine_steps{tag} {m.loop.steps}",
                f"helix_prefill_tokens_total{tag} {eng.num_prefill_tokens}",
                f"helix_decode_tokens_total{tag} {eng.num_decode_tokens}",
                # ragged mixed steps: chunk prefill + decode in ONE call
                f"helix_mixed_steps_total{tag} "
                f"{getattr(eng, 'num_mixed_steps', 0)}",
                # MoE prefill routing assignments dropped to expert-
                # capacity overflow (rode the residual stream instead)
                f"helix_moe_dropped_tokens_total{tag} "
                f"{getattr(eng, 'moe_dropped_tokens', 0)}",
                f"helix_waiting_requests{tag} {len(eng.waiting)}",
                f"helix_active_slots{tag} "
                f"{sum(1 for s in eng.slots if s is not None)}",
                f"helix_free_pages{tag} {eng.allocator.free_pages}",
                # robustness spine: step failure/retry/quarantine/shed
                # accounting (ISSUE 2)
                f"helix_step_failures_total{tag} "
                f"{getattr(m.loop, 'step_failures', 0)}",
                f"helix_step_retries_total{tag} "
                f"{getattr(m.loop, 'step_retries', 0)}",
                f"helix_quarantine_evictions_total{tag} "
                f"{getattr(m.loop, 'quarantine_evictions', 0)}",
                f"helix_shed_requests_total{tag} "
                f"{getattr(m.loop, 'shed_requests', 0)}",
            ]
            pc = getattr(eng, "prefix_cache", None)
            if pc is not None:
                st = pc.stats
                lines += [
                    f"helix_prefix_cache_pages{tag} {st['pages']}",
                    f"helix_prefix_cache_hit_pages_total{tag} "
                    f"{st['hits']}",
                    f"helix_prefix_cache_miss_pages_total{tag} "
                    f"{st['misses']}",
                ]
            ttfts = getattr(eng, "recent_ttfts", None)
            if ttfts:
                # the engine thread appends concurrently; a mutation during
                # iteration raises — retry on a fresh snapshot
                s = []
                for _ in range(3):
                    try:
                        s = sorted(ttfts)
                        break
                    except RuntimeError:
                        continue
                if s:
                    lines += [
                        f"helix_ttft_ms_p50{tag} {s[len(s) // 2]:.1f}",
                        f"helix_ttft_ms_p95{tag} "
                        f"{s[min(len(s) - 1, int(len(s) * 0.95))]:.1f}",
                    ]
        mgr = self._residency_manager()
        if mgr is not None:
            # executor: stats() takes the manager lock, which acquire()
            # holds across whole model builds — never block the event loop
            st = await asyncio.get_running_loop().run_in_executor(
                None, mgr.stats
            )
            lines += [
                "# TYPE helix_residency_loads_total counter",
                f"helix_residency_loads_total {st['loads']}",
                f"helix_residency_evictions_total {st['evictions']}",
                f"helix_residency_used_bytes {st['used_bytes']}",
            ]
            for name, ms in sorted(st["swap_ms"].items()):
                lines.append(
                    f'helix_model_swap_ms{{model="{name}"}} {ms:.1f}'
                )
            for name, ms in sorted(st["load_ms"].items()):
                lines.append(
                    f'helix_model_load_ms{{model="{name}"}} {ms:.1f}'
                )
        return web.Response(text="\n".join(lines) + "\n")

    def _residency_manager(self):
        """The ResidencyManager behind the registry, if hot-swap is on."""
        for cand in (self.registry, getattr(self.registry, "inner", None)):
            if cand is not None and hasattr(cand, "prefetch"):
                return cand
        return None

    async def prefetch_model(self, request):
        """Stage a model's weights in the background ahead of traffic (the
        async half of hot-swap; swap_ms in /metrics shows the payoff)."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — client error, not server fault
            return _error(400, "invalid JSON body")
        name = body.get("model", "")
        mgr = self._residency_manager()
        if mgr is None:
            return _error(
                409, "no residency manager: profile has no residency block"
            )
        if name not in mgr.names():
            return _error(404, f"unknown model {name!r}")
        # executor: prefetch() takes the manager lock (see /metrics note)
        started = await asyncio.get_running_loop().run_in_executor(
            None, lambda: bool(mgr.prefetch(name))
        )
        return web.json_response(
            {"model": name, "prefetch": "started" if started else "declined"}
        )

    async def tail_logs(self, request):
        """Node log tail for the admin UI (hydra logbuf analogue)."""
        try:
            n = max(1, min(int(request.query.get("tail", 200)), 2000))
        except ValueError:
            return _error(400, "tail must be an integer")
        return web.json_response({"logs": self.logbuf.tail(n)})

    async def list_models(self, request):
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": m.name,
                        "object": "model",
                        "created": m.created,
                        "owned_by": m.owned_by,
                        **(
                            {"context_length": m.context_length}
                            if m.context_length
                            else {}
                        ),
                    }
                    for m in self.registry.list()
                ],
            }
        )

    # ------------------------------------------------------------------
    async def _lookup(self, model: str):
        """Resolve a model, faulting it in off the event loop (the registry
        may be a ResidencyManager that loads weights on demand).  Returns
        (served, error_response)."""
        try:
            served = await asyncio.get_running_loop().run_in_executor(
                None, self.registry.get, model
            )
        except MemoryError as e:
            return None, _error(503, str(e), "overloaded_error")
        if served is None:
            return None, _error(
                404,
                f"model '{model}' not found; available: {self.registry.names()}",
                "model_not_found",
            )
        return served, None

    @staticmethod
    def _require_loop(served, model: str):
        """Generation needs a live engine loop; embedding-only workers
        and multi-host FOLLOWERS (journal replay, no local traffic) have
        loop=None and must answer with a clean error, not a 500."""
        if served.loop is not None:
            return None
        if served.follower is not None:
            return _error(
                409,
                f"'{model}' is a multi-host follower replica on this "
                "host; send traffic to the leader",
            )
        return _error(
            404, f"'{model}' does not serve generation", "model_not_found"
        )

    @staticmethod
    def _precheck_admission(served, prompt_ids):
        """Shed before committing response headers: streaming handlers
        prepare() the SSE response before the first engine event, so a
        queue_full discovered after submit can only surface as an in-band
        error frame — this pre-check turns it into a real 429/503."""
        check = getattr(served.loop, "check_admission", None)
        if check is None:
            return None
        err = check(len(prompt_ids), count_shed=True)
        if err is None:
            return None
        return _engine_error_response(EngineRequestError(err))

    def _sampling_from_body(self, body: dict) -> SamplingParams:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            max_tokens=int(
                body.get("max_tokens")
                or body.get("max_completion_tokens")
                or 256
            ),
            stop=tuple(stop),
            seed=body.get("seed"),
        )

    async def _generate(self, served, prompt_ids, sampling, extra=None):
        """Submit to the engine; yields (delta_text, token_id, finished,
        finish_reason).  ``extra`` carries multimodal Request fields."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_event(ev):
            loop.call_soon_threadsafe(q.put_nowait, ev)

        req = Request(
            id=f"req-{uuid.uuid4().hex[:12]}",
            prompt_tokens=list(prompt_ids),
            sampling=sampling,
            stop_token_ids=tuple(served.tokenizer.eos_ids),
            **(extra or {}),
        )
        served.loop.submit(req, on_event)
        detok = IncrementalDetokenizer(served.tokenizer)
        emitted_len = 0
        try:
            while True:
                try:
                    ev = await asyncio.wait_for(
                        q.get(), timeout=self.inter_token_timeout
                    )
                except asyncio.TimeoutError:
                    # never leak a raw TimeoutError (dead stream / bare
                    # 500): abort the engine request and surface a typed
                    # error the handlers map to 504 / an SSE error event
                    served.loop.abort(req.id)
                    raise EngineRequestError(
                        f"inter_token_timeout: no engine event for "
                        f"{self.inter_token_timeout:.0f}s; request "
                        f"{req.id} aborted"
                    ) from None
                if ev.error:
                    raise EngineRequestError(ev.error)
                is_eos = ev.token_id in served.tokenizer.eos_ids
                delta = "" if is_eos else detok.push(ev.token_id)
                # serving-level stop strings
                hit_stop = None
                for s in sampling.stop:
                    idx = detok._emitted.find(s, max(0, emitted_len - len(s)))
                    if idx >= 0:
                        hit_stop = idx
                        break
                if hit_stop is not None:
                    keep = detok._emitted[:hit_stop]
                    final_delta = keep[emitted_len:]
                    served.loop.abort(req.id)
                    yield final_delta, ev.token_id, True, "stop"
                    return
                emitted_len = len(detok._emitted)
                yield delta, ev.token_id, ev.finished, ev.finish_reason
                if ev.finished:
                    return
        finally:
            if not req.finished:
                served.loop.abort(req.id)

    # ------------------------------------------------------------------
    async def chat_completions(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model", "")
        served, err = await self._lookup(model)
        if err is not None:
            return err
        if served.kind == "embedding":
            return _error(404, f"model '{model}' is an embedding model",
                          "model_not_found")
        err = self._require_loop(served, model)
        if err is not None:
            return err
        messages = body.get("messages")
        if not messages:
            return _error(400, "'messages' is required")
        sampling = self._sampling_from_body(body)
        has_images = any(
            isinstance(m.get("content"), list)
            and any(
                p.get("type") in ("image_url", "image")
                for p in m["content"]
            )
            for m in messages
        )
        extra = None
        if has_images:
            if served.vision is None:
                return _error(
                    400, f"model '{model}' does not accept image input"
                )
            try:
                extra = await asyncio.get_running_loop().run_in_executor(
                    None, served.vision.prepare, messages, served.tokenizer
                )
            except Exception as e:  # noqa: BLE001 — bad image data etc.
                return _error(400, f"image processing failed: {e}")
            prompt_ids = extra.pop("prompt_tokens")
        else:
            prompt_ids = served.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True
            )
        shed = self._precheck_admission(served, prompt_ids)
        if shed is not None:
            return shed
        rid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = _now()

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                }
            )
            await resp.prepare(request)

            async def send(obj):
                await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

            first = True
            finish_reason = None
            ntokens = 0
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling, extra
              ):
                ntokens += 1
                chunk_delta = {}
                if first:
                    chunk_delta["role"] = "assistant"
                    first = False
                if delta:
                    chunk_delta["content"] = delta
                finish_reason = reason if finished else None
                await send(
                    {
                        "id": rid,
                        "object": "chat.completion.chunk",
                        "created": created,
                        "model": model,
                        "choices": [
                            {
                                "index": 0,
                                "delta": chunk_delta,
                                "finish_reason": finish_reason,
                            }
                        ],
                    }
                )
                if finished:
                    break
            except EngineRequestError as e:
                await send({"error": {"message": str(e)}})
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp

        text_parts = []
        finish_reason = "stop"
        ntokens = 0
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling, extra
          ):
            text_parts.append(delta)
            ntokens += 1
            if finished:
                finish_reason = reason or "stop"
                break
        except EngineRequestError as e:
            return _engine_error_response(e)
        return web.json_response(
            {
                "id": rid,
                "object": "chat.completion",
                "created": created,
                "model": model,
                "choices": [
                    {
                        "index": 0,
                        "message": {
                            "role": "assistant",
                            "content": "".join(text_parts),
                        },
                        "finish_reason": finish_reason,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(prompt_ids),
                    "completion_tokens": ntokens,
                    "total_tokens": len(prompt_ids) + ntokens,
                },
            }
        )

    # ------------------------------------------------------------------
    async def completions(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model", "")
        served, err = await self._lookup(model)
        if err is not None:
            return err
        err = self._require_loop(served, model)
        if err is not None:
            return err
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        sampling = self._sampling_from_body(body)
        prompt_ids = served.tokenizer.encode(prompt)
        shed = self._precheck_admission(served, prompt_ids)
        if shed is not None:
            return shed
        rid = f"cmpl-{uuid.uuid4().hex[:16]}"
        created = _now()

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling
              ):
                await resp.write(
                    f"data: {json.dumps({'id': rid, 'object': 'text_completion', 'created': created, 'model': model, 'choices': [{'index': 0, 'text': delta, 'finish_reason': reason if finished else None}]})}\n\n".encode()
                )
                if finished:
                    break
            except EngineRequestError as e:
                await resp.write(
                    f"data: {json.dumps({'error': {'message': str(e)}})}\n\n".encode()
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp

        parts = []
        finish_reason = "stop"
        n = 0
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling
          ):
            parts.append(delta)
            n += 1
            if finished:
                finish_reason = reason or "stop"
                break
        except EngineRequestError as e:
            return _engine_error_response(e)
        return web.json_response(
            {
                "id": rid,
                "object": "text_completion",
                "created": created,
                "model": model,
                "choices": [
                    {
                        "index": 0,
                        "text": "".join(parts),
                        "finish_reason": finish_reason,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(prompt_ids),
                    "completion_tokens": n,
                    "total_tokens": len(prompt_ids) + n,
                },
            }
        )

    # ------------------------------------------------------------------
    async def embeddings(self, request):
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model", "")
        served, err = await self._lookup(model)
        if err is not None:
            return err
        if served.kind not in ("embedding", "vision-embedding"):
            return _error(
                404, f"'{model}' is not an embedding model", "model_not_found"
            )
        inputs = body.get("input", [])
        if isinstance(inputs, (str, dict)):
            inputs = [inputs]
        bad = [
            x for x in inputs if isinstance(x, dict) and "image" not in x
        ]
        if bad:
            return _error(
                400,
                "dict inputs must be {\"image\": <url/base64>}; got keys "
                f"{sorted(bad[0])}",
            )
        has_images = any(
            isinstance(x, dict) and "image" in x for x in inputs
        )
        if has_images:
            # vision-RAG: image entries ({"image": url/b64}) pool through
            # the vision tower into the same space as text (reference:
            # Qwen3-VL-Embedding pooling runner)
            if served.kind != "vision-embedding":
                return _error(
                    400,
                    f"'{model}' cannot embed images; serve a "
                    "vision-embedding model",
                )
            embed = served.embedder.embed_mixed
        else:
            embed = served.embedder.embed_texts
        vectors = await asyncio.get_running_loop().run_in_executor(
            None, embed, inputs
        )
        text_tokens = sum(
            len(served.tokenizer.encode(t))
            for t in inputs
            if isinstance(t, str)
        )
        return web.json_response(
            {
                "object": "list",
                "model": model,
                "data": [
                    {"object": "embedding", "index": i, "embedding": list(map(float, v))}
                    for i, v in enumerate(vectors)
                ],
                "usage": {
                    "prompt_tokens": text_tokens,
                    "total_tokens": text_tokens,
                },
            }
        )

    # ------------------------------------------------------------------
    async def anthropic_messages(self, request):
        """Native Anthropic /v1/messages surface (reference:
        ``api/pkg/anthropic/anthropic_proxy.go``)."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model", "")
        served, err = await self._lookup(model)
        if err is not None:
            return err
        err = self._require_loop(served, model)
        if err is not None:
            return err
        messages = list(body.get("messages", []))
        if body.get("system"):
            messages = [{"role": "system", "content": body["system"]}] + messages
        sampling = SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            max_tokens=int(body.get("max_tokens", 256)),
            stop=tuple(body.get("stop_sequences", []) or []),
        )
        prompt_ids = served.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True
        )
        shed = self._precheck_admission(served, prompt_ids)
        if shed is not None:
            return shed
        rid = f"msg_{uuid.uuid4().hex[:20]}"

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)

            async def ev(name, obj):
                await resp.write(
                    f"event: {name}\ndata: {json.dumps(obj)}\n\n".encode()
                )

            await ev(
                "message_start",
                {
                    "type": "message_start",
                    "message": {
                        "id": rid,
                        "type": "message",
                        "role": "assistant",
                        "model": model,
                        "content": [],
                        "usage": {"input_tokens": len(prompt_ids), "output_tokens": 0},
                    },
                },
            )
            await ev(
                "content_block_start",
                {
                    "type": "content_block_start",
                    "index": 0,
                    "content_block": {"type": "text", "text": ""},
                },
            )
            n = 0
            stop_reason = "end_turn"
            try:
              async for delta, tok, finished, reason in self._generate(
                served, prompt_ids, sampling
              ):
                n += 1
                if delta:
                    await ev(
                        "content_block_delta",
                        {
                            "type": "content_block_delta",
                            "index": 0,
                            "delta": {"type": "text_delta", "text": delta},
                        },
                    )
                if finished:
                    stop_reason = (
                        "max_tokens" if reason == "length" else "end_turn"
                    )
                    break
            except EngineRequestError as e:
                await ev("error", {"type": "error",
                                   "error": {"message": str(e)}})
            await ev(
                "content_block_stop", {"type": "content_block_stop", "index": 0}
            )
            await ev(
                "message_delta",
                {
                    "type": "message_delta",
                    "delta": {"stop_reason": stop_reason},
                    "usage": {"output_tokens": n},
                },
            )
            await ev("message_stop", {"type": "message_stop"})
            await resp.write_eof()
            return resp

        parts = []
        n = 0
        stop_reason = "end_turn"
        try:
          async for delta, tok, finished, reason in self._generate(
            served, prompt_ids, sampling
          ):
            parts.append(delta)
            n += 1
            if finished:
                stop_reason = "max_tokens" if reason == "length" else "end_turn"
                break
        except EngineRequestError as e:
            return _engine_error_response(e)
        return web.json_response(
            {
                "id": rid,
                "type": "message",
                "role": "assistant",
                "model": model,
                "content": [{"type": "text", "text": "".join(parts)}],
                "stop_reason": stop_reason,
                "usage": {
                    "input_tokens": len(prompt_ids),
                    "output_tokens": n,
                },
            }
        )


def run_server(registry: ModelRegistry, host="0.0.0.0", port=8000):
    server = OpenAIServer(registry)
    web.run_app(server.build_app(), host=host, port=port, print=None)
