"""Tokenization + chat templating for the serving surface.

The reference delegates tokenization to vLLM inside its containers; here the
engine works on token ids, so the serving layer owns the text boundary:

- ``HFTokenizer`` wraps a local ``tokenizer.json`` (HuggingFace `tokenizers`
  runtime — no network fetch; checkpoints are mounted like model weights).
- ``ByteTokenizer`` is a dependency-free UTF-8 byte fallback used by tests
  and as a safety net when a model directory ships no tokenizer.
- Chat templating implements the Llama-3 instruct wire format natively plus
  a generic fallback; template choice keys off the model name the same way
  the reference's model catalogue carries per-model metadata
  (``api/pkg/model/models.go``).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    def encode(self, text: str) -> list: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    @property
    def eos_ids(self) -> tuple: ...
    @property
    def vocab_size(self) -> int: ...
    def apply_chat_template(self, messages: list, add_generation_prompt: bool = True) -> list: ...


class ByteTokenizer:
    """UTF-8 bytes + 4 specials. id = byte + 4."""

    BOS, EOS, PAD, SEP = 0, 1, 2, 3
    OFFSET = 4

    @property
    def vocab_size(self) -> int:
        return 260

    @property
    def eos_ids(self) -> tuple:
        return (self.EOS,)

    def encode(self, text: str) -> list:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        bs = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return bs.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages, add_generation_prompt=True) -> list:
        out = [self.BOS]
        for m in messages:
            out += self.encode(f"{m['role']}: ")
            out += self.encode(_content_text(m.get("content", "")))
            out.append(self.SEP)
        if add_generation_prompt:
            out += self.encode("assistant: ")
        return out


def _content_text(content) -> str:
    """OpenAI content can be a string or a list of typed parts."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        return "".join(
            p.get("text", "") for p in content if p.get("type") == "text"
        )
    return str(content)


class HFTokenizer:
    """Wraps a local `tokenizers` fast-tokenizer file."""

    def __init__(self, path: str, model_name: str = ""):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(path)
        self.model_name = model_name
        self._eos_ids = tuple(
            i
            for t in (
                "</s>",
                "<|eot_id|>",
                "<|end_of_text|>",
                "<|endoftext|>",
                "<|im_end|>",
                "<|end|>",
            )
            if (i := self._tok.token_to_id(t)) is not None
        )

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def eos_ids(self) -> tuple:
        return self._eos_ids

    def encode(self, text: str) -> list:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def _special(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def apply_chat_template(self, messages, add_generation_prompt=True) -> list:
        name = self.model_name.lower()
        if "llama-3" in name or self._special("<|start_header_id|>") is not None:
            return self._llama3_template(messages, add_generation_prompt)
        if "qwen" in name or self._special("<|im_start|>") is not None:
            return self._chatml_template(messages, add_generation_prompt)
        # generic fallback
        ids: list = []
        for m in messages:
            ids += self.encode(
                f"{m['role']}: {_content_text(m.get('content', ''))}\n"
            )
        if add_generation_prompt:
            ids += self.encode("assistant: ")
        return ids

    def _llama3_template(self, messages, add_gen) -> list:
        """Llama-3 instruct format (header/eot special tokens)."""
        bot = self._special("<|begin_of_text|>")
        soh = self._special("<|start_header_id|>")
        eoh = self._special("<|end_header_id|>")
        eot = self._special("<|eot_id|>")
        ids = [bot] if bot is not None else []
        for m in messages:
            ids += [soh, *self.encode(m["role"]), eoh]
            ids += self.encode("\n\n" + _content_text(m.get("content", "")))
            ids.append(eot)
        if add_gen:
            ids += [soh, *self.encode("assistant"), eoh]
            ids += self.encode("\n\n")
        return ids

    def _chatml_template(self, messages, add_gen) -> list:
        ims = self._special("<|im_start|>")
        ime = self._special("<|im_end|>")
        nl = self.encode("\n")
        ids: list = []
        for m in messages:
            ids += [ims, *self.encode(m["role"]), *nl]
            ids += self.encode(_content_text(m.get("content", "")))
            ids += [ime, *nl]
        if add_gen:
            ids += [ims, *self.encode("assistant"), *nl]
        return ids


def load_tokenizer(model_dir: Optional[str], model_name: str = ""):
    """HF fast tokenizer if the model dir ships one, else byte fallback."""
    if model_dir:
        p = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(p):
            return HFTokenizer(p, model_name=model_name)
    return ByteTokenizer()


class IncrementalDetokenizer:
    """Streams text from a growing token list without re-decoding garbage at
    UTF-8/multi-token boundaries: re-decodes the full sequence and emits the
    stable suffix delta."""

    def __init__(self, tokenizer):
        self.tok = tokenizer
        self.ids: list = []
        self._emitted = ""

    def push(self, token_id: int) -> str:
        self.ids.append(token_id)
        text = self.tok.decode(self.ids)
        # hold back a trailing replacement char (possible split UTF-8 rune)
        safe = text[:-1] if text.endswith("�") else text
        delta = safe[len(self._emitted):]
        self._emitted = safe
        return delta
