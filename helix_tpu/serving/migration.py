"""Cross-runner request migration: wire format, shipping, stream glue.

ISSUE 11 makes an in-flight request a first-class, portable object.  The
engine builds and consumes ``RequestSnapshot``s (``Engine.export_request``
/ ``import_request`` — checksummed pages, device-evolved sampler state);
this module owns everything around that core:

- the **wire format**: a JSON-safe encoding of a snapshot (numpy page
  buffers ride base64 with dtype+shape; scalar fields are covered by a
  meta checksum) shipped over ``POST /v1/migrate/import``;
- the **drain shipper** (``PeerShipper``): during graceful shutdown the
  node agent wires it into each engine loop — survivors of the drain
  deadline are snapshotted and POSTed to a peer runner instead of shed
  (targets come from the control plane's migration-targets endpoint);
- the **imported-stream registry** (``ImportedStreams``): a migrated-in
  request starts generating as soon as the peer engine has resources,
  possibly before anyone is listening — its token events buffer here
  until the control plane attaches via ``POST /v1/migrate/resume`` (or
  the claim TTL expires and the request is aborted);
- the **SSE plumbing** the control plane's mid-stream failover uses to
  watch a proxied stream (incremental parser, delta-text extraction,
  frame templating) so a runner death past the first byte continues the
  client's stream with exactly-once token delivery;
- the **metric vocabulary**: every ``helix_migrations_*`` /
  ``helix_migration_*`` / ``helix_cp_midstream_*`` /
  ``helix_cp_runner_draining`` series is minted HERE and only here
  (``tools/lint_metrics.py`` contract 6) — the runner and control plane
  call the collector helpers below.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
import time
from typing import Callable, Optional

import numpy as np

from helix_tpu.engine.engine import (
    SNAPSHOT_VERSION,
    RequestSnapshot,
    SnapshotError,
)

# ---------------------------------------------------------------------------
# metric vocabulary (lint_metrics contract 6: minted only in this module)
# ---------------------------------------------------------------------------

MIGRATIONS_EXPORTED = "helix_migrations_exported_total"
MIGRATIONS_IMPORTED = "helix_migrations_imported_total"
MIGRATION_FAILURES = "helix_migration_failures_total"
MIGRATION_DRAIN_STATE = "helix_migration_drain_state"
CP_MIDSTREAM_FAILOVERS = "helix_cp_midstream_failovers_total"
CP_RUNNER_DRAINING = "helix_cp_runner_draining"

# KV-transfer series (ISSUE 14, lint_metrics contract 10: the
# ``helix_xfer_*`` family is minted only here).  Shipping is the network
# rung of the residency ladder, so its outcomes get the same per-outcome
# accounting the dispatch path has — a slow or flapping peer shows up as
# a labelled counter, not a mystery drain stall.
XFER_ATTEMPTS = "helix_xfer_attempts_total"
XFER_SHIP_SECONDS = "helix_xfer_ship_seconds_total"
XFER_SHIPPED_BYTES = "helix_xfer_shipped_bytes_total"
XFER_DEADLINE_EXCEEDED = "helix_xfer_deadline_exceeded_total"
XFER_PREFILL_HANDOFFS = "helix_xfer_prefill_handoffs_total"

# every way one ship attempt can end (the XFER_ATTEMPTS label values)
XFER_OUTCOMES = (
    "ok",          # peer answered 200 — snapshot accepted
    "unreachable",  # connect error / injected drop
    "rejected",    # peer answered 4xx (corrupt/incompatible/duplicate)
    "http_error",  # peer answered 5xx / other status
    "timeout",     # per-attempt timeout expired
)


class XferStats:
    """Process-wide KV-transfer accounting (runner side).  Thread
    contract: shippers increment under the lock from worker threads; the
    /metrics collector reads snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self.attempts = {o: 0 for o in XFER_OUTCOMES}
        self.ship_seconds = 0.0
        self.shipped_bytes = 0
        self.shipped_pages = 0
        self.deadline_exceeded = 0
        self.prefill_handoffs = 0

    def note_attempt(self, outcome: str, seconds: float = 0.0) -> None:
        with self._lock:
            if outcome not in self.attempts:
                outcome = "http_error"
            self.attempts[outcome] += 1
            self.ship_seconds += max(0.0, seconds)

    def note_shipped(self, wire: dict, prefill: bool = False) -> None:
        with self._lock:
            pages = wire.get("pages") or []
            self.shipped_pages += len(pages)
            self.shipped_bytes += sum(
                len((f or {}).get("b64", ""))
                for p in pages
                for f in (p or {}).values()
                if isinstance(f, dict)
            )
            if prefill:
                self.prefill_handoffs += 1

    def note_deadline(self) -> None:
        with self._lock:
            self.deadline_exceeded += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "attempts": dict(self.attempts),
                "ship_seconds": self.ship_seconds,
                "shipped_bytes": self.shipped_bytes,
                "shipped_pages": self.shipped_pages,
                "deadline_exceeded": self.deadline_exceeded,
                "prefill_handoffs": self.prefill_handoffs,
            }


# the one process-wide instance every shipper feeds (drain shippers are
# per-drain, disagg shippers per-request — counters must outlive both)
XFER_STATS = XferStats()


def collect_xfer(c) -> None:
    """Runner-side KV-transfer series (called from the OpenAI server's
    scrape-time collector)."""
    snap = XFER_STATS.snapshot()
    for outcome, n in sorted(snap["attempts"].items()):
        c.counter(
            XFER_ATTEMPTS, n, {"outcome": outcome},
            help="KV snapshot ship attempts by outcome",
        )
    c.counter(
        XFER_SHIP_SECONDS, snap["ship_seconds"],
        help="Cumulative wall time spent shipping KV snapshots",
    )
    c.counter(
        XFER_SHIPPED_BYTES, snap["shipped_bytes"],
        help="Wire bytes of successfully shipped KV snapshots",
    )
    c.counter(
        XFER_DEADLINE_EXCEEDED, snap["deadline_exceeded"],
        help="Ships abandoned at the total transfer deadline",
    )
    c.counter(
        XFER_PREFILL_HANDOFFS, snap["prefill_handoffs"],
        help="Disaggregated prefill snapshots shipped to a decode peer",
    )

# error-message prefix for a request that was exported instead of shed
# (the engine-loop/openai error-mapping contract, like QUEUE_FULL); the
# control plane's mid-stream failover parses the peer out of the message
MIGRATED = "migrated"

_PEER_RE = re.compile(r"peer=([A-Za-z0-9._:\-]+)")


def migrated_error(request_id: str, peer_id: str) -> str:
    """The in-band terminal event for a drained-and-shipped request.
    Carries enough structure for the control plane to resume the stream
    on the peer: the engine request id and the peer runner id."""
    return f"{MIGRATED}: request {request_id} exported to peer={peer_id}"


def parse_migrated_peer(message: str) -> Optional[str]:
    """The peer runner id from a ``migrated_error`` message, or None."""
    if not message.startswith(MIGRATED):
        return None
    m = _PEER_RE.search(message)
    return m.group(1) if m else None


def collect_runner_migration(c, loop, labels: dict) -> None:
    """Runner-side migration series for one engine loop (called from the
    OpenAI server's scrape-time collector; plain GIL-atomic reads)."""
    eng = loop.engine
    c.counter(
        MIGRATIONS_EXPORTED,
        getattr(eng, "num_snapshots_exported", 0), labels,
        help="Request snapshots exported for cross-runner migration",
    )
    c.counter(
        MIGRATIONS_IMPORTED,
        getattr(eng, "num_snapshots_imported", 0), labels,
        help="Request snapshots imported from a peer runner",
    )
    c.counter(
        MIGRATION_FAILURES,
        getattr(loop, "migration_failures", 0), labels,
        help="Failed exports/ships/imports (request shed instead)",
    )
    c.gauge(
        MIGRATION_DRAIN_STATE,
        1 if getattr(loop, "draining", False) else 0, labels,
        help="1 while this engine loop is draining (shutdown ladder)",
    )


def collect_cp_migration(c, failovers: int, draining: dict) -> None:
    """Control-plane migration series: mid-stream failover count + the
    per-runner drain-state gauge (pruned with the runner — ``draining``
    comes from live router state, the breaker-cardinality rule)."""
    c.counter(
        CP_MIDSTREAM_FAILOVERS, failovers,
        help="Client streams continued on another runner after a "
             "mid-stream death (resume-from-snapshot or replay)",
    )
    for rid, is_draining in sorted(draining.items()):
        c.gauge(
            CP_RUNNER_DRAINING, 1 if is_draining else 0,
            {"runner": rid},
            help="1 while the runner reports draining in its heartbeat",
        )


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def migration_timeout() -> float:
    """HELIX_MIGRATION_TIMEOUT: per-snapshot ship timeout at export AND
    the TTL an imported request waits for its stream to be claimed."""
    return float(os.environ.get("HELIX_MIGRATION_TIMEOUT", "30") or 30)


def drain_seconds() -> float:
    """HELIX_DRAIN_SECONDS: graceful-shutdown drain window before
    survivors are exported (node agent SIGTERM path)."""
    return float(os.environ.get("HELIX_DRAIN_SECONDS", "10") or 10)


def midstream_failover_enabled() -> bool:
    """HELIX_MIDSTREAM_FAILOVER: opt-in for the control plane's
    SSE-parsing failover path (resume/replay past the first byte)."""
    return os.environ.get("HELIX_MIDSTREAM_FAILOVER", "") not in ("", "0")


def disagg_pools_enabled() -> bool:
    """HELIX_POOL_DISAGG: opt-in for disaggregated prefill/decode —
    the control plane hands streaming prompts to a prefill-pool runner
    that computes the prompt, ships the KV snapshot to a decode-pool
    peer, and the stream resumes there.  Off = colocated serving
    (every runner prefills its own traffic), the seed behaviour."""
    return os.environ.get("HELIX_POOL_DISAGG", "") not in ("", "0")


# disaggregation handoff headers (ISSUE 14): the control plane marks a
# dispatch as prefill-only and names the decode peer the snapshot must
# ship to.  Runner-token gated on the runner side like /v1/migrate/* —
# handoff is cluster-internal traffic.
DISAGG_HEADER = "X-Helix-Disagg"
DISAGG_PEER_ID_HEADER = "X-Helix-Disagg-Peer"
DISAGG_PEER_ADDR_HEADER = "X-Helix-Disagg-Peer-Addr"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except (TypeError, ValueError):
        return default


class XferConfig:
    """KV-transfer retry/backoff discipline (ISSUE 14 satellite): every
    ship attempt gets a per-attempt timeout, attempts back off with a
    capped exponential, and the WHOLE transfer has a hard deadline — a
    slow or black-holed peer can wedge neither a drain nor a prefill
    handoff (the hard fallback — local recompute — is always reachable
    in bounded time)."""

    def __init__(self, attempt_timeout: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 backoff_base: Optional[float] = None,
                 backoff_cap: Optional[float] = None,
                 deadline: Optional[float] = None):
        self.attempt_timeout = (
            attempt_timeout if attempt_timeout is not None
            else _env_float("HELIX_XFER_ATTEMPT_TIMEOUT", 10.0)
        )
        self.max_attempts = (
            max_attempts if max_attempts is not None
            else max(1, _env_int("HELIX_XFER_MAX_ATTEMPTS", 3))
        )
        self.backoff_base = (
            backoff_base if backoff_base is not None
            else _env_float("HELIX_XFER_BACKOFF_BASE", 0.1)
        )
        self.backoff_cap = (
            backoff_cap if backoff_cap is not None
            else _env_float("HELIX_XFER_BACKOFF_CAP", 2.0)
        )
        self.deadline = (
            deadline if deadline is not None
            else _env_float("HELIX_XFER_DEADLINE", migration_timeout())
        )


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

_PAGE_FIELDS = ("k", "v", "k_scale", "v_scale")
# RequestSnapshot fields covered by the meta checksum (everything except
# the page payloads, which carry per-page digests of their own)
_META_FIELDS = (
    "version", "model", "request_id", "prompt_tokens", "output_tokens",
    "sampling", "stop_token_ids", "tenant", "trace_id", "sched_class",
    "max_len", "preempt_count", "position", "last_token", "mrope_delta",
    "key", "token_counts", "page_size", "num_layers", "kv_heads",
    "head_dim", "kv_dtype", "page_checksums", "total_pages", "adapter",
)


def _wire_adapter(doc: dict) -> str:
    """The snapshot's adapter id, sanitised at the wire boundary."""
    from helix_tpu.engine.adapters import sanitize_adapter_id

    raw = doc.get("adapter", "") or ""
    if not raw:
        return ""
    adapter = sanitize_adapter_id(str(raw))
    if not adapter:
        raise SnapshotError(
            "snapshot adapter id failed sanitisation",
            code="snapshot_invalid",
        )
    return adapter


def _meta_checksum(doc: dict) -> str:
    h = hashlib.blake2b(digest_size=16)
    canon = {
        k: doc.get(k) for k in _META_FIELDS
    }
    if not canon.get("adapter"):
        # adapter-free snapshots hash EXACTLY like pre-ISSUE-15 wires
        # (the key joined the schema later): both directions of a
        # mixed-version rollout keep verifying for base-model traffic.
        # An adapter-carrying snapshot hashes the id — an old importer
        # rejects it (typed), which beats silently dropping the adapter
        canon.pop("adapter", None)
    h.update(json.dumps(canon, sort_keys=True, default=str).encode())
    return h.hexdigest()


def _encode_array(a) -> Optional[dict]:
    if a is None:
        return None
    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(doc) -> Optional[np.ndarray]:
    if doc is None:
        return None
    try:
        raw = base64.b64decode(doc["b64"])
        a = np.frombuffer(raw, dtype=np.dtype(doc["dtype"]))
        return a.reshape([int(d) for d in doc["shape"]]).copy()
    except (KeyError, TypeError, ValueError) as e:
        raise SnapshotError(
            f"undecodable page buffer: {e}", code="snapshot_corrupt"
        ) from e


def snapshot_to_wire(snap: RequestSnapshot) -> dict:
    """JSON-safe encoding of a snapshot: scalar fields verbatim, page
    buffers as base64 with dtype+shape, plus a meta checksum over the
    scalar fields so header corruption is as detectable as page
    corruption."""
    import dataclasses

    doc = dataclasses.asdict(snap)
    doc["token_counts"] = {
        str(k): int(v) for k, v in snap.token_counts.items()
    }
    doc["pages"] = [
        {f: _encode_array(p.get(f)) for f in _PAGE_FIELDS}
        for p in snap.pages
    ]
    doc["meta_checksum"] = _meta_checksum(doc)
    return doc


def wire_to_snapshot(doc: dict) -> RequestSnapshot:
    """Decode + structurally validate one wire document.  Raises
    ``SnapshotError`` (typed) on version/shape/meta-checksum problems;
    page-content checksums are verified later by the ENGINE, immediately
    before any allocator mutation (the import contract)."""
    if not isinstance(doc, dict):
        raise SnapshotError("snapshot body must be a JSON object")
    version = doc.get("version")
    if version != SNAPSHOT_VERSION:
        raise SnapshotError(
            f"snapshot version {version!r} != supported "
            f"{SNAPSHOT_VERSION}",
            code="snapshot_unsupported",
        )
    claimed = doc.get("meta_checksum")
    if not claimed or _meta_checksum(doc) != claimed:
        raise SnapshotError(
            "snapshot meta checksum mismatch", code="snapshot_corrupt"
        )
    counts_doc = doc.get("token_counts") or {}
    if not isinstance(counts_doc, dict):
        raise SnapshotError(
            "token_counts must be an object", code="snapshot_corrupt"
        )
    try:
        token_counts = {int(k): int(v) for k, v in counts_doc.items()}
    except (TypeError, ValueError) as e:
        raise SnapshotError(
            f"undecodable token_counts: {e}", code="snapshot_corrupt"
        ) from e
    pages_doc = doc.get("pages") or []
    pages = [
        {f: _decode_array((p or {}).get(f)) for f in _PAGE_FIELDS}
        for p in pages_doc
    ]
    try:
        return RequestSnapshot(
            version=int(version),
            model=str(doc.get("model", "")),
            request_id=str(doc.get("request_id", "")),
            prompt_tokens=[int(t) for t in doc.get("prompt_tokens", [])],
            output_tokens=[int(t) for t in doc.get("output_tokens", [])],
            sampling=dict(doc.get("sampling") or {}),
            stop_token_ids=[
                int(t) for t in doc.get("stop_token_ids", [])
            ],
            tenant=str(doc.get("tenant", "")),
            trace_id=str(doc.get("trace_id", "")),
            sched_class=str(doc.get("sched_class", "")),
            max_len=(
                int(doc["max_len"])
                if doc.get("max_len") is not None else None
            ),
            preempt_count=int(doc.get("preempt_count", 0)),
            position=(
                int(doc["position"])
                if doc.get("position") is not None else None
            ),
            last_token=(
                int(doc["last_token"])
                if doc.get("last_token") is not None else None
            ),
            mrope_delta=int(doc.get("mrope_delta", 0)),
            key=(
                [int(w) for w in doc["key"]]
                if doc.get("key") is not None else None
            ),
            token_counts=token_counts,
            page_size=int(doc.get("page_size", 0)),
            num_layers=int(doc.get("num_layers", 0)),
            kv_heads=int(doc.get("kv_heads", 0)),
            head_dim=int(doc.get("head_dim", 0)),
            kv_dtype=str(doc.get("kv_dtype", "")),
            pages=pages,
            page_checksums=[
                str(s) for s in doc.get("page_checksums", [])
            ],
            total_pages=int(doc.get("total_pages", 0) or 0),
            # multi-LoRA adapter id (ISSUE 15; absent on older wires).
            # Sanitised at the wire boundary like every other adapter
            # entry surface: a present-but-hostile id is a REJECTED
            # snapshot (never a silent fall-back to base weights, and
            # never a raw string that could reach a filestore path or
            # metrics label on the importer)
            adapter=_wire_adapter(doc),
        )
    except (TypeError, ValueError) as e:
        raise SnapshotError(
            f"malformed snapshot field: {e}", code="snapshot_corrupt"
        ) from e


# ---------------------------------------------------------------------------
# imported-stream registry (runner side)
# ---------------------------------------------------------------------------


class ImportedStream:
    """Token-event buffer for one migrated-in request.

    The peer engine resumes the request as soon as resources allow —
    typically before the control plane's resume call lands — so events
    buffer until exactly one consumer attaches.  Thread contract: the
    engine thread calls ``on_event``; the aiohttp handler (event loop
    thread) calls ``attach``."""

    def __init__(self, request_id: str, model: str, prior_tokens: list,
                 stop: tuple = (), trace_id: str = ""):
        self.request_id = request_id
        self.model = model
        self.prior_tokens = list(prior_tokens)
        # the CALLER's trace identity (ISSUE 18): carried from import
        # to resume so the resume leg lands in the same federated
        # timeline as the ship that delivered the snapshot
        self.trace_id = trace_id
        # serving-level stop STRINGS travel with the snapshot: the
        # resume stream must truncate on them exactly like the ordinary
        # handler would (engine-side stop_token_ids alone miss them)
        self.stop = tuple(s for s in (stop or ()) if s)
        self.created = time.monotonic()
        self._lock = threading.Lock()
        self._backlog: list = []
        self._consumer = None   # (asyncio loop, asyncio.Queue)
        self.claimed = False

    def on_event(self, ev) -> None:
        with self._lock:
            if self._consumer is not None:
                loop, q = self._consumer
                loop.call_soon_threadsafe(q.put_nowait, ev)
            else:
                self._backlog.append(ev)

    def attach(self, loop, q) -> bool:
        """Claim the stream (once); backlogged events drain into ``q``
        first, later events follow live.  False = already claimed."""
        with self._lock:
            if self.claimed:
                return False
            self.claimed = True
            for ev in self._backlog:
                q.put_nowait(ev)
            self._backlog = []
            self._consumer = (loop, q)
            return True


class ImportedStreams:
    """Bounded registry of migrated-in requests awaiting their stream.

    ``sweep`` expires unclaimed entries past the migration timeout and
    returns them so the caller can abort the now-ownerless requests —
    an imported request whose control plane never came back must not
    generate into the void forever."""

    def __init__(self, max_entries: int = 256):
        self._lock = threading.Lock()
        self._entries: dict[str, ImportedStream] = {}
        self.max_entries = max_entries

    def register(self, stream: ImportedStream) -> bool:
        with self._lock:
            if len(self._entries) >= self.max_entries:
                return False
            self._entries[stream.request_id] = stream
            return True

    def get(self, request_id: str) -> Optional[ImportedStream]:
        with self._lock:
            return self._entries.get(request_id)

    def discard(self, request_id: str) -> None:
        with self._lock:
            self._entries.pop(request_id, None)

    def sweep(self, ttl: Optional[float] = None) -> list:
        """Expired, never-claimed streams (removed from the registry)."""
        if ttl is None:
            ttl = migration_timeout()
        now = time.monotonic()
        with self._lock:
            dead = [
                s for s in self._entries.values()
                if not s.claimed and now - s.created > ttl
            ]
            for s in dead:
                del self._entries[s.request_id]
            return dead


# ---------------------------------------------------------------------------
# drain shipper (node-agent side)
# ---------------------------------------------------------------------------


def _flip_wire_page(wire: dict, page_idx: int) -> dict:
    """A shallow copy of ``wire`` with one byte flipped inside page
    ``page_idx``'s k buffer (chaos ``transfer`` corrupt mode).  The
    receiver's pre-mutation checksum validation MUST reject the result
    — detection-then-recompute is the contract the chaos lane proves."""
    pages = list(wire.get("pages") or [])
    if not pages:
        return wire
    i = max(0, min(page_idx, len(pages) - 1))
    page = dict(pages[i])
    k = dict(page.get("k") or {})
    raw = bytearray(base64.b64decode(k.get("b64", "") or "AA=="))
    raw[0] ^= 0xFF
    k["b64"] = base64.b64encode(bytes(raw)).decode("ascii")
    page["k"] = k
    pages[i] = page
    return {**wire, "pages": pages}


class PeerShipper:
    """Ships wire snapshots to a peer runner (the drain ladder AND the
    disaggregated prefill handoff).

    Targets are fetched once per drain from the control plane's
    migration-targets endpoint (routable, non-draining runners serving
    an overlapping model set) — or injected directly (tests, and the
    disagg handoff where the control plane names the peer).  The call
    contract matches ``EngineLoop.exporter``: given a wire dict, return
    the peer runner id that accepted it, raise on failure.

    Robustness discipline (ISSUE 14 satellite): every attempt has a
    per-attempt timeout, rounds over the target set back off with a
    capped exponential, and the whole ship has a hard deadline — a slow
    peer cannot wedge a drain, and per-outcome counters
    (``helix_xfer_attempts_total``) make a flapping link visible.
    ``post``/``clock``/``sleep`` are injectable for deterministic
    tests."""

    def __init__(self, control_plane_url: str = "", runner_id: str = "",
                 runner_token: str = "", targets: Optional[list] = None,
                 timeout: Optional[float] = None,
                 config: Optional[XferConfig] = None,
                 post=None, clock=time.monotonic, sleep=time.sleep,
                 stats: Optional[XferStats] = None,
                 prefill: bool = False):
        self.control_plane_url = control_plane_url.rstrip("/")
        self.runner_id = runner_id
        self.runner_token = runner_token
        self._targets = targets
        self.config = config if config is not None else XferConfig(
            attempt_timeout=timeout
        )
        # legacy knob: an explicit timeout= is the per-attempt timeout
        self.timeout = self.config.attempt_timeout
        self._post = post
        self._clock = clock
        self._sleep = sleep
        self.stats = stats if stats is not None else XFER_STATS
        self.prefill = prefill   # counts helix_xfer_prefill_handoffs

    def _headers(self) -> dict:
        return (
            {"X-Runner-Token": self.runner_token}
            if self.runner_token else {}
        )

    def _ship_headers(self, wire: dict) -> dict:
        """Import-POST headers: the runner token PLUS the request's
        trace id (ISSUE 18 bugfix).  Without the header the importing
        peer adopted nothing and minted a fresh id, so the handoff leg
        vanished from the caller's federated timeline.  Only a
        well-shaped id is forwarded — never fabricated."""
        from helix_tpu.obs.trace import TRACE_HEADER, is_trace_id

        h = self._headers()
        tid = wire.get("trace_id")
        if is_trace_id(tid):
            h[TRACE_HEADER] = tid
        return h

    def targets(self) -> list:
        if self._targets is not None:
            return self._targets
        import requests

        r = requests.get(
            f"{self.control_plane_url}/api/v1/runners/"
            f"{self.runner_id}/migration-targets",
            headers=self._headers(), timeout=min(self.timeout, 10.0),
        )
        r.raise_for_status()
        self._targets = [
            t for t in r.json().get("targets", [])
            if t.get("address")
        ]
        return self._targets

    def _post_fn(self):
        if self._post is not None:
            return self._post
        import requests

        return requests.post

    def __call__(self, wire: dict) -> str:
        from helix_tpu.testing import faults

        post = self._post_fn()
        model = wire.get("model", "")
        cfg = self.config
        deadline = self._clock() + cfg.deadline
        last_err = "no migration target"
        candidates = [
            t for t in self.targets()
            if not model or model in (t.get("models") or [model])
        ]
        if not candidates:
            raise RuntimeError(f"snapshot ship failed: {last_err}")
        for attempt in range(cfg.max_attempts):
            for t in candidates:
                peer_id = t.get("id", t.get("address", ""))
                remaining = deadline - self._clock()
                if remaining <= 0:
                    self.stats.note_deadline()
                    raise RuntimeError(
                        f"snapshot ship failed: transfer deadline "
                        f"({cfg.deadline:.1f}s) exceeded; last error: "
                        f"{last_err}"
                    )
                body = wire
                inj = faults.active()
                fault = inj.transfer_fault(peer_id) if inj else None
                if fault is not None:
                    if fault["mode"] == "slow":
                        self._sleep(fault["delay"])
                    elif fault["mode"] == "corrupt":
                        body = _flip_wire_page(wire, fault["page"])
                    elif fault["mode"] == "partial":
                        pages = list(wire.get("pages") or [])
                        body = {**wire, "pages": pages[: len(pages) // 2]}
                    else:   # drop: the peer is unreachable
                        self.stats.note_attempt("unreachable")
                        last_err = f"{peer_id}: injected transfer drop"
                        continue
                t0 = self._clock()
                try:
                    r = post(
                        f"{t['address'].rstrip('/')}/v1/migrate/import",
                        json=body, headers=self._ship_headers(wire),
                        timeout=min(cfg.attempt_timeout, remaining),
                    )
                except Exception as e:  # noqa: BLE001 — try the next peer
                    dt = self._clock() - t0
                    outcome = (
                        "timeout"
                        if "timeout" in type(e).__name__.lower()
                        or "timed out" in str(e).lower()
                        else "unreachable"
                    )
                    self.stats.note_attempt(outcome, dt)
                    last_err = f"{peer_id}: {e}"
                    continue
                dt = self._clock() - t0
                if r.status_code == 200:
                    self.stats.note_attempt("ok", dt)
                    self.stats.note_shipped(body, prefill=self.prefill)
                    return peer_id
                outcome = (
                    "rejected" if 400 <= r.status_code < 500
                    else "http_error"
                )
                self.stats.note_attempt(outcome, dt)
                last_err = f"{peer_id}: HTTP {r.status_code}"
            if attempt + 1 >= cfg.max_attempts:
                break
            backoff = min(
                cfg.backoff_cap, cfg.backoff_base * (2 ** attempt)
            )
            remaining = deadline - self._clock()
            if remaining <= 0:
                self.stats.note_deadline()
                raise RuntimeError(
                    f"snapshot ship failed: transfer deadline "
                    f"({cfg.deadline:.1f}s) exceeded; last error: "
                    f"{last_err}"
                )
            self._sleep(min(backoff, remaining))
        raise RuntimeError(f"snapshot ship failed: {last_err}")


# ---------------------------------------------------------------------------
# SSE plumbing (control-plane mid-stream failover)
# ---------------------------------------------------------------------------


class SSEParser:
    """Incremental server-sent-events parser: feed raw bytes, get the
    ``data:`` payload strings of every complete event (``[DONE]``
    included verbatim).  Partial events stay buffered."""

    def __init__(self):
        self._buf = b""

    def feed(self, chunk: bytes) -> list:
        self._buf += chunk
        out = []
        while True:
            # events are \n\n-terminated; tolerate \r\n line endings
            idx = self._buf.find(b"\n\n")
            if idx < 0:
                break
            raw, self._buf = self._buf[:idx], self._buf[idx + 2:]
            for line in raw.split(b"\n"):
                line = line.strip(b"\r")
                if line.startswith(b"data:"):
                    out.append(line[5:].strip().decode(
                        "utf-8", "replace"
                    ))
        return out


def sse_frame(payload) -> bytes:
    """One SSE data frame (payload = dict to JSON-encode, or a
    preformatted string such as ``[DONE]``)."""
    if not isinstance(payload, str):
        payload = json.dumps(payload)
    return f"data: {payload}\n\n".encode()


def chunk_delta_text(doc: dict) -> str:
    """Generated text carried by one OpenAI stream chunk (chat
    ``delta.content`` or legacy-completions ``text``)."""
    try:
        choice = (doc.get("choices") or [{}])[0]
    except (TypeError, IndexError):
        return ""
    if "delta" in choice:
        return str((choice.get("delta") or {}).get("content") or "")
    return str(choice.get("text") or "")


def chunk_finish_reason(doc: dict) -> Optional[str]:
    try:
        choice = (doc.get("choices") or [{}])[0]
    except (TypeError, IndexError):
        return None
    fr = choice.get("finish_reason")
    return str(fr) if fr else None


def make_chunk(template: dict, kind: str, delta_text: str,
               finish_reason: Optional[str],
               first: bool = False) -> dict:
    """Re-materialise a stream chunk in the CLIENT's original frame
    shape from a neutral (resume) or foreign (replay) delta.
    ``template`` carries the id/model/created the client has been
    seeing, captured from the frames forwarded before the death."""
    if kind == "chat":
        delta: dict = {}
        if first:
            delta["role"] = "assistant"
        if delta_text:
            delta["content"] = delta_text
        return {
            "id": template.get("id", ""),
            "object": "chat.completion.chunk",
            "created": template.get("created", 0),
            "model": template.get("model", ""),
            "choices": [
                {
                    "index": 0,
                    "delta": delta,
                    "finish_reason": finish_reason,
                }
            ],
        }
    return {
        "id": template.get("id", ""),
        "object": "text_completion",
        "created": template.get("created", 0),
        "model": template.get("model", ""),
        "choices": [
            {
                "index": 0,
                "text": delta_text,
                "finish_reason": finish_reason,
            }
        ],
    }


class ElisionTracker:
    """Exactly-once accounting for a failed-over stream: how many
    characters of generated text the CLIENT has already received, and
    the elision of a replayed stream's duplicate head against it.

    ``note_forwarded`` counts what went to the client; after a death,
    ``elide`` is fed the replacement stream's deltas and returns only
    the not-yet-delivered suffix (deterministic generation — greedy or
    seeded — makes the replayed prefix byte-identical, so character
    arithmetic is exact)."""

    def __init__(self):
        self.forwarded_chars = 0
        self._replay_seen = 0

    def note_forwarded(self, text: str) -> None:
        self.forwarded_chars += len(text)

    def start_replay(self) -> None:
        self._replay_seen = 0

    def elide(self, text: str) -> str:
        """The portion of a replayed delta the client has NOT seen."""
        if not text:
            return ""
        start = self._replay_seen
        self._replay_seen += len(text)
        skip = self.forwarded_chars - start
        if skip <= 0:
            return text
        if skip >= len(text):
            return ""
        return text[skip:]


MigrationExporter = Callable[[dict], str]
